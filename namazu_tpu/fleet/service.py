"""PlacementService: pool-level leases over M orchestrator hosts.

The fleet-of-fleets control plane (doc/tenancy.md "Fleet of fleets").
One service owns a pool of orchestrator hosts — each already serving
the tenancy lease/renew/release wire (tenancy/registry.py) — and hands
out POOL leases that it places onto a concrete host:

* **placement** — capacity-aware (fleet/placement.py): each monitor
  tick snapshots every host's federated ``/fleet`` document and scores
  hosts by serving rate, parked depth, slot occupancy, and SLO burn;
  a run's re-lease prefers the host that last served it (journal
  affinity);
* **migration** — ``drain`` (graceful: the old host's lease is
  *reclaimed*, parking its events in the run's journal) and host
  *death* (abrupt: snapshot fetches fail past the dead-after window)
  both re-place the host's leases elsewhere; the replacement host's
  ``lease`` with the same run name + journal dir recovers the parked
  events exactly-once (tenancy/host.py ``_recover_ns_journal``);
* **admission** — new pool leases are refused while the pool's worst
  SLO burn is >= the admission threshold or no eligible host has a
  free slot: the refusal is the 429 + Retry-After contract the
  tenancy client's bounded retry honors (``fleet.admission.refuse``
  is the chaos seam that forces it deterministically);
* **one surface** — the service speaks the tenancy op grammar
  (``lease``/``renew``/``release``/``reclaim``/``runs``) over the
  framed wire, so an unmodified :class:`TenancyClient` — and therefore
  ``nmz-tpu campaign --serve`` — can point at the pool instead of a
  single host; pool ops (``pool_status``/``drain``/``hosts``) ride the
  same wire for ``nmz-tpu fleet status``/``drain`` and
  ``tools top --pool``.

Lease replies carry ``host``/``host_url`` — the assigned host's
workload URL — and renew replies repeat them, so a campaign notices a
migration on its next renew and re-targets its transceivers.

Pool state (``<state_dir>/fleet.json`` + ``leases/<id>.json`` +
``journals/<run>/``) is persisted for ``tools fsck``: a SIGKILLed
service leaves reconcilable records, never mystery files. The pool
assumes its hosts share the state dir's filesystem (the local-pool /
shared-storage deployment this repo targets); a cross-host pool would
move journal recovery onto a blob store — out of scope here.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
import uuid as _uuid
from typing import Any, Dict, List, Optional

from namazu_tpu import chaos, obs
from namazu_tpu.endpoint.framed import FramedServer
from namazu_tpu.fleet import placement
from namazu_tpu.tenancy.client import TenancyClient, TenancyWireError
from namazu_tpu.tenancy.registry import TenancyError, _clamp_ttl
from namazu_tpu.utils.atomic import atomic_write_json
from namazu_tpu.utils.log import get_logger

log = get_logger("fleet")

MANIFEST_NAME = "fleet.json"
MANIFEST_SCHEMA = "nmz-fleet-v1"
LEASES_DIR = "leases"
JOURNALS_DIR = "journals"

#: wire ops that may block on a host round trip — parked per-connection
#: by the framed server instead of wedging its worker pool
BLOCKING_OPS = frozenset({"lease", "release", "reclaim", "drain"})

#: default Retry-After (seconds) on an admission refusal
DEFAULT_RETRY_AFTER_S = 0.5


class HostState:
    __slots__ = ("name", "url", "client", "state", "fails", "last_ok",
                 "summary")

    def __init__(self, name: str, url: str,
                 timeout: float = 5.0) -> None:
        self.name = name
        self.url = url
        self.client = TenancyClient(url, timeout=timeout)
        #: "live" | "draining" | "dead"
        self.state = "live"
        self.fails = 0
        self.last_ok = time.monotonic()
        self.summary = placement.summarize_fleet_doc(None)


class PoolLease:
    __slots__ = ("lease_id", "run", "policy", "policy_param", "ttl_s",
                 "collect_trace", "journal_dir", "host",
                 "host_lease_id", "run_id", "expires_at", "migrations",
                 "state")

    def __init__(self, run: str, ttl_s: float, policy: str,
                 policy_param: Optional[dict], collect_trace: bool,
                 journal_dir: str) -> None:
        self.lease_id = _uuid.uuid4().hex
        self.run = run
        self.policy = policy
        self.policy_param = dict(policy_param) if policy_param else None
        self.ttl_s = ttl_s
        self.collect_trace = collect_trace
        self.journal_dir = journal_dir
        self.host = ""            # "" while pending
        self.host_lease_id = ""
        self.run_id = ""
        self.expires_at = time.monotonic() + ttl_s
        self.migrations = 0
        #: "placed" | "pending" (no eligible host yet; retried per tick)
        self.state = "pending"


def _journal_slug(run: str) -> str:
    """A filesystem-safe, collision-free directory name for one run's
    pool journal (run names are namespace-validated, not path-
    validated)."""
    safe = "".join(c if c.isalnum() or c in "-_." else "_"
                   for c in run)[:48]
    digest = hashlib.sha1(run.encode()).hexdigest()[:8]
    return f"{safe}-{digest}"


class PlacementService:
    """One pool of orchestrator hosts behind one lease surface."""

    def __init__(self, state_dir: str,
                 default_ttl_s: float = 15.0,
                 max_runs_per_host: int = 8,
                 admission_burn_max: float = 1.0,
                 monitor_interval_s: float = 0.5,
                 dead_after_s: float = 3.0,
                 retry_after_s: float = DEFAULT_RETRY_AFTER_S,
                 host_timeout_s: float = 5.0) -> None:
        self.state_dir = os.path.abspath(state_dir)
        self.default_ttl_s = default_ttl_s
        self.max_runs_per_host = max(0, int(max_runs_per_host))
        self.admission_burn_max = float(admission_burn_max)
        self.monitor_interval_s = max(0.05, float(monitor_interval_s))
        self.dead_after_s = max(0.2, float(dead_after_s))
        self.retry_after_s = max(0.0, float(retry_after_s))
        self._host_timeout_s = host_timeout_s
        # ONE lock over hosts/leases, held across the host round trips
        # of a grant or migration: serializing placement is exactly the
        # double-grant guard (a drained host's lease racing its
        # replacement resolves to one winner), and the control plane's
        # op rate is campaign lifecycles, not events
        self._lock = threading.RLock()
        self._hosts: Dict[str, HostState] = {}
        self._leases: Dict[str, PoolLease] = {}
        self._by_run: Dict[str, PoolLease] = {}
        #: run -> host name that last served it (journal affinity)
        self._affinity: Dict[str, str] = {}
        self._counters: Dict[str, int] = {}
        self._servers: List[FramedServer] = []
        self._monitor_stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self.serve_urls: List[str] = []

    # -- pool membership --------------------------------------------------

    def add_host(self, url: str, name: str = "") -> str:
        """Register one orchestrator host (``name=url`` spec or bare
        url; the name defaults to ``hostN``)."""
        if not name and "=" in url.split("://", 1)[0]:
            name, url = url.split("=", 1)
        with self._lock:
            if not name:
                name = f"host{len(self._hosts)}"
            if name in self._hosts:
                raise ValueError(f"duplicate host name {name!r}")
            self._hosts[name] = HostState(name, url,
                                          timeout=self._host_timeout_s)
        return name

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        os.makedirs(os.path.join(self.state_dir, LEASES_DIR),
                    exist_ok=True)
        os.makedirs(os.path.join(self.state_dir, JOURNALS_DIR),
                    exist_ok=True)
        self._write_manifest()
        self.refresh_hosts()
        t = threading.Thread(target=self._monitor_loop,
                             name="fleet-monitor", daemon=True)
        t.start()
        self._monitor = t

    def serve_unix(self, path: str) -> None:
        srv = FramedServer(self.handle_wire, name="fleet",
                           blocking_ops=BLOCKING_OPS)
        srv.bind_unix(path)
        srv.start()
        self._servers.append(srv)
        self.serve_urls.append(f"uds://{path}")
        self._write_manifest()

    def serve_tcp(self, host: str, port: int) -> int:
        srv = FramedServer(self.handle_wire, name="fleet",
                           blocking_ops=BLOCKING_OPS)
        bound = srv.bind_tcp(host, port)
        srv.start()
        self._servers.append(srv)
        self.serve_urls.append(f"tcp://{host}:{bound}")
        self._write_manifest()
        return bound

    def shutdown(self) -> None:
        self._monitor_stop.set()
        for srv in self._servers:
            srv.shutdown()
        self._servers = []
        if self._monitor is not None:
            self._monitor.join(timeout=5)
            self._monitor = None
        with self._lock:
            for host in self._hosts.values():
                host.client.close()

    # -- persistence (tools fsck reads these) -----------------------------

    def _write_manifest(self) -> None:
        with self._lock:
            hosts = {h.name: h.url for h in self._hosts.values()}
        atomic_write_json(
            os.path.join(self.state_dir, MANIFEST_NAME),
            {"schema": MANIFEST_SCHEMA, "pid": os.getpid(),
             "serve_urls": list(self.serve_urls), "hosts": hosts,
             "updated_at": time.time()}, indent=2, sort_keys=True)

    def _lease_record_path(self, lease_id: str) -> str:
        return os.path.join(self.state_dir, LEASES_DIR,
                            f"{lease_id}.json")

    def _persist_lease(self, lease: PoolLease) -> None:
        with self._lock:
            host = self._hosts.get(lease.host)
            doc = {
                "lease_id": lease.lease_id, "run": lease.run,
                "host": lease.host,
                "host_url": host.url if host is not None else "",
                "journal_dir": lease.journal_dir,
                "policy": lease.policy,
                "policy_param": lease.policy_param,
                "ttl_s": lease.ttl_s, "state": lease.state,
                "migrations": lease.migrations,
                # walltime expiry so an offline fsck can age records
                # without this process's monotonic clock
                "expires_wall": time.time() + max(
                    0.0, lease.expires_at - time.monotonic()),
            }
        atomic_write_json(self._lease_record_path(lease.lease_id), doc,
                          indent=2, sort_keys=True)

    def _drop_lease_record(self, lease_id: str) -> None:
        try:
            os.unlink(self._lease_record_path(lease_id))
        except OSError:
            pass

    # -- monitor ----------------------------------------------------------

    def _monitor_loop(self) -> None:
        while not self._monitor_stop.wait(self.monitor_interval_s):
            try:
                self.refresh_hosts()
                self.place_pending()
                self.sweep()
            except Exception:  # pragma: no cover - defensive
                log.exception("fleet monitor tick failed")

    def refresh_hosts(self) -> None:
        """Snapshot every host's ``/fleet`` doc; declare hosts dead
        past the silence window and migrate their leases."""
        from namazu_tpu.obs import federation

        with self._lock:
            hosts = list(self._hosts.values())
        died: List[HostState] = []
        now = time.monotonic()
        for host in hosts:
            try:
                doc = federation.fetch(host.url, "fleet")
            except Exception:
                host.fails += 1
                if (host.state == "live"
                        and now - host.last_ok >= self.dead_after_s):
                    host.state = "dead"
                    died.append(host)
                continue
            host.summary = placement.summarize_fleet_doc(doc)
            host.fails = 0
            host.last_ok = time.monotonic()
            if host.state == "dead":
                # a host back from the dead rejoins as a placement
                # target; its old leases were already migrated away
                log.warning("host %s is reachable again; rejoining the "
                            "pool", host.name)
                host.state = "live"
        for host in died:
            log.warning("host %s silent for %.1fs; declaring it dead "
                        "and re-placing its leases", host.name,
                        now - host.last_ok)
            self._migrate_host_leases(host.name, reason="death")
        self._refresh_gauges()

    def _refresh_gauges(self) -> None:
        with self._lock:
            hosts = len(self._hosts)
            dead = sum(1 for h in self._hosts.values()
                       if h.state == "dead")
            leases = len(self._leases)
            pending = sum(1 for l in self._leases.values()
                          if l.state == "pending")
        obs.fleet_pool_stats(hosts, dead, leases, pending)

    # -- placement --------------------------------------------------------

    def _candidates(self, exclude: str = "") -> List[Dict[str, Any]]:
        with self._lock:
            per_host: Dict[str, int] = {}
            for lease in self._leases.values():
                if lease.host:
                    per_host[lease.host] = per_host.get(lease.host,
                                                        0) + 1
            return [{
                "name": h.name, "summary": h.summary,
                "leased_runs": per_host.get(h.name, 0),
                "eligible": h.state == "live" and h.name != exclude,
            } for h in self._hosts.values()]

    def _choose_host(self, run: str,
                     exclude: str = "") -> Optional[HostState]:
        name = placement.choose_host(
            self._candidates(exclude=exclude),
            affinity_host=self._affinity.get(run, ""),
            max_runs_per_host=self.max_runs_per_host)
        if name is None:
            return None
        with self._lock:
            return self._hosts.get(name)

    def _admission_refusal(self) -> Optional[Dict[str, Any]]:
        """The admission gate for NEW leases (never migrations — an
        overloaded pool still re-places a dead host's existing
        tenants). Returns the refusal doc, or None to admit."""
        fault = chaos.decide("fleet.admission.refuse")
        if fault is not None:
            obs.fleet_admission_rejected("chaos")
            self._count("admission_rejections")
            return {"ok": False,
                    "error": "pool admission refused (chaos)",
                    "status": int(fault.get("status", 429)),
                    "retry_after": float(fault.get("retry_after",
                                                   self.retry_after_s))}
        with self._lock:
            summaries = [h.summary for h in self._hosts.values()
                         if h.state == "live"]
        burn = placement.pool_burn(summaries)
        if burn >= self.admission_burn_max:
            obs.fleet_admission_rejected("slo_burn")
            self._count("admission_rejections")
            return {"ok": False,
                    "error": f"pool SLO burn {burn:.2f} >= "
                             f"{self.admission_burn_max:g}; not "
                             "admitting new runs",
                    "status": 429,
                    "retry_after": self.retry_after_s}
        return None

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    # -- wire ops ---------------------------------------------------------

    def handle_wire(self, req: Dict[str, Any]) -> Dict[str, Any]:
        op = req.get("op")
        try:
            if op == "lease":
                return self.lease_op(req)
            if op == "renew":
                return self.renew_op(req)
            if op == "release":
                return self.release_op(req)
            if op == "reclaim":
                return self.reclaim_op(req)
            if op == "runs":
                return {"ok": True, "runs": self.runs_payload()}
            if op == "pool_status":
                return {"ok": True, "pool": self.pool_payload()}
            if op == "drain":
                return self.drain_op(req)
            if op == "hosts":
                with self._lock:
                    return {"ok": True,
                            "hosts": {h.name: h.url
                                      for h in self._hosts.values()}}
        except TenancyWireError as e:
            return {"ok": False, "error": f"host op failed: {e}"}
        except (TenancyError, ValueError) as e:
            return {"ok": False, "error": str(e)}
        return {"ok": False, "error": f"unknown pool op {op!r}"}

    def lease_op(self, req: Dict[str, Any]) -> Dict[str, Any]:
        from namazu_tpu import tenancy

        run = tenancy.validate_ns(req.get("run") or "")
        ttl = _clamp_ttl(req.get("ttl_s"), default=self.default_ttl_s)
        refusal = self._admission_refusal()
        if refusal is not None:
            log.warning("admission refused lease for run %s: %s", run,
                        refusal["error"])
            return refusal
        with self._lock:
            if run in self._by_run:
                return {"ok": False,
                        "error": f"run {run!r} is already pool-leased"}
            host = self._choose_host(run)
            if host is None:
                obs.fleet_admission_rejected("capacity")
                self._count("admission_rejections")
                return {"ok": False,
                        "error": "no eligible host has a free slot",
                        "status": 429,
                        "retry_after": self.retry_after_s}
            lease = PoolLease(
                run=run, ttl_s=ttl,
                policy=str(req.get("policy") or "random"),
                policy_param=(req.get("policy_param")
                              if isinstance(req.get("policy_param"),
                                            dict) else None),
                collect_trace=bool(req.get("collect_trace", True)),
                journal_dir=os.path.join(self.state_dir, JOURNALS_DIR,
                                         _journal_slug(run)))
            doc = self._grant_on_host(lease, host)
            self._leases[lease.lease_id] = lease
            self._by_run[run] = lease
            self._affinity[run] = host.name
        self._persist_lease(lease)
        self._refresh_gauges()
        log.info("pool-leased run %s onto %s (ttl %.1fs%s)", run,
                 host.name, ttl,
                 f", recovered {doc.get('recovered')}"
                 if doc.get("recovered") else "")
        return {"ok": True, "lease_id": lease.lease_id, "run": run,
                "run_id": lease.run_id, "ttl_s": ttl,
                "recovered": doc.get("recovered", 0),
                "host": host.name, "host_url": host.url}

    def _grant_on_host(self, lease: PoolLease,
                       host: HostState) -> Dict[str, Any]:
        """Grant ``lease`` on ``host`` over the per-host tenancy wire;
        updates the lease's placement fields. Raises TenancyWireError
        upward (the caller answers ``ok: false``)."""
        doc = host.client.lease(
            lease.run, ttl_s=lease.ttl_s, policy=lease.policy,
            policy_param=lease.policy_param,
            journal_dir=lease.journal_dir,
            collect_trace=lease.collect_trace)
        lease.host = host.name
        lease.host_lease_id = doc.get("lease_id", "")
        lease.run_id = doc.get("run_id", "")
        lease.state = "placed"
        lease.expires_at = time.monotonic() + lease.ttl_s
        return doc

    def renew_op(self, req: Dict[str, Any]) -> Dict[str, Any]:
        lease_id = str(req.get("lease_id") or "")
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None:
                return {"ok": False,
                        "error": f"unknown pool lease {lease_id!r} "
                                 "(expired and reclaimed?)"}
            lease.ttl_s = _clamp_ttl(req.get("ttl_s"),
                                     default=lease.ttl_s)
            lease.expires_at = time.monotonic() + lease.ttl_s
            host = self._hosts.get(lease.host)
            if lease.state == "placed" and host is not None \
                    and host.state != "dead":
                try:
                    host.client.renew(lease.host_lease_id,
                                      ttl_s=lease.ttl_s)
                except TenancyWireError as e:
                    # the host forgot the lease (restart, expiry while
                    # we were partitioned): re-place it now — the
                    # journal recovers whatever was parked
                    log.warning("host %s lost lease for run %s (%s); "
                                "re-placing", lease.host, lease.run, e)
                    self._migrate_lease(lease, reason="death",
                                        reclaim_old=False)
                    host = self._hosts.get(lease.host)
            return {"ok": True, "lease_id": lease_id, "run": lease.run,
                    "ttl_s": lease.ttl_s,
                    "migrations": lease.migrations,
                    "state": lease.state, "host": lease.host,
                    "host_url": host.url if host is not None else ""}

    def release_op(self, req: Dict[str, Any]) -> Dict[str, Any]:
        lease_id = str(req.get("lease_id") or "")
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None:
                return {"ok": False,
                        "error": f"unknown pool lease {lease_id!r} "
                                 "(expired and reclaimed?)"}
            host = self._hosts.get(lease.host)
            if lease.state != "placed" or host is None:
                return {"ok": False,
                        "error": f"run {lease.run} is not placed "
                                 "(pending re-placement); retry",
                        "status": 429,
                        "retry_after": self.retry_after_s}
            doc = host.client.release(
                lease.host_lease_id,
                want_trace=bool(req.get("trace", True)))
            self._forget_lease(lease)
        self._drop_lease_record(lease_id)
        self._sweep_released_journal(lease)
        self._refresh_gauges()
        log.info("pool-released run %s from %s", lease.run, lease.host)
        return dict(doc, ok=True, host=lease.host)

    def _sweep_released_journal(self, lease: PoolLease) -> None:
        """A clean release removed the journal FILE (the run
        completed); remove the now-empty per-run journal dir too, so
        the pool state dir fscks clean without repair. Never touches a
        journal with unreleased events — reclaim/migration keep theirs."""
        import shutil

        try:
            from namazu_tpu.chaos.journal import EventJournal

            if lease.journal_dir \
                    and not EventJournal(lease.journal_dir).unreleased():
                shutil.rmtree(lease.journal_dir, ignore_errors=True)
        except Exception:
            pass  # an unreadable journal is fsck's business, not ours

    def reclaim_op(self, req: Dict[str, Any]) -> Dict[str, Any]:
        lease_id = str(req.get("lease_id") or "")
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None:
                return {"ok": False,
                        "error": f"unknown pool lease {lease_id!r} "
                                 "(expired and reclaimed?)"}
            host = self._hosts.get(lease.host)
            doc: Dict[str, Any] = {"run": lease.run}
            if lease.state == "placed" and host is not None \
                    and host.state != "dead":
                doc = host.client.reclaim(lease.host_lease_id)
            self._forget_lease(lease)
        self._drop_lease_record(lease_id)
        self._refresh_gauges()
        return dict(doc, ok=True, host=lease.host)

    def _forget_lease(self, lease: PoolLease) -> None:
        self._leases.pop(lease.lease_id, None)
        if self._by_run.get(lease.run) is lease:
            self._by_run.pop(lease.run, None)

    def drain_op(self, req: Dict[str, Any]) -> Dict[str, Any]:
        name = str(req.get("host") or "")
        with self._lock:
            host = self._hosts.get(name)
            if host is None:
                return {"ok": False, "error": f"unknown host {name!r}"}
            if host.state == "dead":
                return {"ok": False,
                        "error": f"host {name} is dead (its leases "
                                 "were already re-placed)"}
            host.state = "draining"
        moved = self._migrate_host_leases(name, reason="drain")
        log.info("drained host %s: %d lease(s) re-placed", name, moved)
        return {"ok": True, "host": name, "migrated": moved}

    # -- migration --------------------------------------------------------

    def _migrate_host_leases(self, host_name: str, reason: str) -> int:
        with self._lock:
            mine = [l for l in self._leases.values()
                    if l.host == host_name and l.state == "placed"]
            moved = 0
            for lease in mine:
                self._migrate_lease(lease, reason=reason,
                                    reclaim_old=(reason == "drain"))
                moved += 1
        self._refresh_gauges()
        return moved

    def _migrate_lease(self, lease: PoolLease, reason: str,
                       reclaim_old: bool) -> None:
        """Move one lease off its current host. Graceful moves reclaim
        the old host's lease first (parking its events in the run's
        journal); abrupt moves skip that — a dead host already left
        the journal as its last word. Either way the replacement
        host's grant with the same run + journal dir is the
        exactly-once recovery step. Caller holds the service lock."""
        old_host = self._hosts.get(lease.host)
        if reclaim_old and old_host is not None \
                and lease.host_lease_id:
            try:
                old_host.client.reclaim(lease.host_lease_id)
            except TenancyWireError as e:
                log.warning("reclaiming run %s on %s failed (%s); its "
                            "lease will expire server-side", lease.run,
                            lease.host, e)
        exclude = lease.host
        lease.host = ""
        lease.host_lease_id = ""
        lease.state = "pending"
        replacement = self._choose_host(lease.run, exclude=exclude)
        if replacement is None:
            log.warning("no eligible host for run %s after %s of %s; "
                        "left pending", lease.run, reason, exclude)
            self._persist_lease(lease)
            return
        try:
            doc = self._grant_on_host(lease, replacement)
        except TenancyWireError as e:
            log.warning("re-placing run %s onto %s failed (%s); left "
                        "pending", lease.run, replacement.name, e)
            self._persist_lease(lease)
            return
        lease.migrations += 1
        self._affinity[lease.run] = replacement.name
        self._count(f"migrations_{reason}")
        obs.fleet_migration(reason)
        self._persist_lease(lease)
        log.warning("migrated run %s: %s -> %s (%s, recovered %s "
                    "parked event(s))", lease.run, exclude,
                    replacement.name, reason, doc.get("recovered", 0))

    def place_pending(self) -> int:
        """Retry placement of pending leases (no-eligible-host at
        migration time); returns how many landed."""
        placed = 0
        with self._lock:
            pending = [l for l in self._leases.values()
                       if l.state == "pending"]
            for lease in pending:
                host = self._choose_host(lease.run)
                if host is None:
                    continue
                try:
                    self._grant_on_host(lease, host)
                except TenancyWireError as e:
                    log.warning("placing pending run %s onto %s failed "
                                "(%s)", lease.run, host.name, e)
                    continue
                lease.migrations += 1
                self._affinity[lease.run] = host.name
                self._count("migrations_death")
                obs.fleet_migration("death")
                self._persist_lease(lease)
                placed += 1
        if placed:
            self._refresh_gauges()
        return placed

    def sweep(self, now: Optional[float] = None) -> int:
        """Expire pool leases whose tenant stopped renewing (one full
        TTL past expiry — the per-host lease has its own TTL and
        reclaims first; this sweep just stops the pool record from
        outliving everyone). Journals are kept, records dropped."""
        now = time.monotonic() if now is None else now
        due: List[PoolLease] = []
        with self._lock:
            for lease in list(self._leases.values()):
                if now - lease.expires_at >= lease.ttl_s:
                    self._forget_lease(lease)
                    due.append(lease)
        for lease in due:
            self._drop_lease_record(lease.lease_id)
            log.warning("pool lease for run %s expired (tenant dead?); "
                        "record dropped, journal kept in %s", lease.run,
                        lease.journal_dir)
        if due:
            self._refresh_gauges()
        return len(due)

    # -- status payloads --------------------------------------------------

    def runs_payload(self) -> List[Dict[str, Any]]:
        now = time.monotonic()
        with self._lock:
            return [{
                "run": l.run, "run_id": l.run_id,
                "lease_id": l.lease_id, "ttl_s": l.ttl_s,
                "expires_in_s": round(l.expires_at - now, 3),
                "host": l.host, "state": l.state,
                "migrations": l.migrations,
            } for l in self._leases.values()]

    def pool_payload(self) -> Dict[str, Any]:
        """The one-surface document ``fleet status`` and ``tools top
        --pool`` render: every host with its load summary and state,
        every pool lease with its placement, and the service's
        migration/admission counters."""
        now = time.monotonic()
        with self._lock:
            hosts = [{
                "name": h.name, "url": h.url, "state": h.state,
                "fails": h.fails,
                "last_ok_age_s": round(now - h.last_ok, 3),
                "summary": dict(h.summary),
            } for h in self._hosts.values()]
            counters = dict(self._counters)
        return {"schema": "nmz-pool-v1", "state_dir": self.state_dir,
                "hosts": hosts, "leases": self.runs_payload(),
                "counters": counters}
