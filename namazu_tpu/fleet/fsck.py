"""Offline integrity checks over a placement service's state dir.

``tools fsck <state_dir>`` lands here when the dir holds a
``fleet.json`` manifest (fleet/service.py writes one at start). The
durable pool state is reconcilable by construction — a SIGKILLed
service leaves lease records under ``leases/`` and per-run journals
under ``journals/`` — and this module classifies what it finds:

* **stale lease records** — leases whose walltime expiry passed more
  than one TTL ago (the tenant stopped renewing and the per-host lease
  reclaimed long since), or, when the live service is reachable
  (``service_url``), records its view no longer contains;
* **orphan journals** — journal dirs no lease record references whose
  journal holds NO unreleased events: nothing left to recover, safe to
  sweep;
* **recoverable journals** — unreferenced journal dirs that DO hold
  unreleased events. Never swept (they are the only durable copy of a
  dead tenant's parked events); reported so an operator can re-lease
  the run over them or archive them deliberately.

``repair=True`` unlinks the stale records and sweeps the orphan
journal dirs; recoverable journals and live leases are never touched.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional, Set

from namazu_tpu.fleet.service import (
    JOURNALS_DIR,
    LEASES_DIR,
    MANIFEST_NAME,
)
from namazu_tpu.utils.log import get_logger

log = get_logger("fleet.fsck")


def looks_like_fleet_dir(path: str) -> bool:
    """A placement-service state dir carries the fleet manifest."""
    return os.path.isfile(os.path.join(path, MANIFEST_NAME))


def _service_lease_ids(service_url: str) -> Optional[Set[str]]:
    """The live service's lease ids, or None when unreachable (fsck
    then falls back to walltime aging)."""
    if not service_url:
        return None
    from namazu_tpu.fleet.client import FleetClient

    client = FleetClient(service_url, timeout=5.0)
    try:
        return {str(r.get("lease_id") or "")
                for r in client.runs().get("runs") or []}
    except Exception as e:
        log.warning("placement service at %s unreachable (%s); "
                    "reconciling by record age instead", service_url, e)
        return None
    finally:
        client.close()


def fsck_pool_state(state_dir: str, repair: bool = False,
                    service_url: str = "",
                    now: Optional[float] = None) -> Dict[str, Any]:
    """One report over a pool state dir; see the module docstring for
    the finding classes. Run against a quiescent dir or pass
    ``service_url`` — without the live view, records still inside
    their TTL grace are simply not stale yet."""
    state_dir = os.path.abspath(state_dir)
    now = time.time() if now is None else now
    report: Dict[str, Any] = {
        "state_dir": state_dir, "manifest_ok": False,
        "lease_records": 0, "live_leases": [],
        "stale_leases": [], "orphan_journals": [],
        "recoverable_journals": [], "unreadable_records": [],
        "repaired": [],
    }
    manifest_path = os.path.join(state_dir, MANIFEST_NAME)
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        report["manifest_ok"] = isinstance(manifest, dict)
        if report["manifest_ok"] and not service_url:
            # the manifest remembers where the service serves; a live
            # one is the authoritative view of which leases exist
            urls = manifest.get("serve_urls") or []
            service_url = str(urls[0]) if urls else ""
    except (OSError, ValueError):
        pass
    live_ids = _service_lease_ids(service_url)

    leases_dir = os.path.join(state_dir, LEASES_DIR)
    referenced_journals: Set[str] = set()
    records: List[str] = []
    if os.path.isdir(leases_dir):
        records = sorted(n for n in os.listdir(leases_dir)
                         if n.endswith(".json"))
    report["lease_records"] = len(records)
    for name in records:
        path = os.path.join(leases_dir, name)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            report["unreadable_records"].append(name)
            if repair:
                try:
                    os.unlink(path)
                    report["repaired"].append(f"record:{name}")
                except OSError:
                    pass
            continue
        lease_id = str(doc.get("lease_id") or name[:-len(".json")])
        ttl = float(doc.get("ttl_s") or 0.0)
        expires = float(doc.get("expires_wall") or 0.0)
        if live_ids is not None:
            stale = lease_id not in live_ids
        else:
            # one full TTL past walltime expiry: the per-host lease
            # reclaimed ages ago and no renewal refreshed the record
            stale = expires > 0 and now - expires > max(ttl, 1.0)
        if stale:
            report["stale_leases"].append(
                {"lease_id": lease_id, "run": str(doc.get("run") or ""),
                 "expired_ago_s": round(max(0.0, now - expires), 1)})
            if repair:
                try:
                    os.unlink(path)
                    report["repaired"].append(f"record:{name}")
                except OSError:
                    pass
        else:
            report["live_leases"].append(lease_id)
            jd = str(doc.get("journal_dir") or "")
            if jd:
                referenced_journals.add(os.path.basename(
                    os.path.normpath(jd)))

    journals_dir = os.path.join(state_dir, JOURNALS_DIR)
    if os.path.isdir(journals_dir):
        from namazu_tpu.chaos.journal import EventJournal

        for name in sorted(os.listdir(journals_dir)):
            path = os.path.join(journals_dir, name)
            if not os.path.isdir(path) or name in referenced_journals:
                continue
            try:
                parked = len(EventJournal(path).unreleased())
            except Exception:
                # an unreadable journal might still hold events; treat
                # as recoverable (never sweep what we can't prove empty)
                parked = -1
            if parked == 0:
                report["orphan_journals"].append(name)
                if repair:
                    try:
                        shutil.rmtree(path)
                        report["repaired"].append(f"journal:{name}")
                    except OSError:
                        pass
            else:
                report["recoverable_journals"].append(
                    {"journal": name, "unreleased": parked})
    return report
