"""Fleet-of-fleets: the crash-tolerant placement plane.

One placement service (service.py) leases campaign slots across a pool
of orchestrator hosts, speaking the same tenancy wire each host already
serves. Capacity-aware scoring lives in placement.py; the pool-control
client in client.py; the CLI surface is ``nmz-tpu fleet serve/status/
drain`` (cli/fleet_cmd.py) and ``tools top --pool``.

See doc/tenancy.md "Fleet of fleets".
"""

from namazu_tpu.fleet.client import FleetClient
from namazu_tpu.fleet.placement import (
    choose_host,
    pool_burn,
    score_host,
    summarize_fleet_doc,
)
from namazu_tpu.fleet.service import (
    JOURNALS_DIR,
    LEASES_DIR,
    MANIFEST_NAME,
    MANIFEST_SCHEMA,
    PlacementService,
)

__all__ = [
    "FleetClient",
    "JOURNALS_DIR",
    "LEASES_DIR",
    "MANIFEST_NAME",
    "MANIFEST_SCHEMA",
    "PlacementService",
    "choose_host",
    "pool_burn",
    "score_host",
    "summarize_fleet_doc",
]
