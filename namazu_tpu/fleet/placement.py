"""Capacity-aware host scoring for the placement plane.

Pure functions over the federated ``/fleet`` documents each
orchestrator host serves (obs/federation.py): the placement service
(fleet/service.py) snapshots every host's doc on its monitor tick and
asks this module two questions — *how loaded is that host* and *which
eligible host should take this run*. Keeping the scoring side-effect
free means tests/test_fleet.py can pin the decision table off synthetic
snapshots, no sockets involved.

Scoring inputs per host (all derived from one ``/fleet`` doc):

* ``events_per_sec`` — summed over the host's fresh producer rows (a
  stale row's rate is history, not load);
* ``parked`` — edge-parked depth plus every tenant namespace's parked
  depth (the backlog a migration would have to recover);
* ``runs`` — distinct leased run namespaces (the slot occupancy the
  ``max_runs_per_host`` cap gates on);
* ``max_burn`` — the worst SLO objective burn rate the host reports
  (>= 1 means the objective is violated over its window).

Selection prefers the least-loaded eligible host, with an affinity
bonus for the host that last served the run — a campaign's retries
land where its journals live, so recovery never crosses hosts unless
the old host is gone.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

#: score bonus for the run's previous host (journal affinity): big
#: enough to win any load tie-break, small enough that a saturated
#: previous host still loses to an idle sibling
AFFINITY_BONUS = 0.25

#: load normalizers: one run's worth of serving traffic. The absolute
#: values only set the scale on which load differences matter; the
#: RANKING is what placement acts on.
RATE_NORM = 10_000.0
PARKED_NORM = 1_000.0


def summarize_fleet_doc(doc: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold one host's ``/fleet`` document into the flat load summary
    the scorer consumes. ``None`` (an unreachable host) summarizes to
    an empty-but-marked doc so callers can treat "no snapshot" and
    "idle host" distinctly."""
    if not isinstance(doc, dict):
        return {"reachable": False, "events_per_sec": 0.0, "parked": 0,
                "runs": 0, "run_names": [], "max_burn": 0.0,
                "stale_instances": 0}
    rate = 0.0
    parked = 0
    run_names: List[str] = []
    seen_runs = set()
    for row in doc.get("instances") or []:
        if not isinstance(row, dict):
            continue
        if row.get("stale"):
            continue
        try:
            rate += float(row.get("events_per_sec") or 0.0)
        except (TypeError, ValueError):
            pass
        try:
            parked += int(row.get("edge_parked") or 0)
        except (TypeError, ValueError):
            pass
        runs = row.get("runs")
        if isinstance(runs, dict):
            for name, stats in runs.items():
                if name not in seen_runs:
                    seen_runs.add(name)
                    run_names.append(name)
                if isinstance(stats, dict):
                    try:
                        parked += int(stats.get("parked") or 0)
                    except (TypeError, ValueError):
                        pass
    max_burn = 0.0
    slo = doc.get("slo")
    if isinstance(slo, dict):
        for obj in slo.get("objectives") or []:
            if not isinstance(obj, dict):
                continue
            try:
                burn = float(obj.get("burn") or 0.0)
            except (TypeError, ValueError):
                continue
            if burn > max_burn:
                max_burn = burn
    try:
        stale = int(doc.get("stale_instances") or 0)
    except (TypeError, ValueError):
        stale = 0
    return {"reachable": True, "events_per_sec": rate, "parked": parked,
            "runs": len(run_names), "run_names": run_names,
            "max_burn": max_burn, "stale_instances": stale}


def score_host(summary: Dict[str, Any], leased_runs: int,
               affinity: bool = False,
               max_runs_per_host: int = 0) -> Optional[float]:
    """One host's placement score (higher = better target), or None
    when the host is ineligible for NEW work: at its run cap, or its
    own SLO burn already >= 1 (placing more load on a violating host
    converts one noisy neighbor into a pool-wide outage).

    ``leased_runs`` is the SERVICE's count of runs it has placed on the
    host — authoritative over the snapshot's view, which lags one
    monitor tick behind the service's own grants."""
    occupancy = max(leased_runs, int(summary.get("runs") or 0))
    if max_runs_per_host > 0 and occupancy >= max_runs_per_host:
        return None
    burn = float(summary.get("max_burn") or 0.0)
    if burn >= 1.0:
        return None
    load = (float(summary.get("events_per_sec") or 0.0) / RATE_NORM
            + int(summary.get("parked") or 0) / PARKED_NORM
            + occupancy)
    score = 1.0 / (1.0 + load) - 0.5 * burn
    if affinity:
        score += AFFINITY_BONUS
    return score


def choose_host(candidates: Iterable[Dict[str, Any]],
                affinity_host: str = "",
                max_runs_per_host: int = 0) -> Optional[str]:
    """Pick the placement target out of ``candidates`` — dicts shaped
    ``{"name", "summary", "leased_runs", "eligible"}`` (the service
    marks draining/dead hosts ineligible before asking). Returns the
    winning host name, or None when no host can take the run (the
    lease goes pending / admission refuses)."""
    best_name: Optional[str] = None
    best_score = float("-inf")
    for cand in candidates:
        if not cand.get("eligible", True):
            continue
        summary = cand.get("summary") or {}
        s = score_host(summary, int(cand.get("leased_runs") or 0),
                       affinity=(cand.get("name") == affinity_host
                                 and bool(affinity_host)),
                       max_runs_per_host=max_runs_per_host)
        if s is None:
            continue
        # deterministic tie-break on name so identical snapshots place
        # identically across service restarts (fsck reconciliation
        # depends on replayable decisions)
        if s > best_score or (s == best_score and best_name is not None
                              and str(cand.get("name")) < best_name):
            best_score = s
            best_name = str(cand.get("name"))
    return best_name


def pool_burn(summaries: Iterable[Dict[str, Any]]) -> float:
    """The pool's admission burn rate: the worst SLO burn any
    reachable host reports. Fleet-max (not mean) on purpose — one
    violating host means the pool is ALREADY failing someone's
    objective, and admission's job is to stop making that worse."""
    worst = 0.0
    for summary in summaries:
        if not summary.get("reachable"):
            continue
        burn = float(summary.get("max_burn") or 0.0)
        if burn > worst:
            worst = burn
    return worst
