"""Client for the placement service's pool ops.

The pool speaks the tenancy op grammar for its lease surface — an
unmodified :class:`~namazu_tpu.tenancy.client.TenancyClient` works for
``lease``/``renew``/``release``/``reclaim``/``runs`` — so this client
only adds the pool-control verbs (``pool_status``/``drain``/``hosts``)
on top, via the raw ``op()`` passthrough. ``nmz-tpu fleet status`` /
``fleet drain`` and ``tools top --pool`` are its callers.
"""

from __future__ import annotations

from typing import Any, Dict

from namazu_tpu.tenancy.client import TenancyClient


class FleetClient(TenancyClient):
    """TenancyClient plus the placement service's pool-control ops."""

    def pool_status(self) -> Dict[str, Any]:
        """The one-surface pool document: hosts with load summaries,
        pool leases with placements, migration/admission counters."""
        return self.op({"op": "pool_status"})["pool"]

    def drain(self, host: str) -> Dict[str, Any]:
        """Gracefully drain one host: its leases are reclaimed (events
        parked to journals) and re-placed onto siblings."""
        return self.op({"op": "drain", "host": host})

    def hosts(self) -> Dict[str, str]:
        """Pool membership: host name -> workload url."""
        return self.op({"op": "hosts"})["hosts"]
