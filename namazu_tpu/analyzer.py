"""Fault-localization analyzer: rank branches by success/failure divergence.

Capability parity with the reference's Java analyzer
(/root/reference/misc/analyzer/java/.../Analyzer.java:17-145), which loads
each experiment run's JaCoCo coverage + result.json and prints
"Suspicious:" branches whose hit counts diverge between successful and
failed runs. Redesign: coverage is a plain JSON mapping
``branch_id -> hit_count`` per run (any tracer can emit it — coverage.py,
a JVM agent, or the C++ agent's hook counters), stored as
``coverage.json`` in the run's working dir or passed explicitly.

The divergence score doubles as a dense search-reward ingredient: branches
that only fire in failing runs point the schedule search toward the bug
(SURVEY.md section 7 "reward sparsity").
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

from namazu_tpu.storage.base import HistoryStorage

Coverage = Dict[str, float]


def load_run_coverage(storage: HistoryStorage, i: int) -> Optional[Coverage]:
    path = os.path.join(storage.run_dir(i), "coverage.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        raw = json.load(f)
    return {str(k): float(v) for k, v in raw.items()}


def divergence_ranking(
    success_covs: Iterable[Coverage],
    failure_covs: Iterable[Coverage],
) -> List[Tuple[str, float, float, float]]:
    """Rank branches by |P(hit | failure) - P(hit | success)|.

    Returns [(branch, divergence, fail_rate, success_rate)] sorted
    descending — the analyzer's "Suspicious" list.
    """
    success_covs = list(success_covs)
    failure_covs = list(failure_covs)
    branches = set()
    for c in success_covs + failure_covs:
        branches.update(c)

    def hit_rate(covs: List[Coverage], b: str) -> float:
        if not covs:
            return 0.0
        return sum(1.0 for c in covs if c.get(b, 0) > 0) / len(covs)

    ranked = []
    for b in branches:
        fr = hit_rate(failure_covs, b)
        sr = hit_rate(success_covs, b)
        ranked.append((b, abs(fr - sr), fr, sr))
    ranked.sort(key=lambda t: (-t[1], t[0]))
    return ranked


def analyze_storage(
    storage: HistoryStorage, top: int = 20
) -> List[Tuple[str, float, float, float]]:
    """Analyze every completed run with recorded coverage."""
    succ, fail = [], []
    for i in range(storage.nr_stored_histories()):
        cov = load_run_coverage(storage, i)
        if cov is None:
            continue
        try:
            ok = storage.is_successful(i)
        except Exception:
            continue
        (succ if ok else fail).append(cov)
    return divergence_ranking(succ, fail)[:top]


def print_report(ranking, min_divergence: float = 0.0) -> None:
    for branch, div, fr, sr in ranking:
        if div < min_divergence:
            continue
        print(f"Suspicious: {branch}  divergence={div:.2f} "
              f"(failure hit-rate {fr:.2f}, success hit-rate {sr:.2f})")
