"""Shared scaffolding for stateful stream parsers (PacketParser impls).

Both semantic parsers (zookeeper, http) are incremental state machines
over per-direction byte streams. This base class owns the mechanics so
they can't drift between protocols:

* parse state keyed by ``(src, dst, conn_id)`` — **per TCP connection**,
  not per link, so concurrent connections on one proxied link never
  interleave bytes into one buffer;
* a lock (pump threads for both directions call concurrently);
* bounded buffering (``MAX_BUFFER``) and desync-to-passthrough: a parse
  error marks only that direction broken ("" hints = no semantic
  identity, traffic still flows);
* keepalive suppression: messages matching ``NOISE_PREFIXES`` are
  dropped from hints, and a chunk that is *pure* keepalive returns
  ``None`` = forward without deferring.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from namazu_tpu.utils.log import get_logger

log = get_logger("inspector.stream_parser")

MAX_BUFFER = 16 * 1024 * 1024


class DirState:
    """Per-(direction, connection) incremental parse state."""

    __slots__ = ("buf", "stage", "broken", "is_request", "skip", "chunked",
                 "mode")

    def __init__(self, is_request: bool) -> None:
        self.buf = bytearray()
        self.stage = "init"
        self.broken = False
        self.is_request = is_request
        # http1-specific fields live here so DirState stays one class
        self.skip = 0
        self.chunked = False
        self.mode = "detect"


class StreamParser:
    """Base PacketParser: subclasses implement ``_step(state)``.

    ``_step`` must consume complete messages from ``state.buf`` and return
    a hint string (or None when it needs more bytes); it is called in a
    loop until it makes no progress. Raise to mark the direction broken.
    """

    #: hint prefixes suppressed when ignore_keepalive is set
    NOISE_PREFIXES: Tuple[str, ...] = ()

    def __init__(self, ignore_keepalive: bool = True):
        self.ignore_keepalive = ignore_keepalive
        self._dirs: Dict[Tuple[str, str, int], DirState] = {}
        self._first_dir: Dict[int, Tuple[str, str]] = {}
        self._lock = threading.Lock()

    def __call__(self, chunk: bytes, src: str, dst: str,
                 conn_id: int = 0) -> Optional[str]:
        with self._lock:
            key = (src, dst, conn_id)
            d = self._dirs.get(key)
            if d is None:
                # the first direction seen on a connection is the side
                # that connected (TCP: the client talks first)
                first = self._first_dir.setdefault(conn_id, (src, dst))
                d = self._dirs[key] = DirState(is_request=first == (src, dst))
            if d.broken:
                return ""
            d.buf.extend(chunk)
            if len(d.buf) > MAX_BUFFER:
                log.warning("%s parser buffer overflow %s->%s; passthrough",
                            type(self).__name__, src, dst)
                d.broken = True
                d.buf.clear()
                return ""
            try:
                msgs = self._drain(d)
            except Exception as e:  # defensive: never kill the pump thread
                log.warning("%s parser desync %s->%s: %s; passthrough",
                            type(self).__name__, src, dst, e)
                d.broken = True
                d.buf.clear()
                return ""
        if not msgs:
            return ""  # incomplete frame: no semantic identity this chunk
        if self.ignore_keepalive:
            noise = self.NOISE_PREFIXES
            msgs = [m for m in msgs if not m.startswith(noise)]
            if not msgs:
                return None  # pure keepalive: forward without deferring
        return ";".join(msgs)

    def _drain(self, d: DirState) -> List[str]:
        msgs: List[str] = []
        while True:
            before = len(d.buf)
            m = self._step(d)
            if m:
                msgs.append(m)
            if len(d.buf) == before:  # no progress: need more bytes
                return msgs

    # -- message-boundary segmentation (the per-message defer path) ------

    def segment(self, chunk: bytes, src: str, dst: str,
                conn_id: int = 0) -> List[Tuple[bytes, Optional[str]]]:
        """Split ``chunk`` at message boundaries: one ``(bytes, hint)``
        entry per complete protocol message, in stream order.

        This is what makes replay hints *timing-independent*: a per-chunk
        hint is the join of whatever messages happened to coalesce in one
        TCP read, so the same logical message gets a different identity
        depending on arrival timing — exactly the instability SURVEY.md
        section 7 warns breaks deterministic replay. Per-message events
        give each message its own stable hint regardless of coalescing.

        ``hint is None`` means forward without deferring (keepalive).
        Bytes of an incomplete trailing frame are HELD in the direction
        buffer until later chunks complete them — the caller forwards
        only what is returned. A broken direction (overflow / desync)
        passes chunks through whole with no identity.
        """
        with self._lock:
            key = (src, dst, conn_id)
            d = self._dirs.get(key)
            if d is None:
                first = self._first_dir.setdefault(conn_id, (src, dst))
                d = self._dirs[key] = DirState(
                    is_request=first == (src, dst))
            if d.broken:
                return [(chunk, "")]
            d.buf.extend(chunk)
            if len(d.buf) > MAX_BUFFER:
                log.warning(
                    "%s parser buffer overflow %s->%s; passthrough",
                    type(self).__name__, src, dst)
                d.broken = True
                held = bytes(d.buf)
                d.buf.clear()
                return [(held, "")]
            segs: List[Tuple[bytes, Optional[str]]] = []
            while True:
                pre = bytes(d.buf)
                try:
                    m = self._step(d)
                except Exception as e:  # defensive: keep traffic flowing
                    log.warning(
                        "%s parser desync %s->%s: %s; passthrough",
                        type(self).__name__, src, dst, e)
                    d.broken = True
                    d.buf.clear()
                    segs.append((pre, ""))
                    return segs
                consumed = len(pre) - len(d.buf)
                if consumed == 0:
                    if m:  # hint with no byte progress: emit, then stop
                        segs.append((b"", m))
                    return segs
                hint: Optional[str] = m or ""
                if (self.ignore_keepalive and m
                        and self.NOISE_PREFIXES
                        and m.startswith(self.NOISE_PREFIXES)):
                    hint = None  # keepalive: forward without deferring
                segs.append((pre[:consumed], hint))

    def _step(self, d: DirState) -> Optional[str]:
        raise NotImplementedError
