"""Edge dispatch: decide and release events locally, backhaul the trace.

The transceiver side of the zero-RTT path (doc/performance.md
"Zero-RTT dispatch"). A transceiver holding a current published table
(policy/edge_table.py) computes each deferred event's delay locally —
``delays[fnv64a(hint) % H]``, bit-exact with the central
``TPUSearchPolicy._delay_for`` — and hands the accepting action
straight to the event's waiter, without a round trip to the
orchestrator. What still flows centrally is **asynchronous backhaul**:
the event plus its decision detail (``decision_source="edge"``,
``table_version``, the delay, and the edge's own lifecycle stamps), so
the flight recorder, analytics, failure ingest, and the collected
trace see exactly what they see today.

Staleness protocol: every batch/poll/backhaul response piggybacks the
server's current table version; :meth:`EdgeDispatcher.note_server_version`
compares it against the held table and re-syncs on any mismatch —
dropping the table FIRST (so concurrent senders fall back to the
central wire immediately, loss-free) and then fetching the new doc. A
stale edge therefore re-syncs within one batch, and every decision
carries exactly the version of the table object that made it (never an
ambiguous mix). The ``table.publish.stale`` chaos seam suppresses one
re-sync so the invariant harness can prove dispatch stays exactly-once
and the trace complete even while an edge runs stale.

Backhaul durability: items stay buffered until a flush is acknowledged;
a failed flush re-queues them at the buffer head and retries with
backoff, and :meth:`shutdown` performs a final synchronous flush —
pending backhaul records are never silently dropped at transceiver
shutdown (mirroring the buffered-events-on-shutdown guarantee of the
batched wire). Replayed backhaul whose ack was lost dedupes on the
endpoint's uuid ring.

Clock note: the edge stamps lifecycle times with ``time.monotonic()``
/ ``time.time()`` in its own process. The edge path is for SAME-HOST
inspectors (loopback REST, the ``uds://`` wire), where
``CLOCK_MONOTONIC`` is system-wide — the orchestrator's recorder can
merge edge stamps with its own on one axis.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from namazu_tpu import chaos
from namazu_tpu.obs import spans as _spans
from namazu_tpu.policy.replayable import fnv64a
from namazu_tpu.signal.action import EventAcceptanceAction
from namazu_tpu.signal.base import fast_uuid4
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.log import get_logger

log = get_logger("inspector.edge")

_new = object.__new__


class RouteGone(RuntimeError):
    """A pool shard's wire route for an entity no longer exists (its
    transceiver unregistered): deliveries/backhaul for that entity are
    permanently undeliverable — drop them, never retry them in front
    of other entities' healthy traffic."""


class BurstAccept:
    """One grouped acceptance verdict for an edge-decided ripe group
    (``Transceiver.send_events_burst``; doc/performance.md "Binary
    wire + sharded edge"). The per-event DECISIONS are unchanged —
    each event's delay came from the same ``delays[fnv64a(hint) % H]``
    lookup the scalar path performs, and each event's full trace
    record (decision detail, ``table_version``, stamps) rides the
    asynchronous backhaul exactly as before — but the *delivery* to
    the waiting inspector is one verdict object per ripe group instead
    of one minted action per event. That is the difference between
    ~0.4M and >1M events/s on one core: burst inspectors (rawpacket
    GSO bursts, the bench) release their whole group on the verdict,
    so per-event action objects on the zero-RTT path are pure
    overhead. Parked (positive-delay) events in the same burst still
    release individually as real actions at their deadlines."""

    __slots__ = ("entity_id", "uuids", "count", "table_version",
                 "event_arrived", "triggered_time")

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"<BurstAccept entity={self.entity_id!r} "
                f"count={self.count} v{self.table_version}>")


class EdgeTable:
    """One immutable published table (policy/edge_table.py doc) plus a
    bounded hint->delay memo — hints repeat heavily (they ARE the
    semantic identity), so the fnv64a pass runs once per distinct hint
    instead of once per event."""

    __slots__ = ("version", "H", "delays", "max_interval", "_memo")

    #: memo bound; a hint space past this is re-hashed (cleared whole —
    #: eviction bookkeeping would cost more than the hash it saves)
    MEMO_CAP = 4096

    def __init__(self, doc: Dict[str, Any]) -> None:
        if doc.get("mode") != "delay":
            raise ValueError(f"unsupported table mode {doc.get('mode')!r}")
        self.version = int(doc["version"])
        self.H = int(doc["H"])
        self.delays = [float(x) for x in doc["delays"]]
        if self.H <= 0 or len(self.delays) != self.H:
            raise ValueError(
                f"table has {len(self.delays)} delays for H={self.H}")
        self.max_interval = float(doc.get("max_interval", 0.0))
        self._memo: Dict[str, float] = {}

    def delay_for(self, hint: str) -> float:
        delay = self._memo.get(hint)
        if delay is None:
            if len(self._memo) >= self.MEMO_CAP:
                self._memo.clear()
            delay = self.delays[fnv64a(hint.encode()) % self.H]
            self._memo[hint] = delay
        return delay


class EdgeDispatcher:
    """The per-transceiver edge engine: local decide + paced release +
    buffered backhaul + version sync. Wire-agnostic — the owning
    transceiver provides three callbacks:

    * ``deliver(action)`` — hand the accepting action to the waiter
      (``Transceiver.dispatch_action``);
    * ``fetch_table() -> (version, doc_or_None)`` — one table fetch
      over the owning wire;
    * ``send_backhaul(entity, items) -> server_version`` — POST one
      backhaul chunk; raises on failure (items are re-queued).
    """

    #: backhaul chunk cap per request
    BACKHAUL_MAX = 512

    def __init__(self, entity_id: str,
                 deliver: Callable[[Any], None],
                 fetch_table: Callable[[], Tuple[int, Optional[dict]]],
                 send_backhaul: Callable[[str, List[dict]], Optional[int]],
                 backhaul_window: float = 0.05,
                 backhaul_max: Optional[int] = None,
                 deliver_many: Optional[Callable[[list], None]] = None
                 ) -> None:
        self.entity_id = entity_id
        self._deliver = deliver
        self._deliver_many = deliver_many
        self._fetch_table = fetch_table
        self._send_backhaul = send_backhaul
        self.backhaul_window = max(0.0, float(backhaul_window))
        self.backhaul_max = int(backhaul_max or self.BACKHAUL_MAX)
        self._table: Optional[EdgeTable] = None
        #: server version for which a fetch returned no doc (withdrawn/
        #: suspended/never-published) — remembered so every response
        #: carrying that same version does not re-trigger a fetch
        self._no_doc_version = 0
        self._sync_lock = threading.Lock()
        self._stop = threading.Event()
        # delayed releases: (release_mono, seq, event, partial item)
        self._heap: list = []
        self._heap_seq = 0
        self._heap_cond = threading.Condition()
        self._release_thread: Optional[threading.Thread] = None
        # backhaul buffer of raw records, flushed by size/window;
        # records are per-event tuples (event first) or burst-group
        # tuples (event LIST first) expanded at flush time; _bh_count
        # tracks the EVENT total across both forms
        self._backhaul: List[tuple] = []
        self._bh_count = 0
        self._bh_cond = threading.Condition()
        self._bh_since = 0.0
        self._bh_thread: Optional[threading.Thread] = None
        self._threads_lock = threading.Lock()
        #: decisions made since start (edge-side tally; the canonical
        #: nmz_edge_decisions_total counts orchestrator-side, where the
        #: backhaul reconciles)
        self.decisions = 0
        #: monotonic stamp of the last server contact that confirmed
        #: our table state (a sync round trip, or a piggybacked version
        #: matching the held one) — the edge-staleness gauge's anchor
        self._confirmed_mono: Optional[float] = None
        # the sampled fleet gauges (staleness age, parked depth, held
        # version) ride the telemetry relay like any other producer:
        # refreshed right before each push, zero cost on the decision
        # hot path (doc/observability.md "Fleet telemetry")
        from namazu_tpu.obs import federation as _federation

        self._federation = _federation
        _federation.register_collector(self.update_gauges)

    # -- table state -----------------------------------------------------

    @property
    def active(self) -> bool:
        return self._table is not None

    @property
    def table_version(self) -> Optional[int]:
        table = self._table
        return table.version if table is not None else None

    def note_server_version(self, version: Optional[int]) -> None:
        """Compare a piggybacked server version against the held table
        and re-sync on mismatch. The one staleness choke point — every
        response on the owning wire routes its version here."""
        if version is None:
            return
        table = self._table
        held = table.version if table is not None \
            else self._no_doc_version
        if version == held:
            if table is not None:
                # the server just vouched for the exact version we
                # decide with: the staleness clock restarts
                self._confirmed_mono = time.monotonic()
            return
        if table is not None \
                and chaos.decide("table.publish.stale") is not None:
            # chaos: stay stale this round — the invariant harness
            # proves dispatch remains exactly-once and the backhaul
            # reconciles anyway (every decision still carries the stale
            # table's own unambiguous version)
            log.debug("chaos: table.publish.stale — holding v%d against "
                      "server v%d", table.version, version)
            return
        self.sync()

    def sync(self, prefetched: Optional[tuple] = None) -> Optional[int]:
        """Fetch and install the server's current table (None doc =
        central fallback); returns the installed version or None.
        Concurrent senders keep deciding against whatever table
        reference they already read — each decision is tagged with that
        table's own version, so a mid-batch rollover never produces an
        ambiguously-versioned record. ``prefetched`` is a
        ``(version, doc)`` the caller already fetched — the shard pool
        fetches ONCE for all its shards instead of N times."""
        with self._sync_lock:
            # drop FIRST: between here and the fetch completing, every
            # send falls back to the central wire — loss-free, and a
            # fetch failure cannot leave a known-stale table active
            self._table = None
            if prefetched is not None:
                version, doc = prefetched
            else:
                try:
                    version, doc = self._fetch_table()
                except Exception as e:
                    log.debug("table fetch failed (%s); staying on the "
                              "central wire", e)
                    self._no_doc_version = 0
                    return None
            if doc is None:
                self._no_doc_version = int(version)
                return None
            try:
                self._table = EdgeTable(doc)
            except (KeyError, TypeError, ValueError) as e:
                log.warning("unusable published table (%s); staying on "
                            "the central wire", e)
                self._no_doc_version = int(version)
                return None
            log.debug("edge table v%d installed (%d buckets)",
                      self._table.version, self._table.H)
            self._confirmed_mono = time.monotonic()
            # search-install -> edge-adoption propagation (the
            # publisher stamped its install time into the doc);
            # negative gaps (cross-host monotonic clocks) and
            # stamp-less docs observe nothing
            try:
                installed = float(doc["installed_mono"])
            except (KeyError, TypeError, ValueError):
                installed = None
            if installed is not None:
                _spans.table_propagation(
                    time.monotonic() - installed)
            return self._table.version

    # -- the decision hot path -------------------------------------------

    def partition(self, events: List[Event]):
        """Split ``events`` into ``(edge_eligible, central)`` with NO
        side effects — one table read, the same eligibility rule as
        :meth:`try_dispatch_batch`. Lets the transceiver run the
        fallible central wire work FIRST and release the eligible
        subset only after it succeeded, so a caller retrying a raised
        ``send_events`` burst can never re-release an already-decided
        event."""
        if self._table is None or self._stop.is_set():
            return [], list(events)
        eligible: List[Event] = []
        central: List[Event] = []
        for event in events:
            (eligible if event.deferred else central).append(event)
        return eligible, central

    def try_dispatch(self, event: Event) -> bool:
        """Decide + release ``event`` locally if the edge is active and
        the event is edge-eligible (deferred, i.e. its answer is the
        accepting action the table schedules). Returns False to send
        the event down the central wire instead."""
        table = self._table
        if table is None or not event.deferred or self._stop.is_set():
            return False
        hint = event.replay_hint()
        delay = table.delay_for(hint)
        m0 = time.monotonic()
        w0 = time.time()
        event.mark_arrived(now=w0)
        self.decisions += 1
        if delay <= 0.0:
            # the zero-RTT fast path: the waiter unblocks on the caller
            # thread, then the trace record rides the async backhaul
            self._release(event, hint, table.version, delay, m0, w0)
            self._drain_if_stopped()
            return True
        with self._heap_cond:
            heapq.heappush(
                self._heap,
                (m0 + delay, self._heap_seq,
                 event, (hint, table.version, delay, m0, w0)))
            self._heap_seq += 1
            self._heap_cond.notify()
        self._ensure_release_thread()
        self._drain_if_stopped()
        return True

    def try_dispatch_batch(self, events: List[Event]) -> List[Event]:
        """Batch decision point (``Transceiver.send_events``): one
        table read, one heap/cond acquisition, one backhaul append run
        for the whole burst. Returns the events NOT edge-eligible
        (table absent, non-deferred) — the caller routes those down
        the central wire. Decision values and per-event stamps are
        identical to :meth:`try_dispatch`; only per-event lock/branch
        overhead is amortized (doc/performance.md)."""
        table = self._table
        if table is None or self._stop.is_set():
            return list(events)
        rejected: List[Event] = []
        ripe = []     # (event, hint, delay)
        parked = []
        w0 = time.time()
        for event in events:
            if not event.deferred:
                rejected.append(event)
                continue
            hint = event.replay_hint()
            delay = table.delay_for(hint)
            event.arrived = w0
            if delay <= 0.0:
                ripe.append((event, hint, delay))
            else:
                parked.append((event, hint, delay))
        self.decisions += len(ripe) + len(parked)
        if parked:
            m0 = time.monotonic()
            with self._heap_cond:
                for event, hint, delay in parked:
                    heapq.heappush(
                        self._heap,
                        (m0 + delay, self._heap_seq,
                         event, (hint, table.version, delay, m0, w0)))
                    self._heap_seq += 1
                self._heap_cond.notify()
            self._ensure_release_thread()
        if ripe:
            # per-BURST clock stamps (m0/w1/m1 bracket the whole ripe
            # run, not each event): at the rates this path serves a
            # burst spans well under a millisecond, and three clock
            # reads per burst beat three per event
            version = table.version
            accept = self._accept_action
            m0 = time.monotonic()
            w1 = time.time()
            actions = []
            for event, hint, delay in ripe:
                action = accept(event, hint)
                action.triggered_time = w1
                actions.append(action)
            if self._deliver_many is not None:
                self._deliver_many(actions)
            else:
                deliver = self._deliver
                for action in actions:
                    deliver(action)
            m1 = time.monotonic()
            self._enqueue_backhaul(
                [(event, version, delay, m0, m1, w0, w1)
                 for event, hint, delay in ripe])
        if parked or ripe:
            self._drain_if_stopped()
        return rejected

    def try_dispatch_burst(self, events, q,
                           register_parked=None) -> List[Event]:
        """Burst decision point for ``Transceiver.send_events_burst``:
        the caller passes DEFERRED events only (its ``partition``
        output). Per-event decisions are identical to
        :meth:`try_dispatch_batch` — same memoized
        ``delays[fnv64a(hint) % H]`` lookup, same version tagging,
        same backhaul trace records — but the ripe (delay <= 0) group
        is answered with ONE :class:`BurstAccept` put on ``q`` instead
        of per-event minted actions (a mixed-entity burst's verdict
        carries the first event's entity id; ``uuids`` has the exact
        membership). Parked events are first handed to
        ``register_parked`` (the transceiver routes their individual
        release actions back to ``q``), then heap-parked as usual.
        Returns the events NOT handled (no table / stopping) — the
        caller sends those down the central wire."""
        table = self._table
        if table is None or self._stop.is_set():
            return list(events)
        memo_get = table._memo.get
        delay_for = table.delay_for
        w0 = time.time()
        ripe: List[Event] = []
        delays: List[float] = []
        parked = []
        r_ap = ripe.append
        d_ap = delays.append
        for ev in events:
            h = ev.__dict__.get("_rh")
            if h is None:
                h = ev.replay_hint()
            dly = memo_get(h)
            if dly is None:
                dly = delay_for(h)
            if dly <= 0.0:
                r_ap(ev)
                d_ap(dly)
            else:
                parked.append((ev, h, dly))
        self.decisions += len(ripe) + len(parked)
        version = table.version
        if parked:
            if register_parked is not None:
                register_parked([p[0] for p in parked])
            for p in parked:
                # parked events release as REAL actions later; their
                # minted event_arrived must carry the decision wall
                # time like every other edge path (ripe events skip
                # this — their BurstAccept verdict carries w0 once)
                p[0].arrived = w0
            m0 = time.monotonic()
            with self._heap_cond:
                for ev, h, dly in parked:
                    heapq.heappush(
                        self._heap,
                        (m0 + dly, self._heap_seq, ev,
                         (h, version, dly, m0, w0)))
                    self._heap_seq += 1
                self._heap_cond.notify()
            self._ensure_release_thread()
        if ripe:
            m0 = time.monotonic()
            w1 = time.time()
            ba = _new(BurstAccept)
            ba.entity_id = ripe[0].entity_id
            ba.uuids = [ev.uuid for ev in ripe]
            ba.count = len(ripe)
            ba.table_version = version
            ba.event_arrived = w0
            ba.triggered_time = w1
            q.put(ba)
            m1 = time.monotonic()
            # ONE group record for the whole ripe run — the flush
            # thread expands it into per-event wire items off the
            # decision path
            self._enqueue_backhaul_group(
                (ripe, delays, version, m0, m1, w0, w1))
        if parked or ripe:
            self._drain_if_stopped()
        return []

    def _drain_if_stopped(self) -> None:
        """Close the dispatch-vs-shutdown race: a dispatcher that
        passed the stop check before :meth:`shutdown` completed may
        park an event or queue a backhaul record AFTER the final
        drain/flush — with the worker threads already gone, both would
        be silently stranded. Dispatch paths call this after
        publishing, and shutdown sets the stop flag before draining,
        so one side always sees the other's work; both drains pop
        under the same locks, so draining twice is loss-free."""
        if not self._stop.is_set():
            return
        self._drain_parked()
        if self.pending_backhaul():
            self._flush_backhaul_once()

    def _drain_parked(self) -> None:
        """Deliver every still-parked release NOW, in (release_time,
        seq) order — the stop-path mirror of the release loop."""
        with self._heap_cond:
            parked = sorted(self._heap)
            self._heap = []
        for _, _, event, meta in parked:
            hint, version, delay, m0, w0 = meta
            self._release(event, hint, version, delay, m0, w0)

    @staticmethod
    def _accept_action(event: Event, hint: str):
        """Mint the accepting action directly — ``object.__new__`` plus
        explicit attribute sets, bypassing the ``Signal.__init__``
        chain (option-dict copy + schema validation) that costs ~5µs
        per action and would alone halve the zero-RTT rate.
        EventAcceptanceAction declares no OPTION_FIELDS and carries an
        empty option, so the skipped validation is a no-op by
        construction (pinned by test_edge_dispatch: the fast mint
        must equal ``Action.for_event`` field-for-field)."""
        action = _new(EventAcceptanceAction)
        action.entity_id = event.entity_id
        action.option = {}
        action.uuid = fast_uuid4()
        action.arrived = None
        action.event_uuid = event.uuid
        action.event_class = event.class_name()
        action.event_hint = hint
        action.event_arrived = event.arrived
        action.triggered_time = None
        _spans.carry(action, event)
        return action

    def _release(self, event: Event, hint: str, version: int,
                 delay: float, m0: float, w0: float) -> None:
        action = self._accept_action(event, hint)
        m1 = time.monotonic()
        w1 = time.time()
        action.triggered_time = w1
        self._deliver(action)
        # raw tuple on the hot path; the wire dict is built at flush
        # time (off the caller thread) — serialization cost must not
        # ride the zero-RTT path
        self._enqueue_backhaul([(event, version, delay, m0, m1, w0, w1)])

    def _enqueue_backhaul(self, items) -> None:
        with self._bh_cond:
            was_empty = self._bh_count == 0
            self._backhaul.extend(items)
            self._bh_count += len(items)
            if was_empty:
                self._bh_since = time.monotonic()
                self._bh_cond.notify()
        if not self._stop.is_set():
            self._ensure_backhaul_thread()

    def _enqueue_backhaul_group(self, record) -> None:
        """One burst-group record (events, delays, version, m0, m1,
        w0, w1) — a single append on the zero-RTT path."""
        with self._bh_cond:
            was_empty = self._bh_count == 0
            self._backhaul.append(record)
            self._bh_count += len(record[0])
            if was_empty:
                self._bh_since = time.monotonic()
                self._bh_cond.notify()
        if not self._stop.is_set():
            self._ensure_backhaul_thread()

    # -- delayed release --------------------------------------------------

    def _ensure_release_thread(self) -> None:
        t = self._release_thread
        if (t is not None and t.is_alive()) or self._stop.is_set():
            return
        with self._threads_lock:
            t = self._release_thread
            if (t is None or not t.is_alive()) \
                    and not self._stop.is_set():
                # None OR dead: the edge.shard.die chaos seam (and any
                # real worker crash) kills the thread, never the shard
                # state — the next park respawns a worker that drains
                # the surviving heap, so nothing is stranded
                t = threading.Thread(
                    target=self._release_loop,
                    name=f"edge-release-{self.entity_id}", daemon=True)
                t.start()
                self._release_thread = t

    def _release_loop(self) -> None:
        # profiling plane: a worker parked on the heap condition shows
        # no classifiable frame — pin it to the edge plane
        from namazu_tpu.obs import profiling

        profiling.tag_current_thread("edge")
        while True:
            if chaos.decide("edge.shard.die") is not None:
                # simulated shard-worker death: the thread exits, the
                # heap/backhaul STATE survives — exactly-once dispatch
                # is the invariant the chaos harness pins across this
                log.debug("chaos: edge.shard.die — release worker of "
                          "%s exiting", self.entity_id)
                return
            with self._heap_cond:
                while not self._heap and not self._stop.is_set():
                    self._heap_cond.wait(0.5)
                if self._stop.is_set():
                    return  # shutdown drains the heap itself
                release_at = self._heap[0][0]
                now = time.monotonic()
                if release_at > now:
                    self._heap_cond.wait(min(release_at - now, 0.5))
                    continue
                _, _, event, meta = heapq.heappop(self._heap)
            hint, version, delay, m0, w0 = meta
            self._release(event, hint, version, delay, m0, w0)

    # -- backhaul ---------------------------------------------------------

    def _ensure_backhaul_thread(self) -> None:
        t = self._bh_thread
        if (t is not None and t.is_alive()) or self._stop.is_set():
            return
        with self._threads_lock:
            t = self._bh_thread
            if (t is None or not t.is_alive()) \
                    and not self._stop.is_set():
                t = threading.Thread(
                    target=self._backhaul_loop,
                    name=f"edge-backhaul-{self.entity_id}", daemon=True)
                t.start()
                self._bh_thread = t

    def _backhaul_loop(self) -> None:
        from namazu_tpu.obs import profiling

        profiling.tag_current_thread("edge")
        backoff = 0.0
        while True:
            if chaos.decide("edge.shard.die") is not None:
                log.debug("chaos: edge.shard.die — backhaul worker of "
                          "%s exiting", self.entity_id)
                return
            with self._bh_cond:
                while not self._backhaul and not self._stop.is_set():
                    self._bh_cond.wait(0.5)
                if self._stop.is_set():
                    return  # shutdown performs the final flush
                since = self._bh_since
            delay = since + self.backhaul_window - time.monotonic()
            if delay > 0 and self._stop.wait(delay):
                return
            if self._flush_backhaul_once():
                backoff = 0.0
            else:
                # items were re-queued; retry after a bounded backoff
                backoff = min(backoff + 0.1, 2.0)
                if self._stop.wait(backoff):
                    return

    @staticmethod
    def _wire_stamp() -> Optional[Tuple[int, str]]:
        """One causality stamp per backhaul CHUNK (obs/context.py):
        the edge's logical clock ticked once — every item in the chunk
        was decided before this flush, so a shared stamp preserves the
        happens-before the per-item tick would encode, without a clock
        lock round per event. None while observability is off."""
        from namazu_tpu.obs import context as _context
        from namazu_tpu.obs import metrics as _metrics

        if not _metrics.enabled():
            return None
        return _context.clock().tick(), _context.origin()

    @staticmethod
    def _wire_item(raw, stamp: Optional[Tuple[int, str]] = None) -> dict:
        event, version, delay, m0, m1, w0, w1 = raw
        decision = {
            "delay": delay,
            "source": "table",
            "decision_source": "edge",
            "table_version": version,
            "t_intercepted": m0,
            "t_dispatched": m1,
            "arrived_wall": w0,
            "triggered_wall": w1,
        }
        # the reconcile side merges this clock and attributes the
        # stamps to THIS process; the event's own span context rides
        # event.to_jsonable(). Built on the flush thread, never the
        # zero-RTT path.
        if stamp is not None:
            decision["lc"], decision["o"] = stamp
        return {"event": event.to_jsonable(), "decision": decision}

    def _flush_backhaul_once(self) -> bool:
        """Drain the buffer onto the wire in entity-grouped chunks;
        False re-queues everything un-acked at the buffer head.
        Burst-group records are expanded into per-event wire items
        HERE, on the flush thread — never on the decision path."""
        with self._bh_cond:
            batch, self._backhaul = self._backhaul, []
            self._bh_count = 0
        if not batch:
            return True
        expanded: List[tuple] = []
        for raw in batch:
            if type(raw[0]) is list:
                events, delays, version, m0, m1, w0, w1 = raw
                expanded.extend(
                    (ev, version, dly, m0, m1, w0, w1)
                    for ev, dly in zip(events, delays))
            else:
                expanded.append(raw)
        batch = expanded
        by_entity: Dict[str, List] = {}
        for raw in batch:
            by_entity.setdefault(raw[0].entity_id, []).append(raw)
        entities = list(by_entity.items())
        for e_idx, (entity, items) in enumerate(entities):
            for i in range(0, len(items), self.backhaul_max):
                chunk = items[i:i + self.backhaul_max]
                stamp = self._wire_stamp()
                try:
                    server_version = self._send_backhaul(
                        entity, [self._wire_item(raw, stamp)
                                 for raw in chunk])
                except RouteGone:
                    # the entity's transceiver unregistered mid-race
                    # (a release that slipped past its drain): its
                    # records are permanently undeliverable — drop
                    # THEM, not the other entities' healthy traffic
                    # behind them (re-queueing would wedge this
                    # shard's whole buffer on an entity that will
                    # never come back)
                    log.warning(
                        "%d backhaul record(s) for departed entity "
                        "%s dropped (its wire is gone)",
                        len(items) - i, entity)
                    break
                except Exception as e:
                    # keep everything not yet acknowledged at the
                    # buffer HEAD: the chunk that raised (whose ack may
                    # have been lost in flight — the endpoint dedupe
                    # ring absorbs a replay) plus every untouched item
                    remaining = items[i:]
                    for _, later in entities[e_idx + 1:]:
                        remaining.extend(later)
                    with self._bh_cond:
                        self._backhaul[:0] = remaining
                        self._bh_count += len(remaining)
                    log.debug("backhaul flush failed (%s); %d "
                              "record(s) re-queued", e, len(remaining))
                    return False
                self.note_server_version(server_version)
        return True

    def pending_backhaul(self) -> int:
        """Trace records (events) still buffered for backhaul."""
        with self._bh_cond:
            return self._bh_count

    # -- fleet gauges ------------------------------------------------------

    def update_gauges(self) -> None:
        """Refresh this edge's sampled fleet gauges (the telemetry
        relay's pre-push collector): how long since the server last
        confirmed the held table (0 on the central wire — central
        dispatch cannot be stale), the parked-heap depth, and the table
        version decisions currently carry (0 = central fallback)."""
        table = self._table
        confirmed = self._confirmed_mono
        staleness = 0.0
        if table is not None and confirmed is not None:
            staleness = max(0.0, time.monotonic() - confirmed)
        _spans.edge_table_staleness(self.entity_id, staleness)
        with self._heap_cond:
            parked = len(self._heap)
        _spans.edge_parked(self.entity_id, parked)
        _spans.edge_table_version_held(
            self.entity_id, table.version if table is not None else 0)

    def drain_entity(self, entity_id: str, flush: bool = True) -> None:
        """Release ``entity_id``'s parked events NOW and flush the
        backhaul buffer — the per-entity slice of :meth:`shutdown`,
        used when one transceiver leaves a shared shard (its waiters
        and wire are about to go away; the other entities' parked
        events stay parked)."""
        with self._heap_cond:
            mine = [item for item in self._heap
                    if item[2].entity_id == entity_id]
            if mine:
                self._heap = [item for item in self._heap
                              if item[2].entity_id != entity_id]
                heapq.heapify(self._heap)
        for _, _, event, meta in sorted(mine):
            hint, version, delay, m0, w0 = meta
            self._release(event, hint, version, delay, m0, w0)
        if flush and self.pending_backhaul():
            self._flush_backhaul_once()

    # -- shutdown ---------------------------------------------------------

    def shutdown(self, flush_attempts: int = 3) -> None:
        """Flush everything: pending delayed releases are delivered
        immediately (mirroring the policy-side loss-free shutdown
        flush), then the backhaul buffer gets a final bounded-retry
        synchronous flush — no trace record is silently dropped."""
        self._federation.unregister_collector(self.update_gauges)
        self._stop.set()
        with self._heap_cond:
            self._heap_cond.notify_all()
        with self._bh_cond:
            self._bh_cond.notify_all()
        for t in (self._release_thread, self._bh_thread):
            if t is not None and t is not threading.current_thread():
                t.join(timeout=5.0)
        self._drain_parked()
        for attempt in range(max(1, flush_attempts)):
            if self._flush_backhaul_once():
                return
            time.sleep(0.05 * (attempt + 1))
        left = self.pending_backhaul()
        if left:
            log.warning("%d backhaul record(s) undeliverable at "
                        "shutdown; the orchestrator's trace for them "
                        "is incomplete", left)


# -- per-core shards (doc/performance.md "Binary wire + sharded edge") ----

class ShardedEdge:
    """One entity's handle onto its pool shard — the EdgeDispatcher
    interface the transceivers already speak, with version/sync
    operations widened to the whole pool (a rollover noticed on any
    wire must re-sync every shard)."""

    __slots__ = ("pool", "shard", "entity_id")

    def __init__(self, pool: "EdgeShardPool", shard: EdgeDispatcher,
                 entity_id: str) -> None:
        self.pool = pool
        self.shard = shard
        self.entity_id = entity_id

    @property
    def active(self) -> bool:
        return self.shard.active

    @property
    def table_version(self):
        return self.shard.table_version

    @property
    def decisions(self) -> int:
        return self.shard.decisions

    def partition(self, events):
        return self.shard.partition(events)

    def try_dispatch(self, event) -> bool:
        return self.shard.try_dispatch(event)

    def try_dispatch_batch(self, events):
        return self.shard.try_dispatch_batch(events)

    def try_dispatch_burst(self, events, q, register_parked=None):
        return self.shard.try_dispatch_burst(events, q, register_parked)

    def note_server_version(self, version) -> None:
        self.pool.note_server_version(version)

    def sync(self):
        return self.pool.sync()

    def pending_backhaul(self) -> int:
        return self.shard.pending_backhaul()

    def shutdown(self, flush_attempts: int = 3) -> None:
        self.pool.unregister(self.entity_id)


class EdgeShardPool:
    """N :class:`EdgeDispatcher` shards serving every edge transceiver
    of this process, entities hashed across them by ``fnv64a(entity) %
    N`` (the bucket function the whole plane already keys on). Each
    shard owns its own parked heap, release worker, backhaul buffer,
    and flush worker — per-shard locks never contend across shards,
    and on a multi-core host the workers spread across cores while the
    zero-RTT decision itself stays on the calling thread. Backhaul
    flush threads never touch the decision path (the PR 8 contract,
    now per shard).

    Wire routing: shards are wire-agnostic, so the pool routes each
    delivery/backhaul to the owning entity's registered transceiver
    callbacks; table fetches ride any registered wire (all wires face
    the same orchestrator). Lifecycle: :meth:`register` on transceiver
    construction, :meth:`unregister` on its shutdown — the entity's
    parked events are released and its buffered trace records flushed
    while its wire still works, and the LAST unregister shuts the
    shards down (or call :meth:`shutdown` explicitly)."""

    def __init__(self, shards: int = 2, backhaul_window: float = 0.05,
                 backhaul_max: Optional[int] = None) -> None:
        self.n_shards = max(1, int(shards))
        self._routes: Dict[str, tuple] = {}
        self._routes_lock = threading.Lock()
        self.closed = False
        self.shards: List[EdgeDispatcher] = [
            EdgeDispatcher(
                f"shard{i}",
                deliver=self._route_deliver,
                deliver_many=self._route_deliver_many,
                fetch_table=self._route_fetch_table,
                send_backhaul=self._route_backhaul,
                backhaul_window=backhaul_window,
                backhaul_max=backhaul_max)
            for i in range(self.n_shards)]

    # -- registration -----------------------------------------------------

    def shard_for(self, entity_id: str) -> EdgeDispatcher:
        return self.shards[fnv64a(entity_id.encode()) % self.n_shards]

    def register(self, entity_id: str, deliver, deliver_many,
                 fetch_table, send_backhaul) -> ShardedEdge:
        with self._routes_lock:
            if self.closed:
                raise RuntimeError("shard pool is closed")
            self._routes[entity_id] = (deliver, deliver_many,
                                       fetch_table, send_backhaul)
        return ShardedEdge(self, self.shard_for(entity_id), entity_id)

    def unregister(self, entity_id: str) -> None:
        """Drain the entity's parked events + flush its shard while
        its wire is still usable, then drop the route; the last
        entity out closes the pool."""
        with self._routes_lock:
            if entity_id not in self._routes:
                return
        try:
            self.shard_for(entity_id).drain_entity(entity_id)
        except Exception:
            log.debug("drain for %s failed at unregister", entity_id,
                      exc_info=True)
        with self._routes_lock:
            self._routes.pop(entity_id, None)
            last = not self._routes and not self.closed
            if last:
                self.closed = True
        if last:
            for shard in self.shards:
                shard.shutdown()

    def shutdown(self) -> None:
        with self._routes_lock:
            if self.closed:
                return
            self.closed = True
            self._routes.clear()
        for shard in self.shards:
            shard.shutdown()

    # -- pool-wide table state --------------------------------------------

    def note_server_version(self, version) -> None:
        for shard in self.shards:
            shard.note_server_version(version)

    def sync(self):
        """One table fetch for ALL shards (N identical round trips per
        transceiver sync would otherwise scale with the shard count);
        a failed fetch leaves every shard on the central wire."""
        try:
            fetched = self._route_fetch_table()
        except Exception as e:
            log.debug("pool table fetch failed (%s); shards stay on "
                      "the central wire", e)
            version = None
            for shard in self.shards:
                version = shard.sync(prefetched=(0, None))
            return None
        version = None
        for shard in self.shards:
            version = shard.sync(prefetched=fetched)
        return version

    @property
    def decisions(self) -> int:
        return sum(shard.decisions for shard in self.shards)

    def pending_backhaul(self) -> int:
        return sum(shard.pending_backhaul() for shard in self.shards)

    # -- wire routing ------------------------------------------------------

    def _route_of(self, entity_id: str):
        route = self._routes.get(entity_id)
        if route is None:
            raise RouteGone(f"no registered wire for {entity_id!r}")
        return route

    def _route_deliver(self, action) -> None:
        route = self._routes.get(action.entity_id)
        if route is None:
            # a release that slipped past the entity's unregister
            # drain: its waiter is gone with its transceiver — drop
            # like any unroutable action, NEVER raise into the shared
            # release worker other entities depend on
            log.debug("dropping release for departed entity %s",
                      action.entity_id)
            return
        route[0](action)

    def _route_deliver_many(self, actions) -> None:
        # shard release bursts are single-entity in practice; fall
        # back to per-action routing when they are not
        first = actions[0].entity_id
        route = self._routes.get(first)
        if route is not None and all(
                a.entity_id == first for a in actions):
            deliver_many = route[1]
            if deliver_many is not None:
                return deliver_many(actions)
        for action in actions:
            self._route_deliver(action)

    def _route_fetch_table(self):
        with self._routes_lock:
            routes = list(self._routes.values())
        if not routes:
            raise RuntimeError("no registered wires to fetch a table")
        return routes[0][2]()

    def _route_backhaul(self, entity_id: str, items):
        return self._route_of(entity_id)[3](entity_id, items)


#: the process-global pool ``edge_shards=N`` transceiver knobs share
_shared_pool: Optional[EdgeShardPool] = None
_shared_pool_lock = threading.Lock()


def shared_pool(shards: int, backhaul_window: float = 0.05
                ) -> EdgeShardPool:
    """The process-global shard pool (created on first use; a closed
    pool is replaced). The first caller's shard count wins — later
    mismatches warn and join the existing pool, because half the
    transceivers hashing entities over a DIFFERENT shard count would
    split one entity across two parked heaps."""
    global _shared_pool
    with _shared_pool_lock:
        pool = _shared_pool
        if pool is None or pool.closed:
            pool = _shared_pool = EdgeShardPool(
                shards, backhaul_window=backhaul_window)
        elif pool.n_shards != max(1, int(shards)):
            log.warning("shared edge pool already has %d shard(s); "
                        "ignoring request for %d", pool.n_shards,
                        shards)
        return pool
