"""Raw packet header decoding for kernel/switch-level interception.

Pure-stdlib decoder for the slice of Ethernet/IPv4/TCP/UDP the
hookswitch backend needs (the reference leans on gopacket for this,
/root/reference/nmz/inspector/ethernet/util.go:36-60): flow endpoints
for entity ids, TCP (seq, ack, flags) for retransmit suppression, and
the L4 payload for semantic hints.
"""

from __future__ import annotations

import hashlib
import struct
from typing import NamedTuple, Optional

ETH_HLEN = 14
ETHERTYPE_IPV4 = 0x0800
PROTO_TCP = 6
PROTO_UDP = 17

# TCP flag bits (low byte of the 13th/14th header bytes)
FIN, SYN, RST, PSH, ACK = 0x01, 0x02, 0x04, 0x08, 0x10


class Packet(NamedTuple):
    """Decoded headers of one ethernet frame (fields None when absent)."""

    src_ip: Optional[str] = None
    dst_ip: Optional[str] = None
    proto: Optional[int] = None  # PROTO_TCP / PROTO_UDP / other
    src_port: Optional[int] = None
    dst_port: Optional[int] = None
    seq: Optional[int] = None  # TCP only
    ack: Optional[int] = None  # TCP only
    flags: Optional[int] = None  # TCP only (FIN|SYN|RST|PSH|ACK bits)
    payload: bytes = b""

    @property
    def src_entity(self) -> str:
        """Flow endpoint as an entity id (parity: makeEntityIDs,
        util.go:25-33 — "entity-IP:PORT", unknown when not IP/TCP)."""
        if self.src_ip is None or self.src_port is None:
            return "_nmz_unknown_entity"
        return f"entity-{self.src_ip}:{self.src_port}"

    @property
    def dst_entity(self) -> str:
        if self.dst_ip is None or self.dst_port is None:
            return "_nmz_unknown_entity"
        return f"entity-{self.dst_ip}:{self.dst_port}"

    @property
    def flow_key(self) -> str:
        return (f"{self.src_ip}:{self.src_port}-"
                f"{self.dst_ip}:{self.dst_port}")

    def content_hint(self) -> str:
        """Timing-independent identity of the frame's payload: protocol +
        a short digest. Raw frames have no semantic parser, so payload
        content is the only stable identity (uuid/seq/timing must stay
        out of replay hints, reference interface.go:24-31); the flow half
        is added by PacketEvent.replay_hint."""
        if not self.payload:
            kind = {PROTO_TCP: "tcp", PROTO_UDP: "udp"}.get(self.proto, "ip")
            return f"frame:{kind}:empty"
        digest = hashlib.sha1(self.payload[:256]).hexdigest()[:16]
        return f"frame:{digest}"


def decode_ethernet(frame: bytes) -> Packet:
    """Decode an ethernet frame's IPv4/TCP/UDP headers, best effort."""
    if len(frame) < ETH_HLEN:
        return Packet()
    (ethertype,) = struct.unpack_from("!H", frame, 12)
    if ethertype != ETHERTYPE_IPV4:
        return Packet()
    off = ETH_HLEN
    if len(frame) < off + 20:
        return Packet()
    ver_ihl = frame[off]
    if ver_ihl >> 4 != 4:
        return Packet()
    ihl = (ver_ihl & 0xF) * 4
    proto = frame[off + 9]
    # clip to the IPv4 total length: sub-60-byte frames arrive with
    # ethernet trailer padding after the IP datagram, and a payload
    # slice taken to the frame end would digest the padding — the same
    # protocol message would then hash into different replay-hint
    # buckets depending on whether the capture path pads (ADVICE r4).
    # GSO/TSO captures are the exception: offloaded super-frames carry
    # total_len == 0 (or a value smaller than the headers they visibly
    # contain); such a length is unknown, not authoritative — fall back
    # to the frame end so ports/seq/payload keep decoding
    (total_len,) = struct.unpack_from("!H", frame, off + 2)
    min_l4 = 20 if proto == PROTO_TCP else 8 if proto == PROTO_UDP else 0
    if total_len == 0 or total_len < ihl + min_l4:
        end = len(frame)
    else:
        end = min(len(frame), off + max(total_len, ihl))
    src_ip = ".".join(str(b) for b in frame[off + 12:off + 16])
    dst_ip = ".".join(str(b) for b in frame[off + 16:off + 20])
    l4 = off + ihl
    if proto == PROTO_TCP and end >= l4 + 20:
        sport, dport, seq, ack = struct.unpack_from("!HHII", frame, l4)
        data_off = (frame[l4 + 12] >> 4) * 4
        flags = frame[l4 + 13] & (FIN | SYN | RST | PSH | ACK)
        return Packet(src_ip, dst_ip, proto, sport, dport, seq, ack,
                      flags, bytes(frame[l4 + data_off:end]))
    if proto == PROTO_UDP and end >= l4 + 8:
        sport, dport = struct.unpack_from("!HH", frame, l4)
        return Packet(src_ip, dst_ip, proto, sport, dport,
                      payload=bytes(frame[l4 + 8:end]))
    return Packet(src_ip, dst_ip, proto)


class TcpRetransWatcher:
    """Suppress TCP retransmissions before they reach the policy.

    Crucial at raw-packet level: a delayed segment triggers the sender's
    retransmit timer, and without suppression the duplicate would be
    queued as a fresh event — double delivery of the same message into
    the schedule (parity: tcpwatcher.go:14-72, keyed by flow and matched
    on seq+ack+flags; an RST clears the flow's memory). Not thread-safe;
    call from the single receive loop, like the reference does.
    """

    def __init__(self) -> None:
        self._last: dict[str, tuple] = {}

    def is_retransmit(self, pkt: Packet) -> bool:
        if pkt.proto != PROTO_TCP or pkt.seq is None:
            return False
        key = pkt.flow_key
        sig = (pkt.seq, pkt.ack, pkt.flags)
        if self._last.get(key) == sig:
            return True
        if pkt.flags is not None and pkt.flags & RST:
            self._last.pop(key, None)
        else:
            self._last[key] = sig
        return False
