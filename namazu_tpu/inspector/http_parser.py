"""HTTP stream parser: semantic hints for HTTP/1.x and HTTP/2 (gRPC) links.

Capability parity (and a substantial upgrade) over the reference's etcd
inspector (/root/reference/example/etcd/3517-reproduce/materials/
etcd_inspector.py), which registered a scapy layer on the etcd peer port
but ultimately base64-encoded raw packets. Here the proxy hands us ordered
byte streams, so we decode properly:

* **HTTP/1.x**: request lines (``POST /raft HTTP/1.1``) and status lines
  become hints ``http:POST:/raft`` / ``http:resp:200``; bodies are skipped
  via Content-Length / chunked framing. etcd v2's raft transport is
  exactly such POSTs between peers.
* **HTTP/2**: the client preface, or — on the server direction, which has
  no preface — a leading SETTINGS frame; hints carry frame type + stream
  id (``h2:HEADERS:s1``). etcd v3's gRPC rides on this.

Volatile payload bytes stay out of hints so schedules replay across runs.
"""

from __future__ import annotations

import struct
from typing import Optional

from namazu_tpu.inspector.stream_parser import MAX_BUFFER, DirState, \
    StreamParser

H2_PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

H2_FRAME_TYPES = {
    0: "DATA", 1: "HEADERS", 2: "PRIORITY", 3: "RST_STREAM", 4: "SETTINGS",
    5: "PUSH_PROMISE", 6: "PING", 7: "GOAWAY", 8: "WINDOW_UPDATE",
    9: "CONTINUATION",
}

_METHODS = (b"GET", b"POST", b"PUT", b"DELETE", b"HEAD", b"OPTIONS",
            b"PATCH", b"CONNECT", b"TRACE")


def _looks_like_h2_settings(buf: bytearray) -> bool:
    """RFC 7540 §3.5: the server's first frame MUST be SETTINGS — length a
    multiple of 6, type 4, flags 0 (the initial SETTINGS is never an ack),
    stream id 0."""
    if len(buf) < 9:
        return False
    length = struct.unpack(">I", b"\x00" + bytes(buf[:3]))[0]
    ftype, flags = buf[3], buf[4]
    stream_id = struct.unpack(">I", bytes(buf[5:9]))[0] & 0x7FFFFFFF
    return (ftype == 4 and flags == 0 and stream_id == 0
            and length % 6 == 0 and length <= 16 * 6)


class HttpStreamParser(StreamParser):
    """Stateful chunk->hint parser for HTTP links; a valid ``PacketParser``.

    HTTP/2 PING / SETTINGS / WINDOW_UPDATE frames are keepalive noise:
    suppressed from hints, and pure-noise chunks forward without deferring.
    """

    NOISE_PREFIXES = ("h2:PING", "h2:SETTINGS", "h2:WINDOW_UPDATE")

    def _step(self, d: DirState) -> Optional[str]:
        buf = d.buf
        if d.mode == "detect":
            if len(buf) < 9 and H2_PREFACE.startswith(bytes(buf)):
                return None  # could still become a client preface
            if bytes(buf[:len(H2_PREFACE)]) == H2_PREFACE:
                del buf[:len(H2_PREFACE)]
                d.mode = "h2"
                return "h2:preface"
            if _looks_like_h2_settings(buf):
                d.mode = "h2"  # server direction: frames from byte 0
            else:
                d.mode = "http1"
        if d.mode == "h2":
            return self._h2_step(d)
        return self._http1_step(d)

    # -- HTTP/1.x ------------------------------------------------------

    def _http1_step(self, d: DirState) -> Optional[str]:
        buf = d.buf
        if d.skip:
            n = min(d.skip, len(buf))
            del buf[:n]
            d.skip -= n
            return None
        if d.chunked:
            return self._chunked_step(d)
        end = buf.find(b"\r\n\r\n")
        if end < 0:
            if len(buf) > 64 * 1024:
                raise ValueError("unterminated HTTP/1 header block")
            return None
        head = bytes(buf[:end]).split(b"\r\n")
        del buf[:end + 4]
        first = head[0]
        length = 0
        chunked = False
        for line in head[1:]:
            lower = line.lower()
            if lower.startswith(b"content-length:"):
                length = int(line.split(b":", 1)[1].strip())
            elif lower.startswith(b"transfer-encoding:") and \
                    b"chunked" in lower:
                chunked = True
        if chunked:
            d.chunked = True
        else:
            d.skip = length
        parts = first.split(b" ")
        if parts and parts[0] in _METHODS and len(parts) >= 2:
            method = parts[0].decode("ascii")
            path = parts[1].decode("utf-8", "replace").split("?")[0]
            return f"http:{method}:{path}"
        if first.startswith(b"HTTP/") and len(parts) >= 2:
            return f"http:resp:{parts[1].decode('ascii', 'replace')}"
        raise ValueError(f"bad HTTP/1 start line {first[:40]!r}")

    def _chunked_step(self, d: DirState) -> Optional[str]:
        buf = d.buf
        while True:
            nl = buf.find(b"\r\n")
            if nl < 0:
                return None
            size = int(bytes(buf[:nl]).split(b";")[0], 16)
            need = nl + 2 + size + 2
            if len(buf) < need:
                return None
            del buf[:need]
            if size == 0:
                d.chunked = False
                return None

    # -- HTTP/2 --------------------------------------------------------

    @staticmethod
    def _h2_step(d: DirState) -> Optional[str]:
        buf = d.buf
        if len(buf) < 9:
            return None
        length = struct.unpack(">I", b"\x00" + bytes(buf[:3]))[0]
        ftype = buf[3]
        stream_id = struct.unpack(">I", bytes(buf[5:9]))[0] & 0x7FFFFFFF
        if length > MAX_BUFFER:
            raise ValueError(f"bad h2 frame length {length}")
        if len(buf) < 9 + length:
            return None
        del buf[:9 + length]
        name = H2_FRAME_TYPES.get(ftype, f"type{ftype}")
        if name in ("DATA", "HEADERS"):
            return f"h2:{name}:s{stream_id}:len={length}"
        return f"h2:{name}"


def etcd_parser(ignore_keepalive: bool = True) -> HttpStreamParser:
    """Parser for etcd peer links: v2 raft-over-HTTP POSTs and v3 gRPC
    (HTTP/2) are both recognized by :class:`HttpStreamParser`."""
    return HttpStreamParser(ignore_keepalive=ignore_keepalive)
