"""ZooKeeper protocol parsers: semantic replay hints for the proxy inspector.

Capability parity with the reference's zktraffic-based inspector
(/root/reference/misc/pynmz/inspector/zookeeper.py:23-167), which sniffs
raw packets and classifies them into FLE / ZAB / client messages so
``PacketEvent``s carry *semantic* replay hints instead of raw bytes — the
precondition for deterministic replay (and for the TPU search plane's
hint->delay tables to transfer across runs).

TPU-era redesign: interception happens in the userspace TCP proxy
(namazu_tpu/inspector/ethernet.py), so instead of per-packet sniffing +
TCP reassembly (zktraffic's job), each (direction, connection) of a link
is a clean ordered byte stream and the parser is a small incremental
state machine. No scapy, no zktraffic — the ZooKeeper wire formats are
decoded directly:

* **FLE** (election port, default 3888): QuorumCnxManager handshake —
  a bare 8-byte sid (<=3.4), or the 8-byte PROTOCOL_VERSION ``-65536``
  followed by sid and the sender's addr buffer (3.5+) — then 4-byte
  length-framed notifications
  ``state, leader, zxid, electionEpoch[, peerEpoch][, version]``.
* **ZAB** (quorum port, default 2888): unframed jute QuorumPacket records
  ``type(i32) zxid(i64) data(buffer) authinfo(vector<Id>)``.
* **client** (default 2181): 4-byte length-framed requests/responses;
  ConnectRequest/Response on the first frame; 4-letter admin words.

Hints deliberately exclude per-run-volatile fields (session ids, xids,
timestamps, payload bytes) the same way the reference's
``map_zktraffic_message_to_dict`` ignores them (zookeeper.py:74-79), but
are human-readable strings rather than opaque hashes.
"""

from __future__ import annotations

import hashlib
import struct
from typing import Optional

from namazu_tpu.inspector.stream_parser import MAX_BUFFER, DirState, \
    StreamParser

# 4-byte framed payloads never legitimately approach this
MAX_FRAME = 4 * 1024 * 1024

FLE_PROTOCOL_VERSION = -65536  # QuorumCnxManager.PROTOCOL_VERSION (3.5+)

FLE_STATES = {0: "looking", 1: "following", 2: "leading", 3: "observing"}

ZAB_TYPES = {
    1: "request", 2: "proposal", 3: "ack", 4: "commit", 5: "ping",
    6: "revalidate", 7: "sync", 8: "inform", 9: "commitandactivate",
    10: "newleader", 11: "followerinfo", 12: "uptodate", 13: "diff",
    14: "trunc", 15: "snap", 16: "observerinfo", 17: "leaderinfo",
    18: "ackepoch", 19: "informandactivate",
}

CLIENT_OPS = {
    0: "notification", 1: "create", 2: "delete", 3: "exists", 4: "getData",
    5: "setData", 6: "getACL", 7: "setACL", 8: "getChildren", 9: "sync",
    11: "ping", 12: "getChildren2", 13: "check", 14: "multi",
    15: "create2", 16: "reconfig", 100: "auth", 101: "setWatches",
    102: "sasl", -10: "createSession", -11: "closeSession", -1: "error",
}

# ops whose request body starts with a path string (first field after the
# header) — enough to give the hint a semantic identity
_PATH_OPS = frozenset(
    ["create", "delete", "exists", "getData", "setData", "getACL", "setACL",
     "getChildren", "sync", "getChildren2", "check", "create2"]
)

FOUR_LETTER_WORDS = frozenset(
    [b"conf", b"cons", b"crst", b"dump", b"envi", b"ruok", b"srst", b"srvr",
     b"stat", b"wchs", b"wchc", b"wchp", b"mntr", b"isro", b"gtmk", b"stmk"]
)


def _i32(b, off: int = 0) -> int:
    return struct.unpack_from(">i", b, off)[0]


def _i64(b, off: int = 0) -> int:
    return struct.unpack_from(">q", b, off)[0]


def _digest(data: bytes) -> str:
    return hashlib.md5(data).hexdigest()[:8]


class ZkStreamParser(StreamParser):
    """Stateful chunk->hint parser for one ZooKeeper protocol.

    Use one instance per proxied link (links are per-port, so the protocol
    is known: election port -> "fle", quorum port -> "zab", client port ->
    "client"). Returning ``None`` tells the inspector to forward without
    deferring (pings), mirroring the reference's ``map_packet_to_event``
    returning None (zookeeper.py:134-167).
    """

    NOISE_PREFIXES = ("ping",)

    def __init__(self, protocol: str, ignore_pings: bool = True):
        if protocol not in ("fle", "zab", "client"):
            raise ValueError(f"unknown protocol {protocol!r}")
        super().__init__(ignore_keepalive=ignore_pings)
        self.protocol = protocol

    @property
    def ignore_pings(self) -> bool:
        return self.ignore_keepalive

    def _step(self, d: DirState) -> Optional[str]:
        if self.protocol == "fle":
            return self._fle_step(d)
        if self.protocol == "zab":
            return self._zab_step(d)
        return self._client_step(d)

    # -- FLE --------------------------------------------------------------

    def _fle_step(self, d: DirState) -> Optional[str]:
        buf = d.buf
        if d.stage == "init":
            if len(buf) < 8:
                return None
            first = _i64(buf)
            if first == FLE_PROTOCOL_VERSION:
                # 3.5+ initial: version(i64 -65536) sid(i64) addr(buffer)
                if len(buf) < 20:
                    return None
                alen = _i32(buf, 16)
                if not 0 <= alen <= MAX_FRAME:
                    raise ValueError(f"bad FLE initial addr len {alen}")
                if len(buf) < 20 + alen:
                    return None
                sid = _i64(buf, 8)
                del buf[:20 + alen]
                d.stage = "frames"
                return f"fle:init:sid={sid}"
            if _i32(buf) == 0:
                # <=3.4 initial: bare big-endian sid (small, high word 0)
                sid = first
                del buf[:8]
                d.stage = "frames"
                return f"fle:init:sid={sid}"
            d.stage = "frames"  # mid-stream attach: assume framed
            return None
        # length-framed notification
        if len(buf) < 4:
            return None
        flen = _i32(buf)
        if not 0 < flen <= MAX_FRAME:
            raise ValueError(f"bad FLE frame len {flen}")
        if len(buf) < 4 + flen:
            return None
        p = bytes(buf[4:4 + flen])
        del buf[:4 + flen]
        if flen < 28:
            return f"fle:short:{_digest(p)}"
        state = _i32(p, 0)
        leader = _i64(p, 4)
        zxid = _i64(p, 12)
        epoch = _i64(p, 20)
        peer_epoch = _i64(p, 28) if flen >= 36 else None
        parts = [
            "fle:notif",
            f"state={FLE_STATES.get(state, state)}",
            f"leader={leader}",
            f"zxid={zxid:#x}",
            f"epoch={epoch}",
        ]
        if peer_epoch is not None:
            parts.append(f"peerEpoch={peer_epoch}")
        return ":".join(parts)

    # -- ZAB --------------------------------------------------------------

    @staticmethod
    def _zab_step(d: DirState) -> Optional[str]:
        buf = d.buf
        # jute QuorumPacket: type(i32) zxid(i64) data(buffer) authinfo(vec)
        if len(buf) < 16:
            return None
        ptype = _i32(buf)
        if ptype not in ZAB_TYPES:
            raise ValueError(f"unknown ZAB packet type {ptype}")
        zxid = _i64(buf, 4)
        off = 12
        dlen = _i32(buf, off)
        off += 4
        if dlen > MAX_FRAME:
            raise ValueError(f"bad ZAB data len {dlen}")
        ndata = max(0, dlen)  # -1 == null buffer
        if len(buf) < off + ndata + 4:
            return None
        off += ndata
        nauth = _i32(buf, off)
        off += 4
        if nauth > 64:
            raise ValueError(f"bad ZAB authinfo count {nauth}")
        for _ in range(max(0, nauth)):  # vector<Id{scheme, id}>
            for _field in range(2):
                if len(buf) < off + 4:
                    return None
                slen = _i32(buf, off)
                off += 4
                if slen > MAX_FRAME:
                    raise ValueError(f"bad ZAB authinfo string {slen}")
                slen = max(0, slen)
                if len(buf) < off + slen:
                    return None
                off += slen
        del buf[:off]
        name = ZAB_TYPES[ptype]
        if name == "ping":
            return "ping"
        return f"zab:{name}:zxid={zxid:#x}:dlen={ndata}"

    # -- client protocol --------------------------------------------------

    def _client_step(self, d: DirState) -> Optional[str]:
        buf = d.buf
        if d.stage == "init" and len(buf) >= 4 and bytes(buf[:4]) in \
                FOUR_LETTER_WORDS:
            word = bytes(buf[:4]).decode("ascii")
            del buf[:4]
            d.stage = "fourletter"  # rest of stream is the text reply
            return f"cm:4lw:{word}"
        if d.stage == "fourletter":
            # free-form text response / nothing further to frame
            buf.clear()
            return None
        if len(buf) < 4:
            return None
        flen = _i32(buf)
        if not 0 <= flen <= MAX_FRAME:
            raise ValueError(f"bad client frame len {flen}")
        if len(buf) < 4 + flen:
            return None
        p = bytes(buf[4:4 + flen])
        del buf[:4 + flen]
        first = d.stage == "init"
        d.stage = "frames"
        if d.is_request:
            return self._client_request(p, first)
        return self._client_response(p, first)

    @staticmethod
    def _client_request(p: bytes, first: bool) -> str:
        if first and len(p) >= 28:
            # ConnectRequest: ver(i32) lastZxid(i64) timeout(i32)
            # sessionId(i64) passwd(buffer) [readOnly(b)]
            last_zxid = _i64(p, 4)
            return f"cm:connect:lastZxid={last_zxid:#x}"
        if len(p) < 8:
            return f"cm:short:{_digest(p)}"
        xid = _i32(p, 0)
        op = _i32(p, 4)
        name = CLIENT_OPS.get(op, f"op{op}")
        if name == "ping" or xid == -2:
            return "ping"
        if name in _PATH_OPS and len(p) >= 12:
            plen = _i32(p, 8)
            if 0 <= plen <= len(p) - 12:
                path = p[12:12 + plen].decode("utf-8", "replace")
                return f"cm:{name}:{path}"
        return f"cm:{name}"

    @staticmethod
    def _client_response(p: bytes, first: bool) -> str:
        if first and len(p) >= 20:
            # ConnectResponse: ver(i32) timeout(i32) sessionId(i64) passwd
            return "sm:connect"
        if len(p) < 16:
            return f"sm:short:{_digest(p)}"
        xid = _i32(p, 0)
        zxid = _i64(p, 4)
        err = _i32(p, 12)
        if xid == -2:
            return "ping"
        if xid == -1:  # watch notification fired by the server
            return f"sm:notification:zxid={zxid:#x}"
        return f"sm:reply:zxid={zxid:#x}:err={err}"


def zk_parser_for_port(port: int, ignore_pings: bool = True) -> ZkStreamParser:
    """Pick the protocol by conventional ZooKeeper port (3888 election,
    2888 quorum, anything else client)."""
    if port % 10000 == 3888:
        return ZkStreamParser("fle", ignore_pings)
    if port % 10000 == 2888:
        return ZkStreamParser("zab", ignore_pings)
    return ZkStreamParser("client", ignore_pings)
