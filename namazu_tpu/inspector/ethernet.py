"""Ethernet (message) inspector: defer, reorder, and drop network traffic.

Capability parity with /root/reference/nmz/inspector/ethernet (NFQUEUE and
hookswitch backends). TPU-era redesign: the primary backend is a
**userspace TCP proxy** — the system-under-test's nodes are pointed at
proxy ports (one per peer link, e.g. via its own config, DNS, or iptables
REDIRECT), and every chunk that flows through a link becomes a deferred
``PacketEvent`` the policy can delay or drop.

Why a proxy instead of NFQUEUE: it needs no root, no kernel modules and no
external switch, works in any container, and — because interception happens
above TCP — retransmissions never reach the inspector, which removes the
reference's whole TCP-retransmit-suppression problem (its tcpwatcher
exists only because delaying raw segments triggers duplicate delivery,
ethernet_nfq.go:53-56). The cost is per-link (not per-interface)
interception, which matches how the reference's examples are actually
wired (one inspected port per ZooKeeper election/quorum link).

A ``parser`` callback turns raw chunks into semantic replay hints (the
role of the reference's zktraffic-based inspectors, misc/pynmz/inspector/
zookeeper.py) so schedules can be replayed deterministically.
"""

from __future__ import annotations

import queue as _queue
import socket
import threading
from typing import Callable, Optional

from namazu_tpu.inspector.transceiver import Transceiver
from namazu_tpu.signal.action import PacketFaultAction
from namazu_tpu.signal.event import PacketEvent
from namazu_tpu.utils.log import get_logger

log = get_logger("inspector.ethernet")

# (chunk, src, dst[, conn_id]) -> replay hint; "" = no semantic identity
# (still deferred), None = uninteresting traffic, forward immediately
# without deferring (parity: map_packet_to_event returning None,
# misc/pynmz/inspector/ether.py). Stateful parsers (StreamParser
# subclasses) take conn_id so concurrent connections on one link never
# share a parse buffer; plain 3-arg callables are also accepted.
PacketParser = Callable[..., Optional[str]]


def _addr(host_port: str) -> tuple[str, int]:
    host, _, port = host_port.rpartition(":")
    return host or "127.0.0.1", int(port)


class ProxyLink:
    """One inspected TCP link: listen address -> upstream address."""

    def __init__(
        self,
        inspector: "EthernetProxyInspector",
        listen: str,
        upstream: str,
        src_entity: str,
        dst_entity: str,
    ):
        self.inspector = inspector
        self.listen = _addr(listen)
        self.upstream = _addr(upstream)
        self.src_entity = src_entity
        self.dst_entity = dst_entity
        self._server: Optional[socket.socket] = None
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

    @property
    def port(self) -> int:
        assert self._server is not None
        return self._server.getsockname()[1]

    def start(self) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(self.listen)
        srv.listen(16)
        self._server = srv
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"proxy-accept-{self.listen[1]}")
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._server.accept()
            except OSError:
                return
            try:
                up = socket.create_connection(self.upstream, timeout=10)
            except OSError as e:
                log.warning("upstream %s unreachable: %s", self.upstream, e)
                client.close()
                continue
            conn_id = self.inspector.next_conn_id()
            for src, dst, se, de in (
                (client, up, self.src_entity, self.dst_entity),
                (up, client, self.dst_entity, self.src_entity),
            ):
                t = threading.Thread(
                    target=self._pump, args=(src, dst, se, de, conn_id),
                    daemon=True, name=f"proxy-pump-{se}->{de}",
                )
                t.start()
                self._threads.append(t)

    def _pump(self, src: socket.socket, dst: socket.socket,
              src_entity: str, dst_entity: str, conn_id: int = 0) -> None:
        """One direction of one connection: reader thread (this) parses
        chunks into message segments and posts their events immediately;
        a writer thread releases segments **in arrival order** as their
        actions come back (drops skip the send).

        Per-direction FIFO mirrors what kernel-level interception gives
        the reference (a delayed NFQUEUE segment holds back the bytes
        behind it — TCP delivers in order), so delaying one message
        delays the rest of its direction, never corrupts the stream; the
        *policy-visible* interleaving across directions/links is where
        reordering happens. Posting every pending message's event before
        the first action returns lets the policy see true arrival times
        for all of them (a blocking per-message loop would serialize
        arrivals behind releases)."""
        rel_q: _queue.Queue = _queue.Queue()
        insp = self.inspector

        # Drop semantics depend on framing: a message-segmenting parser
        # guarantees the skipped bytes are one whole protocol message,
        # so the peer's decoder stays in sync (the closest analogue of
        # the reference's NF_DROP, which TCP itself repairs by
        # retransmission). On raw/chunk links a skip would tear an
        # arbitrary byte range out of a live stream — a fault model no
        # real network produces — so the drop is realized as a
        # CONNECTION CLOSE instead: a reset is a real-world fault, and
        # the testee's reconnect logic (not its codec) absorbs it.
        framed = hasattr(insp.parser, "segment")

        def writer() -> None:
            # once the stream is dead (unframed drop closed it, or a
            # send failed) the writer keeps consuming rel_q in drain
            # mode until the reader's None sentinel: deferred events
            # queued behind the break must be forgotten, not stranded —
            # their correlation state would leak and late actions would
            # land in channels nobody reads (ADVICE r4)
            draining = False
            while True:
                item = rel_q.get()
                if item is None:
                    break
                data, ch, event = item
                if draining:
                    if ch is not None:
                        insp.trans.forget(event)
                    continue
                if ch is not None:
                    try:
                        action = ch.get(timeout=insp.action_timeout)
                    except _queue.Empty:
                        insp.trans.forget(event)
                        log.warning(
                            "packet %s->%s: no action in %ss; releasing",
                            src_entity, dst_entity, insp.action_timeout)
                        action = None
                    if isinstance(action, PacketFaultAction):
                        insp.count_drop()
                        if framed:
                            continue  # skip one whole message
                        log.info(
                            "drop on unframed link %s->%s: closing the "
                            "connection (byte-range skips would desync "
                            "the stream)", src_entity, dst_entity)
                        for s in (src, dst):
                            try:
                                s.shutdown(socket.SHUT_RDWR)
                            except OSError:
                                pass
                        draining = True
                        continue
                if data:
                    try:
                        dst.sendall(data)
                    except OSError:
                        draining = True

        wt = threading.Thread(
            target=writer, daemon=True,
            name=f"proxy-write-{src_entity}->{dst_entity}")
        wt.start()
        try:
            while not self._stop.is_set():
                try:
                    chunk = src.recv(65536)
                except OSError:
                    break
                if not chunk:
                    break
                for data, ch, event in insp.intercept(
                        chunk, src_entity, dst_entity, conn_id):
                    rel_q.put((data, ch, event))
        finally:
            rel_q.put(None)  # writer drains pending releases, loss-free
            wt.join(timeout=60)
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass


class UdpProxyLink:
    """One inspected UDP relay: datagrams in via the listen socket are
    deferred per-datagram and forwarded to the upstream address; replies
    from upstream route back to the most recent client address.

    UDP is where per-packet interception semantics are CLEAN, unlike the
    TCP proxy's parsed streams: a datagram is a self-contained message,
    so a drop is exactly the reference's NF_DROP (any-IP capture,
    /root/reference/nmz/inspector/ethernet/ethernet_nfq.go:95-103 — its
    packet verdicts are per-datagram for UDP flows) and independent
    per-datagram release order IS the interleaving being fuzzed — no
    stream to desynchronize, no retransmit problem (UDP has none).
    """

    #: bounded concurrent deferrals: a datagram burst must not spawn a
    #: thread per packet (thousands of parked ch.get threads distort the
    #: very timing being fuzzed); N workers give N-way independent
    #: release reordering, and bursts beyond N queue FIFO behind them
    RELEASE_WORKERS = 16

    def __init__(
        self,
        inspector: "EthernetProxyInspector",
        listen: str,
        upstream: str,
        src_entity: str,
        dst_entity: str,
    ):
        self.inspector = inspector
        self.listen = _addr(listen)
        self.upstream = _addr(upstream)
        self.src_entity = src_entity
        self.dst_entity = dst_entity
        self._sock: Optional[socket.socket] = None
        self._up: Optional[socket.socket] = None
        self._client_addr: Optional[tuple] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._rel_q: _queue.Queue = _queue.Queue()

    @property
    def port(self) -> int:
        assert self._sock is not None
        return self._sock.getsockname()[1]

    def start(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind(self.listen)
        self._up = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._up.bind((self.listen[0], 0))
        conn = self.inspector.next_conn_id()
        for name, sock, fwd, se, de in (
            ("fwd", self._sock, self._send_upstream,
             self.src_entity, self.dst_entity),
            ("rev", self._up, self._send_client,
             self.dst_entity, self.src_entity),
        ):
            threading.Thread(
                target=self._recv_loop, args=(sock, fwd, se, de, conn),
                daemon=True,
                name=f"udp-{name}-{se}->{de}",
            ).start()
        for i in range(self.RELEASE_WORKERS):
            threading.Thread(
                target=self._release_worker, daemon=True,
                name=f"udp-release-{self.src_entity}-{i}",
            ).start()

    def stop(self) -> None:
        self._stop.set()
        for _ in range(self.RELEASE_WORKERS):
            self._rel_q.put(None)
        for s in (self._sock, self._up):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass

    def _send_upstream(self, data: bytes) -> None:
        self._up.sendto(data, self.upstream)

    def _send_client(self, data: bytes) -> None:
        with self._lock:
            addr = self._client_addr
        if addr is not None:
            self._sock.sendto(data, addr)

    def _recv_loop(self, sock: socket.socket, forward, src_entity: str,
                   dst_entity: str, conn_id: int) -> None:
        insp = self.inspector
        while not self._stop.is_set():
            try:
                data, addr = sock.recvfrom(65536)
            except OSError:
                return
            if sock is self._sock:
                with self._lock:
                    self._client_addr = addr
            seg, ch, event = insp.intercept_datagram(
                data, src_entity, dst_entity, conn_id)
            if ch is None:
                try:
                    forward(seg)
                except OSError:
                    pass  # transient send failure must not kill the
                    # whole receive direction (datagrams are lossy)
                continue
            # datagrams release independently as their actions arrive —
            # true per-packet reordering, which a byte stream cannot
            # allow but datagram semantics do (bounded by the worker
            # pool; see RELEASE_WORKERS)
            self._rel_q.put((seg, ch, event, forward))

    def _release_worker(self) -> None:
        insp = self.inspector
        while True:
            item = self._rel_q.get()
            if item is None:
                return
            data, ch, event, forward = item
            try:
                action = ch.get(timeout=insp.action_timeout)
            except _queue.Empty:
                insp.trans.forget(event)
                log.warning("datagram %s->%s: no action in %ss; releasing",
                            event.option.get("src_entity"),
                            event.option.get("dst_entity"),
                            insp.action_timeout)
                action = None
            if isinstance(action, PacketFaultAction):
                insp.count_drop()  # the fault: datagram never forwarded
                continue
            try:
                forward(data)
            except OSError:
                pass


class EthernetProxyInspector:
    def __init__(
        self,
        transceiver: Transceiver,
        entity_id: str = "_nmz_ethernet_inspector",
        parser: Optional[PacketParser] = None,
        action_timeout: Optional[float] = 30.0,
    ):
        self.trans = transceiver
        self.entity_id = entity_id
        self.parser = parser
        # does the parser accept a conn_id (stateful stream parsers do)?
        self._parser_takes_conn = False
        if parser is not None:
            import inspect

            try:
                sig = inspect.signature(parser)
                self._parser_takes_conn = len(sig.parameters) >= 4 or any(
                    p.kind == inspect.Parameter.VAR_POSITIONAL
                    for p in sig.parameters.values()
                )
            except (TypeError, ValueError):
                pass
        self.action_timeout = action_timeout
        self.links: list[ProxyLink] = []
        self.packet_count = 0
        self.drop_count = 0
        self._conn_counter = 0
        self._conn_lock = threading.Lock()
        # reader threads and release workers bump these concurrently;
        # unguarded += lost increments under contention (ADVICE r4 —
        # HookSwitchInspector already guards its counters)
        self._stats_lock = threading.Lock()

    def count_drop(self) -> None:
        with self._stats_lock:
            self.drop_count += 1

    def _count_packet(self) -> None:
        with self._stats_lock:
            self.packet_count += 1

    def next_conn_id(self) -> int:
        with self._conn_lock:
            self._conn_counter += 1
            return self._conn_counter

    def add_link(self, listen: str, upstream: str,
                 src_entity: str, dst_entity: str) -> ProxyLink:
        link = ProxyLink(self, listen, upstream, src_entity, dst_entity)
        self.links.append(link)
        return link

    def add_udp_link(self, listen: str, upstream: str,
                     src_entity: str, dst_entity: str) -> UdpProxyLink:
        """Inspect a UDP flow (per-datagram defer/drop/reorder)."""
        if self.parser is not None and hasattr(self.parser, "segment"):
            # a stream parser buffers partial TCP frames across calls —
            # on datagrams that silently holds/merges packets; refuse
            # rather than lose traffic
            raise ValueError(
                f"{type(self.parser).__name__} is a stream parser and "
                "cannot apply to UDP datagrams; use a chunk-level parser "
                "or none"
            )
        link = UdpProxyLink(self, listen, upstream, src_entity, dst_entity)
        self.links.append(link)
        return link

    def start(self) -> None:
        self.trans.start()
        for link in self.links:
            link.start()

    def stop(self) -> None:
        for link in self.links:
            link.stop()

    # -- the per-message hook (parity: onPacket, ethernet_nfq.go:95-109) --

    def intercept(self, chunk: bytes, src_entity: str, dst_entity: str,
                  conn_id: int = 0):
        """Split ``chunk`` into message segments and post one deferred
        ``PacketEvent`` per segment; returns ``[(bytes, ch, event)]`` in
        stream order for the caller's writer to release (``ch is None``
        = forward without deferring: keepalives and non-semantic
        passthrough).

        Semantic parsers (``StreamParser`` subclasses) segment at message
        boundaries so replay hints are timing-independent; chunk-level
        parsers and raw links defer whole chunks (their hints have no
        sub-chunk structure to preserve)."""
        if self.parser is None:
            segments = [(chunk, "")]
        elif hasattr(self.parser, "segment"):
            segments = self.parser.segment(chunk, src_entity, dst_entity,
                                           conn_id)
        elif self._parser_takes_conn:
            segments = [(chunk, self.parser(chunk, src_entity, dst_entity,
                                            conn_id))]
        else:
            segments = [(chunk, self.parser(chunk, src_entity,
                                            dst_entity))]
        out = []
        for data, hint in segments:
            if hint is None:
                out.append((data, None, None))
                continue
            self._count_packet()
            event = PacketEvent.create(
                self.entity_id, src_entity, dst_entity,
                payload=data[:128], hint=hint,
            )
            ch = self.trans.send_event(event)
            out.append((data, ch, event))
        return out

    def intercept_datagram(self, data: bytes, src_entity: str,
                           dst_entity: str, conn_id: int = 0):
        """One datagram -> at most one deferred event.

        Stream segmentation never applies here (it would buffer bytes of
        "incomplete frames" across datagrams — i.e. silently hold or
        merge packets); chunk-level parsers run per datagram, and a
        ``None`` hint forwards without deferring, same contract as
        :meth:`intercept`."""
        hint = ""
        if self.parser is not None:
            if self._parser_takes_conn:
                hint = self.parser(data, src_entity, dst_entity, conn_id)
            else:
                hint = self.parser(data, src_entity, dst_entity)
            if hint is None:
                return (data, None, None)
        self._count_packet()
        event = PacketEvent.create(
            self.entity_id, src_entity, dst_entity,
            payload=data[:128], hint=hint or "",
        )
        return (data, self.trans.send_event(event), event)


def serve_proxy_inspector(
    transceiver: Transceiver, listen: str, upstream: str,
    parser: Optional[PacketParser] = None, udp: bool = False,
) -> int:
    """CLI entry: proxy one link until interrupted."""
    inspector = EthernetProxyInspector(transceiver, parser=parser)
    add = inspector.add_udp_link if udp else inspector.add_link
    add(listen, upstream, src_entity="client", dst_entity="server")
    inspector.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        inspector.stop()
    return 0
