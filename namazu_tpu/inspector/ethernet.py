"""Ethernet (message) inspector: defer, reorder, and drop network traffic.

Capability parity with /root/reference/nmz/inspector/ethernet (NFQUEUE and
hookswitch backends). TPU-era redesign: the primary backend is a
**userspace TCP proxy** — the system-under-test's nodes are pointed at
proxy ports (one per peer link, e.g. via its own config, DNS, or iptables
REDIRECT), and every chunk that flows through a link becomes a deferred
``PacketEvent`` the policy can delay or drop.

Why a proxy instead of NFQUEUE: it needs no root, no kernel modules and no
external switch, works in any container, and — because interception happens
above TCP — retransmissions never reach the inspector, which removes the
reference's whole TCP-retransmit-suppression problem (its tcpwatcher
exists only because delaying raw segments triggers duplicate delivery,
ethernet_nfq.go:53-56). The cost is per-link (not per-interface)
interception, which matches how the reference's examples are actually
wired (one inspected port per ZooKeeper election/quorum link).

A ``parser`` callback turns raw chunks into semantic replay hints (the
role of the reference's zktraffic-based inspectors, misc/pynmz/inspector/
zookeeper.py) so schedules can be replayed deterministically.
"""

from __future__ import annotations

import queue as _queue
import socket
import threading
from typing import Callable, Optional

from namazu_tpu.inspector.transceiver import Transceiver
from namazu_tpu.signal.action import PacketFaultAction
from namazu_tpu.signal.event import PacketEvent
from namazu_tpu.utils.log import get_logger

log = get_logger("inspector.ethernet")

# (chunk, src, dst[, conn_id]) -> replay hint; "" = no semantic identity
# (still deferred), None = uninteresting traffic, forward immediately
# without deferring (parity: map_packet_to_event returning None,
# misc/pynmz/inspector/ether.py). Stateful parsers (StreamParser
# subclasses) take conn_id so concurrent connections on one link never
# share a parse buffer; plain 3-arg callables are also accepted.
PacketParser = Callable[..., Optional[str]]


def _addr(host_port: str) -> tuple[str, int]:
    host, _, port = host_port.rpartition(":")
    return host or "127.0.0.1", int(port)


class ProxyLink:
    """One inspected TCP link: listen address -> upstream address."""

    def __init__(
        self,
        inspector: "EthernetProxyInspector",
        listen: str,
        upstream: str,
        src_entity: str,
        dst_entity: str,
    ):
        self.inspector = inspector
        self.listen = _addr(listen)
        self.upstream = _addr(upstream)
        self.src_entity = src_entity
        self.dst_entity = dst_entity
        self._server: Optional[socket.socket] = None
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

    @property
    def port(self) -> int:
        assert self._server is not None
        return self._server.getsockname()[1]

    def start(self) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(self.listen)
        srv.listen(16)
        self._server = srv
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"proxy-accept-{self.listen[1]}")
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._server.accept()
            except OSError:
                return
            try:
                up = socket.create_connection(self.upstream, timeout=10)
            except OSError as e:
                log.warning("upstream %s unreachable: %s", self.upstream, e)
                client.close()
                continue
            conn_id = self.inspector.next_conn_id()
            for src, dst, se, de in (
                (client, up, self.src_entity, self.dst_entity),
                (up, client, self.dst_entity, self.src_entity),
            ):
                t = threading.Thread(
                    target=self._pump, args=(src, dst, se, de, conn_id),
                    daemon=True, name=f"proxy-pump-{se}->{de}",
                )
                t.start()
                self._threads.append(t)

    def _pump(self, src: socket.socket, dst: socket.socket,
              src_entity: str, dst_entity: str, conn_id: int = 0) -> None:
        try:
            while not self._stop.is_set():
                try:
                    chunk = src.recv(65536)
                except OSError:
                    break
                if not chunk:
                    break
                if self.inspector.allow(chunk, src_entity, dst_entity,
                                        conn_id):
                    try:
                        dst.sendall(chunk)
                    except OSError:
                        break
                # dropped chunks are simply not forwarded (the fault)
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass


class EthernetProxyInspector:
    def __init__(
        self,
        transceiver: Transceiver,
        entity_id: str = "_nmz_ethernet_inspector",
        parser: Optional[PacketParser] = None,
        action_timeout: Optional[float] = 30.0,
    ):
        self.trans = transceiver
        self.entity_id = entity_id
        self.parser = parser
        # does the parser accept a conn_id (stateful stream parsers do)?
        self._parser_takes_conn = False
        if parser is not None:
            import inspect

            try:
                sig = inspect.signature(parser)
                self._parser_takes_conn = len(sig.parameters) >= 4 or any(
                    p.kind == inspect.Parameter.VAR_POSITIONAL
                    for p in sig.parameters.values()
                )
            except (TypeError, ValueError):
                pass
        self.action_timeout = action_timeout
        self.links: list[ProxyLink] = []
        self.packet_count = 0
        self.drop_count = 0
        self._conn_counter = 0
        self._conn_lock = threading.Lock()

    def next_conn_id(self) -> int:
        with self._conn_lock:
            self._conn_counter += 1
            return self._conn_counter

    def add_link(self, listen: str, upstream: str,
                 src_entity: str, dst_entity: str) -> ProxyLink:
        link = ProxyLink(self, listen, upstream, src_entity, dst_entity)
        self.links.append(link)
        return link

    def start(self) -> None:
        self.trans.start()
        for link in self.links:
            link.start()

    def stop(self) -> None:
        for link in self.links:
            link.stop()

    # -- the per-chunk hook (parity: onPacket, ethernet_nfq.go:95-109) ---

    def allow(self, chunk: bytes, src_entity: str, dst_entity: str,
              conn_id: int = 0) -> bool:
        """Defer ``chunk``; returns False when the policy drops it."""
        self.packet_count += 1
        if self.parser is None:
            hint = ""
        elif self._parser_takes_conn:
            hint = self.parser(chunk, src_entity, dst_entity, conn_id)
        else:
            hint = self.parser(chunk, src_entity, dst_entity)
        if hint is None:
            return True
        event = PacketEvent.create(
            self.entity_id, src_entity, dst_entity,
            payload=chunk[:128], hint=hint,
        )
        ch = self.trans.send_event(event)
        try:
            action = ch.get(timeout=self.action_timeout)
        except _queue.Empty:
            self.trans.forget(event)
            log.warning("packet %s->%s: no action in %ss; releasing",
                        src_entity, dst_entity, self.action_timeout)
            return True
        if isinstance(action, PacketFaultAction):
            self.drop_count += 1
            return False
        return True


def serve_proxy_inspector(
    transceiver: Transceiver, listen: str, upstream: str,
    parser: Optional[PacketParser] = None,
) -> int:
    """CLI entry: proxy one link until interrupted."""
    inspector = EthernetProxyInspector(transceiver, parser=parser)
    inspector.add_link(listen, upstream, src_entity="client",
                       dst_entity="server")
    inspector.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        inspector.stop()
    return 0
