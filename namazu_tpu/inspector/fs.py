"""Filesystem inspector: defer and fault-inject filesystem operations.

Capability parity with /root/reference/nmz/inspector/fs/fs.go:22-183 (a
hookfs/FUSE passthrough with pre/post hooks). TPU-era redesign: the hook
protocol is transport-agnostic —

* :class:`FsInspector` — the hook core: builds a ``FilesystemEvent`` per
  intercepted op, blocks until the policy answers, translates a
  ``FilesystemFaultAction`` into EIO (parity: commonHook, fs.go:56-74);
* :class:`InterposedFs` — library-level interposition for testees that can
  route file I/O through a Python object (also the in-proc test fake the
  reference keeps for every layer);
* the C++ LD_PRELOAD interposer under ``native/`` speaks the guest-agent
  protocol and reuses the same event classes for testees that cannot
  (no FUSE mount or root required);
* a FUSE mount backend is gated: this image ships no libfuse headers or
  Python FUSE binding, so ``serve_fs_inspector`` reports the gap cleanly.

Hooked ops (parity fs.go:77-183): post-read, post-opendir, pre-write,
pre-mkdir, pre-rmdir, pre-fsync.
"""

from __future__ import annotations

import errno
import os
import queue as _queue
from typing import Optional

from namazu_tpu.inspector.transceiver import Transceiver
from namazu_tpu.signal.action import FilesystemFaultAction
from namazu_tpu.signal.event import FilesystemEvent, FilesystemOp
from namazu_tpu.utils.log import get_logger

log = get_logger("inspector.fs")


class FsInspector:
    """The hook core shared by every interposition backend."""

    def __init__(
        self,
        transceiver: Transceiver,
        entity_id: str = "_nmz_fs_inspector",
        action_timeout: Optional[float] = 30.0,
    ):
        self.trans = transceiver
        self.entity_id = entity_id
        self.action_timeout = action_timeout
        self.hook_count = 0
        self.fault_count = 0

    def start(self) -> None:
        self.trans.start()

    def hook(self, op: FilesystemOp, path: str) -> None:
        """Block the calling operation until the policy releases it.

        Raises ``OSError(EIO)`` when the policy injects a filesystem fault
        (parity: FilesystemFaultAction => -EIO, fs.go:62-71).
        """
        self.hook_count += 1
        event = FilesystemEvent.create(self.entity_id, op, path)
        ch = self.trans.send_event(event)
        try:
            action = ch.get(timeout=self.action_timeout)
        except _queue.Empty:
            self.trans.forget(event)
            log.warning("fs hook %s %s: no action within %ss; releasing",
                        op.value, path, self.action_timeout)
            return
        if isinstance(action, FilesystemFaultAction):
            self.fault_count += 1
            raise OSError(errno.EIO, os.strerror(errno.EIO), path)


class InterposedFs:
    """Library-level interposition over a root directory.

    Each method mirrors one hooked operation of the reference's FUSE layer
    (fs.go:77-183): reads/opendirs hook *after* the real op, writes/mkdirs/
    rmdirs/fsyncs hook *before* it — same pre/post split, so fault
    injection cannot corrupt reads but can prevent persistence.
    """

    def __init__(self, root: str, inspector: FsInspector):
        self.root = os.path.abspath(root)
        self.inspector = inspector

    def _real(self, path: str) -> str:
        p = os.path.normpath(os.path.join(self.root, path.lstrip("/")))
        if not p.startswith(self.root):
            raise ValueError(f"path escapes root: {path}")
        return p

    # -- post-hooked ops -------------------------------------------------

    def read(self, path: str) -> bytes:
        with open(self._real(path), "rb") as f:
            data = f.read()
        self.inspector.hook(FilesystemOp.POST_READ, path)
        return data

    def listdir(self, path: str) -> list[str]:
        entries = os.listdir(self._real(path))
        self.inspector.hook(FilesystemOp.POST_OPENDIR, path)
        return entries

    # -- pre-hooked ops --------------------------------------------------

    def write(self, path: str, data: bytes) -> None:
        self.inspector.hook(FilesystemOp.PRE_WRITE, path)
        with open(self._real(path), "wb") as f:
            f.write(data)

    def mkdir(self, path: str) -> None:
        self.inspector.hook(FilesystemOp.PRE_MKDIR, path)
        os.mkdir(self._real(path))

    def rmdir(self, path: str) -> None:
        self.inspector.hook(FilesystemOp.PRE_RMDIR, path)
        os.rmdir(self._real(path))

    def fsync(self, path: str) -> None:
        self.inspector.hook(FilesystemOp.PRE_FSYNC, path)
        fd = os.open(self._real(path), os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)


def serve_fs_inspector(
    transceiver: Transceiver, mount_point: str, original_dir: str
) -> int:
    """FUSE-mount backend — gated.

    This image has no libfuse development headers and no Python FUSE
    binding, so the mount backend cannot be built here. Use the
    LD_PRELOAD interposer (native/fs_interpose) or :class:`InterposedFs`.
    """
    raise NotImplementedError(
        "FUSE mount backend unavailable: no libfuse headers/binding in this "
        "environment. Use the native LD_PRELOAD interposer "
        "(native/fs_interpose) or InterposedFs for library-level hooks."
    )
