"""REST transceiver: the HTTP client side.

Parity: /root/reference/nmz/inspector/transceiver/resttransceiver.go —
``POST`` events non-blockingly; one receive thread long-polls
``GET /actions/{entity}``, acknowledges with ``DELETE``, and dispatches the
action to the per-event waiter queue; linear backoff on transport errors
(resttransceiver.go:158-188).

Event-plane fast path (doc/performance.md), on top of the parity wire:

* **persistent keep-alive connections** — one ``http.client`` connection
  for the outbound (POST) side and one owned by the receive thread, each
  reused across requests/long-poll cycles with a single transparent
  reconnect on a stale socket, instead of a fresh TCP handshake per
  request;
* **client-side event coalescing** — with ``use_batch`` (default),
  ``_post`` buffers events and flushes them as one
  ``POST /events/{entity}/batch`` when the buffer reaches ``batch_max``
  OR ``flush_window`` seconds after the first buffered event, so
  single-event latency is bounded by the window. ``flush_window=0``
  (the default) flushes synchronously on the caller thread: same wire
  batching, zero added latency, and transport errors still raise into
  inspector code exactly like the per-event path;
* **batched receive** — ``GET /actions/{entity}?batch=N`` drains up to N
  actions per long-poll round trip, acknowledged with ONE multi-uuid
  ``DELETE``.

The coalescing/linger windows default to 0: a fuzzer's transport must
not add latency the policy didn't choose (injected delays ARE the
product), so out of the box the batch wire only amortizes what is
already queued. Throughput deployments opt into windows explicitly —
``bench.py --pipeline`` shows the trade (doc/performance.md).

``use_batch=False`` speaks the exact pre-batch per-event wire (POST per
event, single-action GET, per-uuid DELETE) — still over the persistent
connections — for orchestrators that predate the batch routes.

Survivability (doc/robustness.md "Chaos plane"): a 429/503 with
``Retry-After`` (the endpoint's bounded-ingress backpressure) rides the
bounded retry honoring the server's requested delay (capped +
jittered) instead of raising into inspector code; posted-but-unanswered
deferred events are kept in a bounded ring and **replayed** when the
receive loop recovers from a transport error — the signature of an
orchestrator restart — which the server-side dedupe (journal-seeded on
recovery) makes idempotent. The ``wire.*`` chaos fault points
(drop/dup/delay/lost-reply/sever) are seamed through the POST and poll
paths and cost one no-op check when chaos is disabled.
"""

from __future__ import annotations

import http.client
import json
import socket
import threading
import time
import urllib.error
from typing import List, Optional
from urllib.parse import urlsplit

from namazu_tpu import chaos, obs, tenancy
from namazu_tpu.endpoint.rest import API_ROOT, TABLE_VERSION_HEADER
from namazu_tpu.signal import binary as _binary
from namazu_tpu.inspector import edge as _edge_mod
from namazu_tpu.inspector.edge import EdgeDispatcher
from namazu_tpu.inspector.transceiver import (Transceiver,
                                              UnackedReplayMixin)
from namazu_tpu.signal.action import Action
from namazu_tpu.signal.base import SignalError, signal_from_jsonable
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.log import get_logger
from namazu_tpu.utils.retry import retry_call

log = get_logger("transceiver.rest")

#: transport errors worth retrying / backing off on: socket-level
#: failures (URLError is an OSError subclass) and HTTP-protocol hiccups
#: from a dropped keep-alive peer
_TRANSPORT_ERRORS = (urllib.error.URLError, OSError,
                     http.client.HTTPException)


class TransientHTTPStatus(OSError):
    """A retryable response status (5xx-class / overload): the old
    urllib path raised HTTPError (a URLError subclass) for these, so
    they rode the bounded POST retry — an OSError subclass keeps them
    inside ``_TRANSPORT_ERRORS``. ``retry_after`` carries the server's
    Retry-After header (seconds) when it sent one — a 429 from the
    endpoint's bounded ingress tells the client exactly when to come
    back, and the bounded retry honors it (capped + jittered,
    utils/retry.py) instead of guessing."""

    def __init__(self, msg: str, retry_after: Optional[float] = None):
        super().__init__(msg)
        self.retry_after = retry_after


def _check_post_status(status: int, what: str,
                       retry_after: Optional[float] = None) -> None:
    if status == 200:
        return
    if status >= 500 or status in (408, 429):
        raise TransientHTTPStatus(f"{what} -> {status}",
                                  retry_after=retry_after)
    raise RuntimeError(f"{what} -> {status}")


def _retry_after_hint(exc: BaseException) -> Optional[float]:
    """The bounded retry's delay_hint: honor a server-sent Retry-After
    (observed into ``nmz_transport_retry_after_seconds``)."""
    hint = getattr(exc, "retry_after", None)
    if hint is None:
        return None
    obs.transport_retry_after(float(hint))
    return float(hint)


class _KeepAliveConn:
    """One persistent HTTP/1.1 connection to the orchestrator.

    NOT thread-safe — each owner (the post path under its lock, the
    receive thread) holds its own instance. A request on a stale
    keep-alive socket (server restarted, idle timeout) gets ONE
    transparent reconnect+replay; every request here is idempotent by
    construction (event POSTs dedupe server-side, GET peeks, DELETE acks
    report already-gone uuids as ``missing``)."""

    def __init__(self, base_url: str, timeout: float, abort=None,
                 extra_headers: Optional[dict] = None):
        #: headers added to EVERY request (the tenancy plane's
        #: X-Nmz-Run namespace piggyback; doc/tenancy.md)
        self.extra_headers = dict(extra_headers or {})
        parts = urlsplit(base_url)
        if parts.scheme not in ("http", "https"):
            raise ValueError(f"unsupported scheme {parts.scheme!r}")
        self._https = parts.scheme == "https"
        self._host = parts.hostname or "127.0.0.1"
        self._port = parts.port
        self._timeout = timeout
        # abort() true = owner is shutting down: a socket error then
        # propagates instead of triggering the transparent replay (which
        # on the long-poll path would block the shutdown for a whole
        # poll window)
        self._abort = abort
        self._conn: Optional[http.client.HTTPConnection] = None
        #: Retry-After (seconds) from the most recent response, None
        #: when absent — read by the POST path right after request()
        #: so a 429's advice reaches the bounded retry
        self.last_retry_after: Optional[float] = None
        #: the zero-RTT table-version piggyback from the most recent
        #: response (doc/performance.md), None when the server has no
        #: table plane — routed to the edge dispatcher's staleness check
        self.last_table_version: Optional[int] = None
        #: codec negotiation state (doc/performance.md "Binary wire"):
        #: True once any response advertised X-Nmz-Codec-Accept; reset
        #: on close/reconnect so a restarted (possibly older) server is
        #: re-probed with JSON first — negotiation is per connection
        self.accepts_binary = False
        self._binary_counted = False
        #: the codec of the most recent response BODY (X-Nmz-Codec)
        self.last_codec: str = _binary.CODEC_JSON
        #: X-Nmz-Codec-Error of the most recent response ("garbled" =
        #: damaged in flight, retry in place; anything else on a binary
        #: 400 = downgrade)
        self.last_codec_error: Optional[str] = None
        #: bumped every time a fresh socket is established — how the
        #: receive loop notices a TRANSPARENT mid-call reconnect (the
        #: peer may be a RESTARTED orchestrator that never saw our
        #: in-flight events, and the reconnect-and-replay window must
        #: arm even when no error escaped this wrapper)
        self.generation = 0

    def request(self, method: str, path: str,
                body: Optional[bytes] = None,
                codec: str = _binary.CODEC_JSON):
        """Issue one request; returns ``(status, body_bytes)``.
        ``codec`` names the body's encoding and asks for the response
        in kind (the X-Nmz-Codec header)."""
        headers = {"Connection": "keep-alive"}
        headers.update(self.extra_headers)
        if codec == _binary.CODEC_BINARY:
            headers[_binary.CODEC_HEADER] = _binary.CODEC_BINARY
            if body is not None:
                headers["Content-Type"] = _binary.CONTENT_TYPE_BINARY
        elif body is not None:
            headers["Content-Type"] = "application/json"
        last_exc: Optional[BaseException] = None
        for attempt in (0, 1):
            if self._abort is not None and self._abort():
                # owner is shutting down: do not open a FRESH connection
                # (a post-close request would park in a new long-poll
                # and outlive the shutdown join)
                raise OSError("connection owner is shutting down")
            # local reference: close() from the owner's shutdown path
            # nulls the attribute concurrently; the socket error that
            # close raises in us must surface as OSError, not as an
            # AttributeError on a vanished connection object
            conn = self._conn
            if conn is None:
                cls = (http.client.HTTPSConnection if self._https
                       else http.client.HTTPConnection)
                conn = self._conn = cls(self._host, self._port,
                                        timeout=self._timeout)
                self.generation += 1
                try:
                    conn.connect()
                    # disable Nagle: the wire pattern here is small
                    # request, wait for reply — exactly what Nagle +
                    # delayed ACK turns into per-request stalls
                    conn.sock.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                except (OSError, AttributeError):
                    pass  # request() below surfaces real failures
            try:
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                raw_ra = resp.getheader("Retry-After")
                try:
                    self.last_retry_after = (None if raw_ra is None
                                             else max(0.0, float(raw_ra)))
                except ValueError:
                    self.last_retry_after = None  # HTTP-date form: skip
                raw_tv = resp.getheader(TABLE_VERSION_HEADER)
                try:
                    self.last_table_version = (None if raw_tv is None
                                               else int(raw_tv))
                except ValueError:
                    self.last_table_version = None
                if resp.getheader(_binary.CODEC_ACCEPT_HEADER) \
                        == _binary.CODEC_BINARY:
                    if not self.accepts_binary \
                            and not self._binary_counted:
                        # one negotiation per connection settles on
                        # binary the moment the server advertises it
                        obs.codec_negotiated(_binary.CODEC_BINARY)
                        self._binary_counted = True
                    self.accepts_binary = True
                self.last_codec = (resp.getheader(_binary.CODEC_HEADER)
                                   or _binary.CODEC_JSON)
                self.last_codec_error = resp.getheader(
                    "X-Nmz-Codec-Error")
                if resp.will_close:
                    self.close()
                return resp.status, data
            except (OSError, http.client.HTTPException) as e:
                # stale socket: reconnect once and replay; a second
                # failure is a real transport error for the caller's
                # backoff machinery
                self.close()
                last_exc = e
                if self._abort is not None and self._abort():
                    raise
        raise last_exc  # type: ignore[misc]

    def close(self) -> None:
        conn, self._conn = self._conn, None
        # a reconnect re-learns the peer's codec from its adverts (the
        # successor on this address may predate the binary wire)
        self.accepts_binary = False
        self._binary_counted = False
        if conn is not None:
            sock = getattr(conn, "sock", None)
            if sock is not None:
                # a plain close() does NOT wake a thread blocked in
                # recv() on this socket (the fd stays open until the
                # read returns); shutdown() does — this is what breaks
                # an in-flight long-poll at owner shutdown
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            try:
                conn.close()
            except Exception:
                pass


class RestTransceiver(UnackedReplayMixin, Transceiver):
    def __init__(self, entity_id: str, orchestrator_url: str,
                 backoff_step: float = 0.5, backoff_max: float = 5.0,
                 post_attempts: int = 4, use_batch: bool = True,
                 batch_max: int = 64, flush_window: float = 0.0,
                 poll_batch: Optional[int] = None,
                 poll_linger: float = 0.0,
                 edge: bool = False,
                 backhaul_window: float = 0.05,
                 codec: str = "auto",
                 edge_shards: int = 0,
                 shard_pool=None,
                 run_ns: str = ""):
        super().__init__(entity_id)
        #: tenancy namespace (doc/tenancy.md): rides every request as
        #: the X-Nmz-Run header; "" = the process-default namespace
        #: (the pre-tenancy wire, byte-identical)
        self.run_ns = str(run_ns or "")
        # the wire codec preference (doc/performance.md "Binary wire +
        # sharded edge"): "auto" upgrades to the binary codec once the
        # server advertises it (JSON until then — pre-binary peers are
        # untouched), "json" pins the legacy wire, "binary" sends
        # binary from the first request (known-capable server). A
        # binary 400 that is NOT a garbled-in-flight reply downgrades
        # this transceiver to JSON permanently, loss-free.
        self.codec_pref = codec
        self._codec_down = False
        self.base = orchestrator_url.rstrip("/") + API_ROOT
        self.backoff_step = backoff_step
        self.backoff_max = backoff_max
        self.post_attempts = post_attempts
        self.use_batch = use_batch
        self.batch_max = max(1, int(batch_max))
        self.flush_window = max(0.0, float(flush_window))
        # how many actions one long-poll round trip may drain, and how
        # long the server may linger after the first action to fill the
        # batch (seconds; latency <-> occupancy knob)
        self.poll_batch = (self.batch_max if poll_batch is None
                           else max(1, int(poll_batch)))
        self.poll_linger = max(0.0, float(poll_linger))
        self._path = urlsplit(self.base).path
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # outbound connection: shared by caller threads (and the flush
        # thread), serialized by _conn_lock; the receive thread owns its
        # own connection so a long-poll never blocks a POST
        ns_headers = ({tenancy.RUN_HEADER: self.run_ns}
                      if self.run_ns else None)
        self._post_conn = _KeepAliveConn(self.base, timeout=30.0,
                                         extra_headers=ns_headers)
        self._recv_conn = _KeepAliveConn(self.base, timeout=65.0,
                                         abort=self._stop.is_set,
                                         extra_headers=ns_headers)
        self._conn_lock = threading.Lock()
        # coalescing buffer (use_batch): _buf_cond guards the buffer,
        # _flush_lock serializes whole flushes so concurrent callers
        # cannot reorder chunks on the wire
        self._buf: List[Event] = []
        self._buf_since = 0.0
        self._buf_cond = threading.Condition()
        self._flush_lock = threading.Lock()
        self._flush_thread: Optional[threading.Thread] = None
        # reconnect-and-replay (doc/robustness.md): deferred events
        # POSTed but not yet answered by an action. When the receive
        # loop recovers from a transport error — the signature of an
        # orchestrator restart — these are re-POSTed: a restarted
        # endpoint accepts the ones its journal recovery seeded into
        # its dedupe ring as duplicates (idempotent), and the ones the
        # old process never journaled as fresh, so nothing is lost
        # either way. Bounded: oldest evicted past the cap.
        self._init_unacked()
        self._replay_armed = False
        # zero-RTT edge dispatch (doc/performance.md): opt-in; dormant
        # until the orchestrator publishes a table (the version
        # piggyback on any batch/poll response activates it), so
        # non-table policies and cold-start windows run the exact
        # central wire above
        self._edge = None
        if edge:
            if shard_pool is not None or edge_shards >= 1:
                # per-core shards: entities hashed across the pool's N
                # engines (doc/performance.md "Binary wire + sharded
                # edge"); edge_shards >= 1 joins the process-global
                # pool (1 = a single shared shard, the bench's
                # edge_shards=1 semantics), 0 = one dispatcher per
                # entity (rounds 7/8)
                pool = (shard_pool if shard_pool is not None
                        else _edge_mod.shared_pool(
                            edge_shards, backhaul_window))
                self._edge = pool.register(
                    entity_id,
                    deliver=self.dispatch_action,
                    deliver_many=self.dispatch_actions,
                    fetch_table=self._fetch_table_once,
                    send_backhaul=self._post_backhaul_once)
            else:
                self._edge = EdgeDispatcher(
                    entity_id,
                    deliver=self.dispatch_action,
                    deliver_many=self.dispatch_actions,
                    fetch_table=self._fetch_table_once,
                    send_backhaul=self._post_backhaul_once,
                    backhaul_window=backhaul_window)

    # -- outbound --------------------------------------------------------

    def _post(self, event: Event) -> None:
        """Queue/POST the event. Per-event mode rides out transient
        transport hiccups with bounded backoff + jitter (exhausted
        retries still raise — the orchestrator is genuinely gone).
        Batch mode appends to the coalescing buffer; the flush (size
        cap, window expiry, or synchronous when ``flush_window=0``)
        carries the same retry policy, and a replayed batch whose 200
        was lost dedupes server-side."""
        if self._edge is not None and self._edge.try_dispatch(event):
            # zero-RTT: decided + released locally against the
            # published table; the trace record rides the async
            # backhaul instead of this POST
            return
        if not self.use_batch:
            retry_call(
                lambda: self._post_once(event),
                exceptions=_TRANSPORT_ERRORS,
                attempts=max(1, self.post_attempts),
                base=self.backoff_step,
                cap=self.backoff_max,
                # an interruptible sleep: shutdown() aborts the backoff
                sleep=self._stop.wait,
                delay_hint=_retry_after_hint,
                on_retry=lambda e, n, d: log.debug(
                    "event POST failed (%s); retry %d in %.2fs", e, n, d),
            )
            return
        if self.flush_window <= 0:
            # window 0: post THIS event directly (a batch of one over
            # the batch wire) instead of routing through the shared
            # buffer — a concurrent sender's failing flush could
            # otherwise drain this event and swallow its error, where
            # the per-event path would have raised into this caller
            retry_call(
                lambda: self._post_batch_once([event], event.entity_id),
                exceptions=_TRANSPORT_ERRORS,
                attempts=max(1, self.post_attempts),
                base=self.backoff_step,
                cap=self.backoff_max,
                sleep=self._stop.wait,
                delay_hint=_retry_after_hint,
                on_retry=lambda e, n, d: log.debug(
                    "batch POST failed (%s); retry %d in %.2fs",
                    e, n, d),
            )
            return
        with self._buf_cond:
            self._buf.append(event)
            if len(self._buf) == 1:
                self._buf_since = time.monotonic()
            n = len(self._buf)
            self._buf_cond.notify()
        if n >= self.batch_max:
            # synchronous flush at the size cap: backpressure on the
            # sending thread
            self._flush()
        else:
            self._ensure_flusher()

    def _post_once(self, event: Event, ignore_stop: bool = False) -> None:
        if self._stop.is_set() and not ignore_stop:
            return  # shutting down: don't fight over a dying server
        if self._wire_fault([event]):
            return
        path = f"{self._path}/events/{event.entity_id}/{event.uuid}"
        body = event.to_json().encode()
        with self._conn_lock:
            t0 = time.perf_counter()
            status, _ = self._post_conn.request("POST", path, body=body)
            obs.transport_rtt("post", time.perf_counter() - t0)
            retry_after = self._post_conn.last_retry_after
            if status == 200 \
                    and chaos.decide("wire.post.dup") is not None:
                # duplicate the POST on the wire: the endpoint's dedupe
                # ring must absorb it
                self._post_conn.request("POST", path, body=body)
        _check_post_status(status, f"POST {path}", retry_after=retry_after)
        self._note_posted([event])
        if chaos.decide("wire.post.lost_reply") is not None:
            # poison the 200 into a lost reply: the caller's bounded
            # retry replays, and the replay must dedupe server-side
            raise TransientHTTPStatus(f"chaos: 200 for POST {path} "
                                      "lost in flight")

    def _wire_fault(self, events: List[Event]) -> bool:
        """Pre-wire chaos seams shared by both POST paths: True = the
        send was dropped (the events never reach the wire — the lost-
        event case the invariant harness accounts against the plan's
        fired count)."""
        fault = chaos.decide("wire.post.delay")
        if fault is not None:
            self._stop.wait(float(fault.get("delay_s", 0.05)))
        if chaos.decide("wire.post.drop") is not None:
            log.debug("chaos: dropped %d event(s) pre-wire", len(events))
            return True
        return False

    def _wire_codec(self, conn: _KeepAliveConn) -> str:
        """The codec for the next request on ``conn``."""
        if self._codec_down or self.codec_pref == _binary.CODEC_JSON \
                or self.codec_pref == "json":
            return _binary.CODEC_JSON
        if self.codec_pref == "binary" \
                or self.codec_pref == _binary.CODEC_BINARY \
                or conn.accepts_binary:
            return _binary.CODEC_BINARY
        return _binary.CODEC_JSON

    @staticmethod
    def _encode_body(value, codec: str) -> bytes:
        if codec == _binary.CODEC_BINARY:
            data = _binary.dumps(value)
            if chaos.decide("wire.binary.garble") is not None:
                # corrupt the payload in flight: the server must 400 it
                # tagged "garbled" and the bounded retry resends clean
                data = bytearray(data)
                data[len(data) // 2] ^= 0xFF
                data = bytes(data)
            return data
        return json.dumps(value).encode()

    @staticmethod
    def _decode_body(conn: _KeepAliveConn, body: bytes):
        if conn.last_codec == _binary.CODEC_BINARY:
            return _binary.loads(body)
        return json.loads(body)

    def _binary_400(self, conn: _KeepAliveConn, codec: str,
                    what: str) -> bool:
        """Handle a 400 answered to a binary request: garbled-in-flight
        raises the retryable class (stay binary); anything else means
        the peer cannot take this codec — downgrade to JSON for the
        rest of this transceiver's life and tell the caller to resend.
        Returns True when the caller should retry the request in JSON,
        False when this was not a binary-codec 400 at all."""
        if codec != _binary.CODEC_BINARY:
            return False
        if conn.last_codec_error == "garbled":
            raise TransientHTTPStatus(
                f"{what}: binary payload damaged in flight")
        if not self._codec_down:
            self._codec_down = True
            log.warning("server refused the binary codec (%s); "
                        "downgrading to JSON", what)
        return True

    def _ensure_flusher(self) -> None:
        if self._flush_thread is not None or self._stop.is_set():
            return
        with self._flush_lock:
            if self._flush_thread is None and not self._stop.is_set():
                self._flush_thread = threading.Thread(
                    target=self._flush_loop,
                    name=f"rest-flush-{self.entity_id}",
                    daemon=True,
                )
                self._flush_thread.start()

    def _flush_loop(self) -> None:
        """Window clock: sleep until ``flush_window`` after the first
        buffered event, then flush whatever accumulated. Events the
        size-cap path already flushed synchronously just leave an empty
        buffer behind — flushing nothing is free."""
        while True:
            with self._buf_cond:
                while not self._buf and not self._stop.is_set():
                    self._buf_cond.wait(0.5)
                if self._stop.is_set():
                    return  # shutdown() drains the buffer after joining
                since = self._buf_since
            delay = since + self.flush_window - time.monotonic()
            if delay > 0 and self._stop.wait(delay):
                return
            try:
                self._flush()
            except Exception:
                # the async path cannot raise into inspector code; the
                # events are lost and their waiters will time out
                log.exception(
                    "batch flush failed after retries; events dropped")

    def _flush(self) -> None:
        """Drain the buffer onto the wire in ``batch_max`` chunks, in
        order (``_flush_lock`` keeps concurrent flushers from
        interleaving their chunks). Events are grouped by their OWN
        entity id — the per-event wire routes by ``event.entity_id``,
        so a transceiver may legitimately carry a neighbor entity's
        events, and the batch route requires every item in a request to
        match its url entity."""
        with self._flush_lock:
            with self._buf_cond:
                batch, self._buf = self._buf, []
            by_entity: "dict[str, List[Event]]" = {}
            for event in batch:
                by_entity.setdefault(event.entity_id, []).append(event)
            for entity, events in by_entity.items():
                for i in range(0, len(events), self.batch_max):
                    chunk = events[i:i + self.batch_max]
                    retry_call(
                        lambda c=chunk, e=entity:
                            self._post_batch_once(c, e),
                        exceptions=_TRANSPORT_ERRORS,
                        attempts=max(1, self.post_attempts),
                        base=self.backoff_step,
                        cap=self.backoff_max,
                        sleep=self._stop.wait,
                        delay_hint=_retry_after_hint,
                        on_retry=lambda e, n, d: log.debug(
                            "batch POST failed (%s); retry %d in %.2fs",
                            e, n, d),
                    )

    def _post_batch_once(self, chunk: List[Event],
                         entity: Optional[str] = None) -> None:
        if self._wire_fault(chunk):
            return
        entity = self.entity_id if entity is None else entity
        codec = self._wire_codec(self._post_conn)
        body = self._encode_body([ev.to_jsonable() for ev in chunk],
                                 codec)
        path = f"{self._path}/events/{entity}/batch"
        with self._conn_lock:
            t0 = time.perf_counter()
            status, resp_body = self._post_conn.request(
                "POST", path, body=body, codec=codec)
            obs.transport_rtt("post_batch", time.perf_counter() - t0)
            retry_after = self._post_conn.last_retry_after
            table_version = self._post_conn.last_table_version
            if status == 200 \
                    and chaos.decide("wire.post.dup") is not None:
                self._post_conn.request("POST", path, body=body,
                                        codec=codec)
        obs.wire_bytes(codec, "post_batch",
                       len(body) + len(resp_body or b""))
        if status == 400 and self._binary_400(
                self._post_conn, codec, f"POST {path}"):
            return self._post_batch_once(chunk, entity)
        if status in (400, 404):
            # a pre-batch orchestrator has no .../batch route (its
            # per-event route reads "batch" as a uuid and 400s the list
            # body): deliver this chunk per-event and stay legacy.
            # ignore_stop: these events were already accepted into the
            # buffer, and this may be shutdown's final flush — a silent
            # early-return would drop them while reporting success
            self._downgrade_to_legacy(f"batch POST -> {status}")
            for event in chunk:
                self._post_once(event, ignore_stop=True)
            return
        _check_post_status(status, f"POST {path}", retry_after=retry_after)
        self._note_posted(chunk)
        obs.event_batch("flush", len(chunk))
        self._note_table_version(table_version)
        if chaos.decide("wire.post.lost_reply") is not None:
            raise TransientHTTPStatus(f"chaos: 200 for POST {path} "
                                      "lost in flight")

    def _post_many(self, events) -> None:
        """Batch hook (``send_events``): the central subset rides the
        wire FIRST — its POSTs can fail, and a replayed burst dedupes
        server-side — then the edge decides the eligible subset in one
        vectorized pass, releasing only after the fallible wire work
        succeeded (a caller retrying a raised burst can never
        re-release an already-decided event). Whatever the edge still
        rejects (table withdrawn in between) falls back to the central
        wire, loss-free."""
        events = list(events)
        eligible = []
        if self._edge is not None:
            eligible, events = self._edge.partition(events)
        for event in events:
            self._post(event)
        if eligible:
            for event in self._edge.try_dispatch_batch(eligible):
                self._post(event)

    # -- zero-RTT edge dispatch (doc/performance.md) ---------------------

    @property
    def edge_active(self) -> bool:
        """True while this transceiver decides events locally against
        a held published table."""
        return self._edge is not None and self._edge.active

    def sync_table(self) -> Optional[int]:
        """Force one table fetch+install (tests/bench priming; normal
        operation activates lazily off the version piggyback). Returns
        the installed version, or None (central fallback)."""
        if self._edge is None:
            return None
        return self._edge.sync()

    def _note_table_version(self, version: Optional[int]) -> None:
        if self._edge is not None:
            self._edge.note_server_version(version)

    def _fetch_table_once(self):
        """One ``GET /policy/table``: ``(version, doc_or_None)``."""
        path = f"{self._path}/policy/table"
        codec = self._wire_codec(self._post_conn)
        with self._conn_lock:
            status, body = self._post_conn.request("GET", path,
                                                   codec=codec)
            version = self._post_conn.last_table_version
        obs.wire_bytes(codec, "table", len(body or b""))
        if status == 200:
            doc = self._decode_body(self._post_conn, body)
            return int(doc.get("version", version or 0)), doc
        if status in (204, 404):
            # 204 = no publishable table at this version; 404 = a
            # pre-table orchestrator — both mean central dispatch
            return int(version or 0), None
        raise RuntimeError(f"GET {path} -> {status}")

    def _post_backhaul_once(self, entity: str,
                            items: List[dict]) -> Optional[int]:
        """POST one backhaul chunk; returns the server's current table
        version from the reply (the edge's staleness signal). Raises on
        failure — the dispatcher re-queues and retries, and a replayed
        chunk whose 200 was lost dedupes server-side."""
        codec = self._wire_codec(self._post_conn)
        body = self._encode_body({"items": items}, codec)
        path = f"{self._path}/events/{entity}/backhaul"
        with self._conn_lock:
            t0 = time.perf_counter()
            status, raw = self._post_conn.request("POST", path,
                                                  body=body, codec=codec)
            obs.transport_rtt("backhaul", time.perf_counter() - t0)
            retry_after = self._post_conn.last_retry_after
        obs.wire_bytes(codec, "backhaul", len(body) + len(raw or b""))
        if status == 400 and self._binary_400(
                self._post_conn, codec, f"POST {path}"):
            return self._post_backhaul_once(entity, items)
        _check_post_status(status, f"POST {path}", retry_after=retry_after)
        try:
            doc = self._decode_body(self._post_conn, raw)
            return int(doc.get("table_version"))
        except (TypeError, ValueError):
            return None

    # -- inbound ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._receive_loop,
                name=f"rest-recv-{self.entity_id}",
                daemon=True,
            )
            self._thread.start()

    def shutdown(self, join_timeout: float = 5.0) -> None:
        """Stop and JOIN the worker threads (bounded): setting the flag
        alone let an in-flight long-poll outlive shutdown and race the
        next run's transceiver for the same entity's actions. Events
        still in the coalescing buffer get one final best-effort
        flush."""
        self._stop.set()
        with self._buf_cond:
            self._buf_cond.notify_all()
        ft = self._flush_thread
        if ft is not None and ft is not threading.current_thread():
            ft.join(timeout=join_timeout)
        try:
            self._flush()
        except Exception:
            log.debug("final flush failed during shutdown", exc_info=True)
        if self._edge is not None:
            # flush pending backhaul BEFORE the connections close: an
            # edge-decided event whose trace record is still buffered
            # must reach the flight recorder (the same loss-free
            # guarantee the coalescing buffer gets above)
            try:
                self._edge.shutdown()
            except Exception:
                log.debug("edge shutdown flush failed", exc_info=True)
        t = self._thread
        if t is not None and t is not threading.current_thread():
            # break an in-flight long-poll: closing the socket under the
            # receive thread makes its blocked read raise, and the loop
            # exits on the stop flag instead of waiting out the server's
            # poll window
            self._recv_conn.close()
            t.join(timeout=join_timeout)
            if t.is_alive():
                log.warning("receive thread still in a long-poll after "
                            "%.1fs; abandoning it (daemon)", join_timeout)
        with self._conn_lock:
            self._post_conn.close()

    def _receive_loop(self) -> None:
        backoff = 0.0
        last_gen: Optional[int] = None
        while not self._stop.is_set():
            try:
                actions = self._poll_once()
                backoff = 0.0
            # SignalError: a malformed/version-skewed 200 body (unknown
            # action class from a newer orchestrator) must back off and
            # retry like any other bad response, not kill this thread
            except (*_TRANSPORT_ERRORS, RuntimeError, ValueError,
                    SignalError) as e:
                backoff = min(backoff + self.backoff_step, self.backoff_max)
                log.debug("poll error (%s); backing off %.1fs", e, backoff)
                # arm replay: when the server answers again it may be a
                # RESTARTED orchestrator that lost our in-flight events
                self._replay_armed = True
                self._stop.wait(backoff)
                continue
            # a TRANSPARENT reconnect inside the keep-alive wrapper is
            # the same restart signature with no error escaping — a
            # poll that raced into a dying listener's last moments and
            # retried onto the successor must still trigger the replay,
            # or that successor never learns of our in-flight events
            gen = self._recv_conn.generation
            if gen != last_gen:
                # generation 1 on the FIRST success is the one clean
                # connect of a fresh transceiver; anything else means
                # a reconnect preceded this success — even one that
                # never surfaced as a poll error
                if last_gen is not None or gen > 1:
                    self._replay_armed = True
                last_gen = gen
            if self._replay_armed:
                self._replay_armed = False
                self._replay_unacked()
            for action in actions:
                self.dispatch_action(action)
        self._recv_conn.close()

    def _replay_chunk(self, chunk, entity: str) -> None:
        if self.use_batch:
            self._post_batch_once(chunk, entity)
        else:
            for event in chunk:
                self._post_once(event)

    def _poll_once(self) -> List[Action]:
        """One long-poll cycle over the receive thread's persistent
        connection; returns the acknowledged actions (empty on a 204
        timeout). Batch mode drains up to ``poll_batch`` actions and
        acks them with one multi-uuid DELETE."""
        if chaos.decide("wire.poll.sever") is not None:
            # tear the keep-alive socket under the receive thread: the
            # loop must back off, reconnect, and (via the replay arm)
            # re-offer unacked events — never die or lose its waiters
            self._recv_conn.close()
            raise OSError("chaos: keep-alive severed")
        if self.use_batch:
            return self._poll_once_batch()
        path = f"{self._path}/actions/{self.entity_id}"
        t0 = time.perf_counter()
        status, body = self._recv_conn.request("GET", path)
        obs.transport_rtt("poll", time.perf_counter() - t0)
        if status == 204:
            return []
        if status != 200:
            raise RuntimeError(f"GET {path} -> {status}")
        d = json.loads(body)
        action = signal_from_jsonable(d)
        if not isinstance(action, Action):
            raise RuntimeError(f"GET {path} returned non-action {d!r}")
        # acknowledge (parity: GET then DELETE, resttransceiver.go:139-156)
        t0 = time.perf_counter()
        status, _ = self._recv_conn.request(
            "DELETE", f"{path}/{action.uuid}")
        obs.transport_rtt("ack", time.perf_counter() - t0)
        # 404 = already acked: the keep-alive layer replays a DELETE
        # whose 200 was lost on a dying socket, and the server dequeued
        # the action on the first attempt — the action is in hand, so
        # this is success, not an error (dropping it would hang the
        # event's waiter)
        if status not in (200, 404):
            raise RuntimeError(f"DELETE {path}/{action.uuid} -> {status}")
        return [action]

    def _downgrade_to_legacy(self, why: str) -> None:
        """The server predates the batch routes: fall back to the
        per-event wire for the rest of this transceiver's life (still
        over the persistent connections)."""
        if self.use_batch:
            self.use_batch = False
            log.warning("orchestrator speaks the pre-batch wire (%s); "
                        "falling back to per-event transport", why)

    def _poll_once_batch(self) -> List[Action]:
        path = f"{self._path}/actions/{self.entity_id}"
        t0 = time.perf_counter()
        linger_ms = int(self.poll_linger * 1000)
        codec = self._wire_codec(self._recv_conn)
        status, body = self._recv_conn.request(
            "GET", f"{path}?batch={self.poll_batch}"
                   f"&linger_ms={linger_ms}", codec=codec)
        obs.transport_rtt("poll", time.perf_counter() - t0)
        self._note_table_version(self._recv_conn.last_table_version)
        obs.wire_bytes(codec, "poll", len(body or b""))
        if status == 204:
            return []
        if status != 200:
            raise RuntimeError(f"GET {path}?batch -> {status}")
        doc = self._decode_body(self._recv_conn, body)
        if not (isinstance(doc, dict)
                and isinstance(doc.get("actions"), list)):
            # a pre-batch orchestrator ignores the query and answers the
            # per-event wire: one action object as the whole body —
            # degrade gracefully instead of killing the receive thread
            action = signal_from_jsonable(doc)
            if not isinstance(action, Action):
                raise RuntimeError(
                    f"GET {path}?batch returned non-action {doc!r}")
            self._downgrade_to_legacy("single-action poll body")
            t0 = time.perf_counter()
            status, _ = self._recv_conn.request(
                "DELETE", f"{path}/{action.uuid}")
            obs.transport_rtt("ack", time.perf_counter() - t0)
            if status not in (200, 404):  # 404 = replayed ack
                raise RuntimeError(
                    f"DELETE {path}/{action.uuid} -> {status}")
            return [action]
        actions: List[Action] = []
        for item in doc["actions"]:
            action = signal_from_jsonable(item)
            if not isinstance(action, Action):
                raise RuntimeError(
                    f"GET {path}?batch returned non-action {item!r}")
            actions.append(action)
        if not actions:
            return []
        return self._ack_batch(path, actions)

    def _ack_batch(self, path: str, actions: List[Action]
                   ) -> List[Action]:
        """One multi-uuid DELETE for a polled batch (re-entered in
        JSON after a binary-codec downgrade)."""
        codec = self._wire_codec(self._recv_conn)
        del_body = self._encode_body(
            {"uuids": [a.uuid for a in actions]}, codec)
        t0 = time.perf_counter()
        status, _ = self._recv_conn.request("DELETE", path,
                                            body=del_body, codec=codec)
        obs.transport_rtt("ack", time.perf_counter() - t0)
        obs.wire_bytes(codec, "ack", len(del_body))
        if status == 400 and self._binary_400(
                self._recv_conn, codec, f"DELETE {path}"):
            return self._ack_batch(path, actions)
        if status != 200:
            raise RuntimeError(f"DELETE {path} (batch) -> {status}")
        return actions
