"""REST transceiver: the HTTP client side.

Parity: /root/reference/nmz/inspector/transceiver/resttransceiver.go —
``POST`` events non-blockingly; one receive thread long-polls
``GET /actions/{entity}``, acknowledges with ``DELETE``, and dispatches the
action to the per-event waiter queue; linear backoff on transport errors
(resttransceiver.go:158-188).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

from namazu_tpu.endpoint.rest import API_ROOT
from namazu_tpu.inspector.transceiver import Transceiver
from namazu_tpu.signal.action import Action
from namazu_tpu.signal.base import signal_from_jsonable
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.log import get_logger
from namazu_tpu.utils.retry import retry_call

log = get_logger("transceiver.rest")


class RestTransceiver(Transceiver):
    def __init__(self, entity_id: str, orchestrator_url: str,
                 backoff_step: float = 0.5, backoff_max: float = 5.0,
                 post_attempts: int = 4):
        super().__init__(entity_id)
        self.base = orchestrator_url.rstrip("/") + API_ROOT
        self.backoff_step = backoff_step
        self.backoff_max = backoff_max
        self.post_attempts = post_attempts
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- outbound --------------------------------------------------------

    def _post(self, event: Event) -> None:
        """POST the event, riding out transient transport hiccups with
        bounded backoff + jitter: the receive loop already backs off,
        but this path used to raise straight into inspector code on one
        dropped connection — killing the inspector over a blip the next
        attempt would have absorbed. Exhausted retries still raise (the
        orchestrator is genuinely gone)."""
        retry_call(
            lambda: self._post_once(event),
            exceptions=(urllib.error.URLError, OSError),
            attempts=max(1, self.post_attempts),
            base=self.backoff_step,
            cap=self.backoff_max,
            # an interruptible sleep: shutdown() aborts the backoff
            sleep=self._stop.wait,
            on_retry=lambda e, n, d: log.debug(
                "event POST failed (%s); retry %d in %.2fs", e, n, d),
        )

    def _post_once(self, event: Event) -> None:
        if self._stop.is_set():
            return  # shutting down: don't fight over a dying server
        url = f"{self.base}/events/{event.entity_id}/{event.uuid}"
        req = urllib.request.Request(
            url,
            data=event.to_json().encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            if resp.status != 200:
                raise RuntimeError(f"POST {url} -> {resp.status}")

    # -- inbound ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._receive_loop,
                name=f"rest-recv-{self.entity_id}",
                daemon=True,
            )
            self._thread.start()

    def shutdown(self, join_timeout: float = 5.0) -> None:
        """Stop and JOIN the receive thread (bounded): setting the flag
        alone let the thread's in-flight long-poll outlive shutdown and
        race the next run's transceiver for the same entity's actions."""
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=join_timeout)
            if t.is_alive():
                log.warning("receive thread still in a long-poll after "
                            "%.1fs; abandoning it (daemon)", join_timeout)

    def _receive_loop(self) -> None:
        backoff = 0.0
        while not self._stop.is_set():
            try:
                action = self._poll_once()
                backoff = 0.0
            except (urllib.error.URLError, OSError, RuntimeError) as e:
                backoff = min(backoff + self.backoff_step, self.backoff_max)
                log.debug("poll error (%s); backing off %.1fs", e, backoff)
                self._stop.wait(backoff)
                continue
            if action is not None:
                self.dispatch_action(action)

    def _poll_once(self) -> Action | None:
        url = f"{self.base}/actions/{self.entity_id}"
        req = urllib.request.Request(url, method="GET")
        with urllib.request.urlopen(req, timeout=60) as resp:
            if resp.status == 204:
                return None
            body = resp.read()
        d = json.loads(body)
        action = signal_from_jsonable(d)
        if not isinstance(action, Action):
            raise RuntimeError(f"GET {url} returned non-action {d!r}")
        # acknowledge (parity: GET then DELETE, resttransceiver.go:139-156)
        del_req = urllib.request.Request(
            f"{url}/{action.uuid}", method="DELETE"
        )
        with urllib.request.urlopen(del_req, timeout=30):
            pass
        return action
