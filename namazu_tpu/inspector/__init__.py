"""Inspector plane: transceivers plus the concrete event interceptors
(proc, fs, ethernet)."""
