"""UDS transceiver: framed JSON over AF_UNIX for same-host inspectors.

Client side of the ``uds://`` wire (endpoint/uds.py; doc/performance.md
"Zero-RTT dispatch"). Same batch/ack semantics as the batched REST
transport — events ride ``post_batch`` ops with a bounded retry (the
endpoint's dedupe ring makes replays idempotent), one receive thread
long-polls the ``poll`` op and multi-acks with ``ack`` — but the wire
is one length-prefixed JSON frame each way on a persistent Unix-domain
connection: no HTTP parse, no TCP handshake, no Nagle interplay.

Connection model mirrors the REST transceiver: one connection for the
outbound ops (serialized by a lock), one owned by the receive thread,
each with ONE transparent reconnect on a stale socket. Posted-but-
unanswered deferred events are kept in a bounded ring and replayed when
the receive loop recovers from a transport error (the signature of an
orchestrator restart) — the server-side dedupe makes that idempotent.

Edge dispatch (``edge=True``) works exactly as over REST: the shared
:class:`~namazu_tpu.inspector.edge.EdgeDispatcher` decides deferred
events against the published table (fetched with the ``table`` op,
staleness noticed from the ``table_version`` field every response
carries) and reconciles trace records through the ``backhaul`` op.

Chaos seams (doc/robustness.md): ``wire.uds.drop`` discards a post
batch pre-wire (the accounted-loss case), ``wire.uds.sever`` tears the
receive connection so the loop must back off, reconnect, and replay.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import List, Optional

from namazu_tpu import chaos, obs
from namazu_tpu.endpoint.agent import (read_frame, read_frame_ex,
                                       write_frame, write_raw_frame)
from namazu_tpu.inspector import edge as _edge_mod
from namazu_tpu.inspector.edge import EdgeDispatcher
from namazu_tpu.signal import binary as _binary
from namazu_tpu.inspector.rest_transceiver import (
    TransientHTTPStatus,
    _retry_after_hint,
)
from namazu_tpu.inspector.transceiver import (Transceiver,
                                              UnackedReplayMixin)
from namazu_tpu.signal.action import Action
from namazu_tpu.signal.base import SignalError, signal_from_jsonable
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.log import get_logger
from namazu_tpu.utils.retry import retry_call

log = get_logger("transceiver.uds")

_TRANSPORT_ERRORS = (OSError,)


def _check_resp(resp: dict, what: str) -> None:
    """Raise on a non-ok framed reply. A ``transient`` refusal (the
    bounded-ingress 429 analogue) raises the retryable class carrying
    the server's retry_after so the bounded retry honors it."""
    if resp.get("ok"):
        return
    error = resp.get("error", "failed")
    if resp.get("transient"):
        ra = resp.get("retry_after")
        raise TransientHTTPStatus(
            f"{what}: {error}",
            retry_after=None if ra is None else float(ra))
    raise RuntimeError(f"{what}: {error}")


class _FramedConn:
    """One persistent framed connection to the UDS endpoint.

    NOT thread-safe — each owner holds its own instance (the post path
    under its lock, the receive thread exclusively). A request on a
    stale socket gets ONE transparent reconnect+replay; every op here
    is idempotent by construction (post_batch dedupes server-side, poll
    peeks, ack reports already-gone uuids as ``missing``).

    Codec: with ``codec="auto"`` each (re)connect negotiates the
    binary codec with one JSON ``codec`` op (doc/performance.md
    "Binary wire + sharded edge"); a pre-binary server answers it with
    an unknown-op error and the connection stays on JSON, loss-free.
    Responses are decoded per frame (the server answers in the
    request's codec), so negotiation never races an in-flight reply."""

    def __init__(self, path: str, timeout: float, abort=None,
                 codec: str = "auto"):
        self._path = path
        self._timeout = timeout
        self._abort = abort
        self._sock: Optional[socket.socket] = None
        self._codec_pref = codec
        #: the codec THIS connection negotiated ("json" until proven)
        self.codec = _binary.CODEC_JSON
        #: bumped per fresh socket (see the REST twin): the receive
        #: loop arms the unacked replay on any transparent reconnect
        self.generation = 0

    def _negotiate(self, sock: socket.socket) -> None:
        """One JSON round trip deciding this connection's codec; any
        failure (old server, odd answer) leaves it on JSON."""
        self.codec = _binary.CODEC_JSON
        if self._codec_pref not in ("auto", "binary",
                                    _binary.CODEC_BINARY):
            return
        try:
            write_frame(sock, {"op": "codec",
                               "codecs": [_binary.CODEC_BINARY]})
            resp = read_frame(sock)
        except (OSError, SignalError, ValueError):
            return
        if isinstance(resp, dict) and resp.get("ok") \
                and resp.get("codec") == _binary.CODEC_BINARY:
            self.codec = _binary.CODEC_BINARY
            obs.codec_negotiated(_binary.CODEC_BINARY)

    def request(self, doc: dict) -> dict:
        last_exc: Optional[BaseException] = None
        for _attempt in (0, 1):
            if self._abort is not None and self._abort():
                raise OSError("connection owner is shutting down")
            sock = self._sock
            if sock is None:
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                sock.settimeout(self._timeout)
                try:
                    sock.connect(self._path)
                except OSError as e:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    last_exc = e
                    continue
                self._sock = sock
                self.generation += 1
                self._negotiate(sock)
            try:
                n_out = self._write(sock, doc)
                resp, resp_codec, n_in = read_frame_ex(sock)
                if resp is None:
                    raise OSError("connection closed mid-request")
                obs.wire_bytes(self.codec, str(doc.get("op") or "frame"),
                               n_out + n_in)
                return resp
            except (OSError, SignalError, ValueError) as e:
                self.close()
                last_exc = e
                if self._abort is not None and self._abort():
                    raise
        raise last_exc  # type: ignore[misc]

    def _write(self, sock: socket.socket, doc: dict) -> int:
        if self.codec == _binary.CODEC_BINARY:
            if chaos.decide("wire.binary.garble") is not None:
                # corrupt the payload under an intact length prefix:
                # the server must ANSWER (transient) without severing,
                # and the bounded retry resends a clean copy
                data = bytearray(_binary.dumps(doc))
                data[len(data) // 2] ^= 0xFF
                write_raw_frame(sock, bytes(data), binary=True)
                return len(data)
            try:
                return write_frame(sock, doc, codec=self.codec)
            except TypeError:
                # a value the binary codec cannot carry: this frame
                # rides JSON (the server answers per frame)
                return write_frame(sock, doc)
        return write_frame(sock, doc)

    def close(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                # wake a thread blocked in recv on this socket (a plain
                # close leaves the read parked until the server answers)
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class UdsTransceiver(UnackedReplayMixin, Transceiver):

    def __init__(self, entity_id: str, path: str,
                 backoff_step: float = 0.5, backoff_max: float = 5.0,
                 post_attempts: int = 4, batch_max: int = 64,
                 poll_batch: Optional[int] = None,
                 poll_linger: float = 0.0,
                 edge: bool = False,
                 backhaul_window: float = 0.05,
                 codec: str = "auto",
                 edge_shards: int = 0,
                 shard_pool=None,
                 shm: bool = False,
                 shm_capacity: int = 0,
                 run_ns: str = ""):
        super().__init__(entity_id)
        #: tenancy namespace (doc/tenancy.md): rides every op as the
        #: "run" field; "" = the process-default namespace (the
        #: pre-tenancy wire, byte-identical)
        self.run_ns = str(run_ns or "")
        # shared-memory fast lane (endpoint/shm.py): opened with the
        # shm_open op at start(); event batches ride the ring, acked
        # ops (poll/ack/backhaul/table) stay on this connection. An
        # old server answers the op with an error -> uds-only.
        self._shm_want = bool(shm)
        self._shm_capacity = int(shm_capacity)
        self._shm_ring = None
        self.path = path
        self.backoff_step = backoff_step
        self.backoff_max = backoff_max
        self.post_attempts = post_attempts
        self.batch_max = max(1, int(batch_max))
        self.poll_batch = (self.batch_max if poll_batch is None
                           else max(1, int(poll_batch)))
        self.poll_linger = max(0.0, float(poll_linger))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._post_conn = _FramedConn(path, timeout=30.0, codec=codec)
        self._recv_conn = _FramedConn(path, timeout=65.0,
                                      abort=self._stop.is_set,
                                      codec=codec)
        self._conn_lock = threading.Lock()
        self._init_unacked()
        self._replay_armed = False
        self._edge = None
        if edge:
            if shard_pool is not None or edge_shards >= 1:
                pool = (shard_pool if shard_pool is not None
                        else _edge_mod.shared_pool(
                            edge_shards, backhaul_window))
                self._edge = pool.register(
                    entity_id,
                    deliver=self.dispatch_action,
                    deliver_many=self.dispatch_actions,
                    fetch_table=self._fetch_table_once,
                    send_backhaul=self._post_backhaul_once)
            else:
                self._edge = EdgeDispatcher(
                    entity_id,
                    deliver=self.dispatch_action,
                    deliver_many=self.dispatch_actions,
                    fetch_table=self._fetch_table_once,
                    send_backhaul=self._post_backhaul_once,
                    backhaul_window=backhaul_window)

    def _ns_doc(self, doc: dict) -> dict:
        """Tag one op doc with this transceiver's run namespace (no-op
        for the default namespace: pre-tenancy ops stay byte-identical)."""
        if self.run_ns:
            doc["run"] = self.run_ns
        return doc

    # -- outbound ---------------------------------------------------------

    def _post(self, event: Event) -> None:
        if self._edge is not None and self._edge.try_dispatch(event):
            return  # zero-RTT: decided locally, backhaul reconciles
        retry_call(
            lambda: self._post_batch_once([event], event.entity_id),
            exceptions=_TRANSPORT_ERRORS,
            attempts=max(1, self.post_attempts),
            base=self.backoff_step,
            cap=self.backoff_max,
            sleep=self._stop.wait,
            delay_hint=_retry_after_hint,
            on_retry=lambda e, n, d: log.debug(
                "uds post failed (%s); retry %d in %.2fs", e, n, d),
        )

    def _post_batch_once(self, chunk: List[Event], entity: str) -> None:
        fault = chaos.decide("wire.uds.drop")
        if fault is not None:
            log.debug("chaos: dropped %d event(s) pre-wire (uds)",
                      len(chunk))
            return
        if self._shm_ring is not None:
            if chaos.decide("wire.shm.drop") is not None:
                # the accounted-loss seam: the burst vanishes pre-ring
                log.debug("chaos: dropped %d event(s) pre-shm",
                          len(chunk))
                return
            payload = _binary.dumps(self._ns_doc(
                {"op": "post_batch", "entity": entity,
                 "events": [ev.to_jsonable() for ev in chunk]}))
            # the ring is SPSC: every writer thread (callers, the
            # flush thread, the receive loop's unacked replay) must
            # serialize — the op wire's _conn_lock is that writer lock
            with self._conn_lock:
                ring = self._shm_ring
                wrote = (ring is not None
                         and ring.try_write_frame(payload, binary=True))
            if wrote:
                # in the server's address space: tracked in the
                # unacked-replay ring like any posted event (a server
                # crash is recovered by the uds-op replay + dedupe)
                self._note_posted(chunk)
                obs.event_batch("flush", len(chunk))
                obs.wire_bytes(_binary.CODEC_BINARY, "shm_post",
                               len(payload))
                return
            if ring is not None:
                # ring full: the acked op wire below IS the
                # backpressure
                obs.shm_ring_full(entity)
        req = self._ns_doc({"op": "post_batch", "entity": entity,
                            "events": [ev.to_jsonable()
                                       for ev in chunk]})
        with self._conn_lock:
            t0 = time.perf_counter()
            resp = self._post_conn.request(req)
            obs.transport_rtt("post_batch", time.perf_counter() - t0)
        _check_resp(resp, "uds post_batch")
        self._note_posted(chunk)
        obs.event_batch("flush", len(chunk))
        self._note_table_version(resp.get("table_version"))

    def _post_many(self, events) -> None:
        """Batch hook (``send_events``): the central subset rides the
        wire FIRST (its ``post_batch`` ops can fail, and a replayed
        burst dedupes server-side), then the edge decides the eligible
        subset in one vectorized pass — releasing only after the
        fallible wire work succeeded, so a caller retrying a raised
        burst can never re-release an already-decided event. Edge
        rejects (table withdrawn in between) fall back per event."""
        events = list(events)
        eligible = []
        if self._edge is not None:
            eligible, events = self._edge.partition(events)
        by_entity: "dict[str, List[Event]]" = {}
        for event in events:
            by_entity.setdefault(event.entity_id, []).append(event)
        for entity, batch in by_entity.items():
            for i in range(0, len(batch), self.batch_max):
                chunk = batch[i:i + self.batch_max]
                retry_call(
                    lambda c=chunk, e=entity: self._post_batch_once(c, e),
                    exceptions=_TRANSPORT_ERRORS,
                    attempts=max(1, self.post_attempts),
                    base=self.backoff_step,
                    cap=self.backoff_max,
                    sleep=self._stop.wait,
                    delay_hint=_retry_after_hint,
                )
        if eligible:
            for event in self._edge.try_dispatch_batch(eligible):
                self._post(event)

    # -- zero-RTT edge dispatch ------------------------------------------

    @property
    def edge_active(self) -> bool:
        return self._edge is not None and self._edge.active

    def sync_table(self) -> Optional[int]:
        if self._edge is None:
            return None
        return self._edge.sync()

    def _note_table_version(self, version) -> None:
        if self._edge is not None and version is not None:
            try:
                self._edge.note_server_version(int(version))
            except (TypeError, ValueError):
                pass

    def _fetch_table_once(self):
        with self._conn_lock:
            resp = self._post_conn.request({"op": "table"})
        if not resp.get("ok"):
            raise RuntimeError(f"uds table: {resp.get('error', 'failed')}")
        return int(resp.get("version", 0)), resp.get("table")

    def _post_backhaul_once(self, entity: str,
                            items: List[dict]) -> Optional[int]:
        req = self._ns_doc({"op": "backhaul", "entity": entity,
                            "items": items})
        with self._conn_lock:
            t0 = time.perf_counter()
            resp = self._post_conn.request(req)
            obs.transport_rtt("backhaul", time.perf_counter() - t0)
        _check_resp(resp, "uds backhaul")
        version = resp.get("table_version")
        return None if version is None else int(version)

    # -- inbound ----------------------------------------------------------

    def start(self) -> None:
        if self._shm_want and self._shm_ring is None:
            self._open_shm()
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._receive_loop,
                name=f"uds-recv-{self.entity_id}", daemon=True)
            self._thread.start()

    def _reset_shm(self) -> None:
        """Drop + renegotiate the shm ring after a server restart: the
        old mapping is an orphan nobody drains — writes into it would
        be note_posted but never delivered. Runs on the receive thread
        when the reconnect-replay arms; the writer lock makes the swap
        safe against in-flight posts."""
        if not self._shm_want:
            return
        with self._conn_lock:
            ring, self._shm_ring = self._shm_ring, None
        if ring is not None:
            try:
                ring.close()
            except Exception:  # pragma: no cover - defensive
                pass
        if not self._stop.is_set():
            self._open_shm()

    def _open_shm(self) -> None:
        from namazu_tpu.endpoint.shm import ShmRing

        req = {"op": "shm_open", "entity": self.entity_id}
        if self._shm_capacity > 0:
            req["capacity"] = self._shm_capacity
        try:
            with self._conn_lock:
                resp = self._post_conn.request(req)
        except (*_TRANSPORT_ERRORS, RuntimeError) as e:
            log.warning("shm_open failed (%s); staying on the uds "
                        "op wire", e)
            return
        if not resp.get("ok") or not resp.get("path"):
            log.warning("server declined shm ring (%s); staying on "
                        "the uds op wire", resp.get("error"))
            return
        try:
            ring = ShmRing(str(resp["path"]))
        except (OSError, ValueError) as e:
            log.warning("cannot map shm ring %s (%s); staying on the "
                        "uds op wire", resp.get("path"), e)
            return
        with self._conn_lock:
            self._shm_ring = ring

    def shutdown(self, join_timeout: float = 5.0) -> None:
        self._stop.set()
        ring, self._shm_ring = self._shm_ring, None
        if ring is not None:
            # wait briefly for the server to drain what we wrote, then
            # unmap (the server owns the file's lifecycle)
            deadline = time.monotonic() + 2.0
            while ring.pending() > 0 and time.monotonic() < deadline:
                time.sleep(0.002)
            ring.close()
        if self._edge is not None:
            # flush pending backhaul while the post connection is still
            # usable — edge-decided trace records are never dropped at
            # shutdown
            try:
                self._edge.shutdown()
            except Exception:
                log.debug("edge shutdown flush failed", exc_info=True)
        t = self._thread
        if t is not None and t is not threading.current_thread():
            self._recv_conn.close()
            t.join(timeout=join_timeout)
            if t.is_alive():
                log.warning("uds receive thread still parked after "
                            "%.1fs; abandoning it (daemon)", join_timeout)
        with self._conn_lock:
            self._post_conn.close()

    def _receive_loop(self) -> None:
        backoff = 0.0
        last_gen = None
        while not self._stop.is_set():
            try:
                actions = self._poll_once()
                backoff = 0.0
            except (*_TRANSPORT_ERRORS, RuntimeError, ValueError,
                    SignalError) as e:
                backoff = min(backoff + self.backoff_step,
                              self.backoff_max)
                log.debug("uds poll error (%s); backing off %.1fs",
                          e, backoff)
                self._replay_armed = True
                self._stop.wait(backoff)
                continue
            # transparent reconnect = restart signature with no error
            # escaping (see the REST receive loop): arm the replay
            gen = self._recv_conn.generation
            if gen != last_gen:
                # generation 1 on the FIRST success is the one clean
                # connect of a fresh transceiver; anything else means
                # a reconnect preceded this success — even one that
                # never surfaced as a poll error
                if last_gen is not None or gen > 1:
                    self._replay_armed = True
                    self._reset_shm()
                last_gen = gen
            if self._replay_armed:
                self._replay_armed = False
                self._replay_unacked()
            for action in actions:
                self.dispatch_action(action)
        self._recv_conn.close()

    def _replay_chunk(self, chunk, entity: str) -> None:
        self._post_batch_once(chunk, entity)

    def _poll_once(self) -> List[Action]:
        if chaos.decide("wire.uds.sever") is not None:
            # tear the keep-alive socket under the receive thread: the
            # loop must back off, reconnect, and replay unacked events
            self._recv_conn.close()
            raise OSError("chaos: uds keep-alive severed")
        t0 = time.perf_counter()
        resp = self._recv_conn.request(self._ns_doc({
            "op": "poll", "entity": self.entity_id,
            "batch": self.poll_batch,
            "linger_ms": int(self.poll_linger * 1000),
            "timeout_s": 25.0,
        }))
        obs.transport_rtt("poll", time.perf_counter() - t0)
        if not resp.get("ok"):
            raise RuntimeError(f"uds poll: {resp.get('error', 'failed')}")
        self._note_table_version(resp.get("table_version"))
        actions: List[Action] = []
        for item in resp.get("actions") or []:
            action = signal_from_jsonable(item)
            if not isinstance(action, Action):
                raise RuntimeError(f"uds poll returned non-action "
                                   f"{item!r}")
            actions.append(action)
        if not actions:
            return []
        t0 = time.perf_counter()
        ack = self._recv_conn.request(self._ns_doc({
            "op": "ack", "entity": self.entity_id,
            "uuids": [a.uuid for a in actions],
        }))
        obs.transport_rtt("ack", time.perf_counter() - t0)
        if not ack.get("ok"):
            raise RuntimeError(f"uds ack: {ack.get('error', 'failed')}")
        return actions
