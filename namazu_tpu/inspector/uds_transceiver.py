"""UDS transceiver: framed JSON over AF_UNIX for same-host inspectors.

Client side of the ``uds://`` wire (endpoint/uds.py; doc/performance.md
"Zero-RTT dispatch"). Same batch/ack semantics as the batched REST
transport — events ride ``post_batch`` ops with a bounded retry (the
endpoint's dedupe ring makes replays idempotent), one receive thread
long-polls the ``poll`` op and multi-acks with ``ack`` — but the wire
is one length-prefixed JSON frame each way on a persistent Unix-domain
connection: no HTTP parse, no TCP handshake, no Nagle interplay.

Connection model mirrors the REST transceiver: one connection for the
outbound ops (serialized by a lock), one owned by the receive thread,
each with ONE transparent reconnect on a stale socket. Posted-but-
unanswered deferred events are kept in a bounded ring and replayed when
the receive loop recovers from a transport error (the signature of an
orchestrator restart) — the server-side dedupe makes that idempotent.

Edge dispatch (``edge=True``) works exactly as over REST: the shared
:class:`~namazu_tpu.inspector.edge.EdgeDispatcher` decides deferred
events against the published table (fetched with the ``table`` op,
staleness noticed from the ``table_version`` field every response
carries) and reconciles trace records through the ``backhaul`` op.

Chaos seams (doc/robustness.md): ``wire.uds.drop`` discards a post
batch pre-wire (the accounted-loss case), ``wire.uds.sever`` tears the
receive connection so the loop must back off, reconnect, and replay.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import List, Optional

from namazu_tpu import chaos, obs
from namazu_tpu.endpoint.agent import read_frame, write_frame
from namazu_tpu.inspector.edge import EdgeDispatcher
from namazu_tpu.inspector.rest_transceiver import (
    TransientHTTPStatus,
    _retry_after_hint,
)
from namazu_tpu.inspector.transceiver import (Transceiver,
                                              UnackedReplayMixin)
from namazu_tpu.signal.action import Action
from namazu_tpu.signal.base import SignalError, signal_from_jsonable
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.log import get_logger
from namazu_tpu.utils.retry import retry_call

log = get_logger("transceiver.uds")

_TRANSPORT_ERRORS = (OSError,)


def _check_resp(resp: dict, what: str) -> None:
    """Raise on a non-ok framed reply. A ``transient`` refusal (the
    bounded-ingress 429 analogue) raises the retryable class carrying
    the server's retry_after so the bounded retry honors it."""
    if resp.get("ok"):
        return
    error = resp.get("error", "failed")
    if resp.get("transient"):
        ra = resp.get("retry_after")
        raise TransientHTTPStatus(
            f"{what}: {error}",
            retry_after=None if ra is None else float(ra))
    raise RuntimeError(f"{what}: {error}")


class _FramedConn:
    """One persistent framed-JSON connection to the UDS endpoint.

    NOT thread-safe — each owner holds its own instance (the post path
    under its lock, the receive thread exclusively). A request on a
    stale socket gets ONE transparent reconnect+replay; every op here
    is idempotent by construction (post_batch dedupes server-side, poll
    peeks, ack reports already-gone uuids as ``missing``)."""

    def __init__(self, path: str, timeout: float, abort=None):
        self._path = path
        self._timeout = timeout
        self._abort = abort
        self._sock: Optional[socket.socket] = None

    def request(self, doc: dict) -> dict:
        last_exc: Optional[BaseException] = None
        for _attempt in (0, 1):
            if self._abort is not None and self._abort():
                raise OSError("connection owner is shutting down")
            sock = self._sock
            if sock is None:
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                sock.settimeout(self._timeout)
                try:
                    sock.connect(self._path)
                except OSError as e:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    last_exc = e
                    continue
                self._sock = sock
            try:
                write_frame(sock, doc)
                resp = read_frame(sock)
                if resp is None:
                    raise OSError("connection closed mid-request")
                return resp
            except (OSError, SignalError, ValueError) as e:
                self.close()
                last_exc = e
                if self._abort is not None and self._abort():
                    raise
        raise last_exc  # type: ignore[misc]

    def close(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                # wake a thread blocked in recv on this socket (a plain
                # close leaves the read parked until the server answers)
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class UdsTransceiver(UnackedReplayMixin, Transceiver):

    def __init__(self, entity_id: str, path: str,
                 backoff_step: float = 0.5, backoff_max: float = 5.0,
                 post_attempts: int = 4, batch_max: int = 64,
                 poll_batch: Optional[int] = None,
                 poll_linger: float = 0.0,
                 edge: bool = False,
                 backhaul_window: float = 0.05):
        super().__init__(entity_id)
        self.path = path
        self.backoff_step = backoff_step
        self.backoff_max = backoff_max
        self.post_attempts = post_attempts
        self.batch_max = max(1, int(batch_max))
        self.poll_batch = (self.batch_max if poll_batch is None
                           else max(1, int(poll_batch)))
        self.poll_linger = max(0.0, float(poll_linger))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._post_conn = _FramedConn(path, timeout=30.0)
        self._recv_conn = _FramedConn(path, timeout=65.0,
                                      abort=self._stop.is_set)
        self._conn_lock = threading.Lock()
        self._init_unacked()
        self._replay_armed = False
        self._edge: Optional[EdgeDispatcher] = None
        if edge:
            self._edge = EdgeDispatcher(
                entity_id,
                deliver=self.dispatch_action,
                deliver_many=self.dispatch_actions,
                fetch_table=self._fetch_table_once,
                send_backhaul=self._post_backhaul_once,
                backhaul_window=backhaul_window)

    # -- outbound ---------------------------------------------------------

    def _post(self, event: Event) -> None:
        if self._edge is not None and self._edge.try_dispatch(event):
            return  # zero-RTT: decided locally, backhaul reconciles
        retry_call(
            lambda: self._post_batch_once([event], event.entity_id),
            exceptions=_TRANSPORT_ERRORS,
            attempts=max(1, self.post_attempts),
            base=self.backoff_step,
            cap=self.backoff_max,
            sleep=self._stop.wait,
            delay_hint=_retry_after_hint,
            on_retry=lambda e, n, d: log.debug(
                "uds post failed (%s); retry %d in %.2fs", e, n, d),
        )

    def _post_batch_once(self, chunk: List[Event], entity: str) -> None:
        fault = chaos.decide("wire.uds.drop")
        if fault is not None:
            log.debug("chaos: dropped %d event(s) pre-wire (uds)",
                      len(chunk))
            return
        req = {"op": "post_batch", "entity": entity,
               "events": [ev.to_jsonable() for ev in chunk]}
        with self._conn_lock:
            t0 = time.perf_counter()
            resp = self._post_conn.request(req)
            obs.transport_rtt("post_batch", time.perf_counter() - t0)
        _check_resp(resp, "uds post_batch")
        self._note_posted(chunk)
        obs.event_batch("flush", len(chunk))
        self._note_table_version(resp.get("table_version"))

    def _post_many(self, events) -> None:
        """Batch hook (``send_events``): the central subset rides the
        wire FIRST (its ``post_batch`` ops can fail, and a replayed
        burst dedupes server-side), then the edge decides the eligible
        subset in one vectorized pass — releasing only after the
        fallible wire work succeeded, so a caller retrying a raised
        burst can never re-release an already-decided event. Edge
        rejects (table withdrawn in between) fall back per event."""
        events = list(events)
        eligible = []
        if self._edge is not None:
            eligible, events = self._edge.partition(events)
        by_entity: "dict[str, List[Event]]" = {}
        for event in events:
            by_entity.setdefault(event.entity_id, []).append(event)
        for entity, batch in by_entity.items():
            for i in range(0, len(batch), self.batch_max):
                chunk = batch[i:i + self.batch_max]
                retry_call(
                    lambda c=chunk, e=entity: self._post_batch_once(c, e),
                    exceptions=_TRANSPORT_ERRORS,
                    attempts=max(1, self.post_attempts),
                    base=self.backoff_step,
                    cap=self.backoff_max,
                    sleep=self._stop.wait,
                    delay_hint=_retry_after_hint,
                )
        if eligible:
            for event in self._edge.try_dispatch_batch(eligible):
                self._post(event)

    # -- zero-RTT edge dispatch ------------------------------------------

    @property
    def edge_active(self) -> bool:
        return self._edge is not None and self._edge.active

    def sync_table(self) -> Optional[int]:
        if self._edge is None:
            return None
        return self._edge.sync()

    def _note_table_version(self, version) -> None:
        if self._edge is not None and version is not None:
            try:
                self._edge.note_server_version(int(version))
            except (TypeError, ValueError):
                pass

    def _fetch_table_once(self):
        with self._conn_lock:
            resp = self._post_conn.request({"op": "table"})
        if not resp.get("ok"):
            raise RuntimeError(f"uds table: {resp.get('error', 'failed')}")
        return int(resp.get("version", 0)), resp.get("table")

    def _post_backhaul_once(self, entity: str,
                            items: List[dict]) -> Optional[int]:
        req = {"op": "backhaul", "entity": entity, "items": items}
        with self._conn_lock:
            t0 = time.perf_counter()
            resp = self._post_conn.request(req)
            obs.transport_rtt("backhaul", time.perf_counter() - t0)
        _check_resp(resp, "uds backhaul")
        version = resp.get("table_version")
        return None if version is None else int(version)

    # -- inbound ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._receive_loop,
                name=f"uds-recv-{self.entity_id}", daemon=True)
            self._thread.start()

    def shutdown(self, join_timeout: float = 5.0) -> None:
        self._stop.set()
        if self._edge is not None:
            # flush pending backhaul while the post connection is still
            # usable — edge-decided trace records are never dropped at
            # shutdown
            try:
                self._edge.shutdown()
            except Exception:
                log.debug("edge shutdown flush failed", exc_info=True)
        t = self._thread
        if t is not None and t is not threading.current_thread():
            self._recv_conn.close()
            t.join(timeout=join_timeout)
            if t.is_alive():
                log.warning("uds receive thread still parked after "
                            "%.1fs; abandoning it (daemon)", join_timeout)
        with self._conn_lock:
            self._post_conn.close()

    def _receive_loop(self) -> None:
        backoff = 0.0
        while not self._stop.is_set():
            try:
                actions = self._poll_once()
                backoff = 0.0
            except (*_TRANSPORT_ERRORS, RuntimeError, ValueError,
                    SignalError) as e:
                backoff = min(backoff + self.backoff_step,
                              self.backoff_max)
                log.debug("uds poll error (%s); backing off %.1fs",
                          e, backoff)
                self._replay_armed = True
                self._stop.wait(backoff)
                continue
            if self._replay_armed:
                self._replay_armed = False
                self._replay_unacked()
            for action in actions:
                self.dispatch_action(action)
        self._recv_conn.close()

    def _replay_chunk(self, chunk, entity: str) -> None:
        self._post_batch_once(chunk, entity)

    def _poll_once(self) -> List[Action]:
        if chaos.decide("wire.uds.sever") is not None:
            # tear the keep-alive socket under the receive thread: the
            # loop must back off, reconnect, and replay unacked events
            self._recv_conn.close()
            raise OSError("chaos: uds keep-alive severed")
        t0 = time.perf_counter()
        resp = self._recv_conn.request({
            "op": "poll", "entity": self.entity_id,
            "batch": self.poll_batch,
            "linger_ms": int(self.poll_linger * 1000),
            "timeout_s": 25.0,
        })
        obs.transport_rtt("poll", time.perf_counter() - t0)
        if not resp.get("ok"):
            raise RuntimeError(f"uds poll: {resp.get('error', 'failed')}")
        self._note_table_version(resp.get("table_version"))
        actions: List[Action] = []
        for item in resp.get("actions") or []:
            action = signal_from_jsonable(item)
            if not isinstance(action, Action):
                raise RuntimeError(f"uds poll returned non-action "
                                   f"{item!r}")
            actions.append(action)
        if not actions:
            return []
        t0 = time.perf_counter()
        ack = self._recv_conn.request({
            "op": "ack", "entity": self.entity_id,
            "uuids": [a.uuid for a in actions],
        })
        obs.transport_rtt("ack", time.perf_counter() - t0)
        if not ack.get("ok"):
            raise RuntimeError(f"uds ack: {ack.get('error', 'failed')}")
        return actions
