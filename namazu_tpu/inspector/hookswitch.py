"""Hookswitch (ZMQ) ethernet inspector backend.

Speaks the hookswitch wire protocol (parity:
/root/reference/nmz/inspector/ethernet/ethernet_hookswitch.go:56-160 and
the pynmz worker, misc/pynmz/inspector/ether.py): the inspector BINDS a
ZMQ PAIR socket; the external switch (Openflow 1.3 via Ryu, or a
userspace NFQ hook) connects and sends each captured ethernet frame as a
two-part message ``[json {"id": N, "op": ...}, frame bytes]``; the
inspector replies ``[json {"id": N, "op": "accept"|"drop"}, b""]`` once
the policy decides. This is the "any IP traffic" capture path the
userspace TCP proxy cannot provide — the switch sees raw frames, so TCP
retransmit suppression (rawpacket.TcpRetransWatcher) is REQUIRED here,
exactly the problem the proxy design sidesteps.

Gated on pyzmq (present in this image); the external hookswitch process
itself is not shipped here — tests drive the inspector with a fake
switch socket, the same strategy the reference's own suite uses
(ethernet_test.go:36-80).
"""

from __future__ import annotations

import json
import queue as _queue
import threading
from typing import Optional

from namazu_tpu.inspector.rawpacket import TcpRetransWatcher, decode_ethernet
from namazu_tpu.inspector.transceiver import Transceiver
from namazu_tpu.signal.action import PacketFaultAction
from namazu_tpu.signal.event import PacketEvent
from namazu_tpu.utils.log import get_logger

log = get_logger("inspector.hookswitch")


def zmq_available() -> bool:
    try:
        import zmq  # noqa: F401
    except ImportError:
        return False
    return True


class HookSwitchInspector:
    """One ZMQ PAIR endpoint serving verdicts to an external switch."""

    #: bounded concurrent deferrals (same rationale as
    #: ethernet.UdpProxyLink.RELEASE_WORKERS: a frame burst must not
    #: become a thread per packet)
    DECIDE_WORKERS = 16

    def __init__(
        self,
        transceiver: Transceiver,
        zmq_addr: str = "ipc:///tmp/nmz-hookswitch",
        entity_id: str = "_nmz_ethernet_inspector",
        enable_tcp_watcher: bool = True,
        action_timeout: Optional[float] = 30.0,
    ):
        if not zmq_available():
            raise RuntimeError(
                "hookswitch backend needs pyzmq; none importable. Use the "
                "TCP-proxy or UDP backends (inspector/ethernet.py), which "
                "have no dependencies."
            )
        self.trans = transceiver
        self.zmq_addr = zmq_addr
        self.entity_id = entity_id
        self.action_timeout = action_timeout
        self.watcher = TcpRetransWatcher() if enable_tcp_watcher else None
        self.packet_count = 0
        self.drop_count = 0
        self.retrans_count = 0
        self._count_lock = threading.Lock()  # counters bump from workers
        self._ctx = None
        self._sock = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._decide_q: _queue.Queue = _queue.Queue()
        # verdicts are queued here and sent by the serve thread: ZMQ
        # sockets are not thread-safe, and a worker's send racing the
        # serve loop's recv on the same PAIR socket can abort the
        # process — ALL socket use stays on one thread
        self._out_q: _queue.Queue = _queue.Queue()

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        import zmq

        self.trans.start()
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.PAIR)
        self._sock.bind(self.zmq_addr)
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="hookswitch-serve")
        self._thread.start()
        for i in range(self.DECIDE_WORKERS):
            threading.Thread(target=self._decide_worker, daemon=True,
                             name=f"hookswitch-decide-{i}").start()

    def stop(self) -> None:
        self._stop.set()
        for _ in range(self.DECIDE_WORKERS):
            self._decide_q.put(None)
        # the serve thread owns the socket (ZMQ sockets are not
        # thread-safe): signal, wait for it to leave its poll, THEN close
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._sock is not None:
            try:
                self._sock.close(linger=0)
            except Exception:  # pragma: no cover - zmq teardown races
                pass

    # -- wire -------------------------------------------------------------

    def _reply(self, frame_id: int, op: str) -> None:
        meta = json.dumps({"id": frame_id, "op": op}).encode()
        self._out_q.put([meta, b""])

    def _flush_replies(self) -> None:
        import zmq

        while True:
            try:
                msg = self._out_q.get_nowait()
            except _queue.Empty:
                return
            try:
                self._sock.send_multipart(msg)
            except zmq.ZMQError:
                return

    def _serve(self) -> None:
        import zmq

        poller = zmq.Poller()
        poller.register(self._sock, zmq.POLLIN)
        while not self._stop.is_set():
            try:
                # short poll bound: verdicts queued by decide workers
                # while no frames arrive must not sit a whole poll cycle
                # — that delay would ride on top of every policy-chosen
                # release time
                ready = poller.poll(timeout=5)
                self._flush_replies()
                if not ready:
                    continue
                parts = self._sock.recv_multipart(flags=zmq.NOBLOCK)
            except zmq.ZMQError:
                return
            if len(parts) != 2:
                log.warning("strange hookswitch message: %d parts",
                            len(parts))
                continue
            try:
                meta = json.loads(parts[0])
                frame_id = int(meta["id"])
            except (ValueError, KeyError) as e:
                log.warning("bad hookswitch meta %r: %s", parts[0][:80], e)
                continue
            pkt = decode_ethernet(parts[1])
            # retransmit suppression runs in the receive loop (the
            # watcher is not thread-safe, same contract as the
            # reference, ethernet_hookswitch.go:87-95): verdict=drop —
            # the endpoint's own TCP stack recovers, and the duplicate
            # never becomes a second event
            if self.watcher is not None and self.watcher.is_retransmit(pkt):
                self.retrans_count += 1
                self._reply(frame_id, "drop")
                continue
            self._decide_q.put((frame_id, pkt))

    def _decide_worker(self) -> None:
        while True:
            item = self._decide_q.get()
            if item is None:
                return
            self._decide(*item)

    def _decide(self, frame_id: int, pkt) -> None:
        with self._count_lock:
            self.packet_count += 1
        event = PacketEvent.create(
            self.entity_id, pkt.src_entity, pkt.dst_entity,
            payload=pkt.payload[:128], hint=pkt.content_hint(),
        )
        ch = self.trans.send_event(event)
        try:
            action = ch.get(timeout=self.action_timeout)
        except _queue.Empty:
            self.trans.forget(event)
            log.warning("frame %d: no action in %ss; accepting",
                        frame_id, self.action_timeout)
            action = None
        if isinstance(action, PacketFaultAction):
            with self._count_lock:
                self.drop_count += 1
            self._reply(frame_id, "drop")
            return
        self._reply(frame_id, "accept")


def serve_hookswitch_inspector(
    transceiver: Transceiver, zmq_addr: str,
    enable_tcp_watcher: bool = True,
) -> int:
    """CLI entry: serve verdicts until interrupted."""
    inspector = HookSwitchInspector(
        transceiver, zmq_addr=zmq_addr,
        enable_tcp_watcher=enable_tcp_watcher)
    inspector.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        inspector.stop()
    return 0
