"""Syslog inspector: observation-only LogEvents from a UDP syslog socket.

Parity: /root/reference/misc/pynmz/inspector/syslog.py:16-84 — point the
system-under-test's syslog at this server; every line becomes a
non-deferred LogEvent (useful as a bug-predicate signal for the search
plane: "leader elected", stack traces, ...).
"""

from __future__ import annotations

import socket
import threading
from typing import Callable, Optional

from namazu_tpu.inspector.transceiver import Transceiver
from namazu_tpu.signal.event import LogEvent
from namazu_tpu.utils.log import get_logger

log = get_logger("inspector.syslog")


class SyslogInspector:
    def __init__(
        self,
        transceiver: Transceiver,
        entity_id: str = "_nmz_syslog_inspector",
        host: str = "127.0.0.1",
        port: int = 10514,
        line_filter: Optional[Callable[[str], bool]] = None,
    ):
        self.trans = transceiver
        self.entity_id = entity_id
        self._addr = (host, port)
        self.line_filter = line_filter
        self._sock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self.line_count = 0

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1] if self._sock else self._addr[1]

    def start(self) -> None:
        self.trans.start()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind(self._addr)
        self._sock.settimeout(0.2)
        threading.Thread(target=self._serve, name="syslog-inspector",
                         daemon=True).start()
        log.info("syslog inspector on udp %s:%d", self._addr[0], self.port)

    def stop(self) -> None:
        self._stop.set()

    def _serve(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    data, _ = self._sock.recvfrom(65536)
                except socket.timeout:
                    continue
                except OSError:
                    return
                for raw in data.decode(errors="replace").splitlines():
                    line = raw.strip()
                    if not line:
                        continue
                    if self.line_filter and not self.line_filter(line):
                        continue
                    self.line_count += 1
                    # observation-only: no action expected back
                    self.trans.send_notification(
                        LogEvent.create(self.entity_id, line)
                    )
        finally:
            try:
                self._sock.close()
            except OSError:
                pass
