"""Transceivers: the inspector-side RPC clients.

Parity: /root/reference/nmz/inspector/transceiver (transceiver.go:15-31):
``send_event`` registers a per-event action queue *before* the event leaves
the process (closing the race noted in localtransceiver.go:40-44), returns
that queue, and a receive loop correlates incoming actions back by their
``event_uuid``.

``new_transceiver(url, entity_id)`` dispatches on scheme: ``local://`` for
the in-process endpoint (autopilot/tests), ``http(s)://`` for REST,
``uds://`` for the same-host framed-JSON AF_UNIX wire, ``agent://``
for the guest-agent framed TCP wire. ``edge=True`` (REST/UDS) opts the
transceiver into zero-RTT edge dispatch: dormant until the
orchestrator publishes a delay table, then deferred events are decided
and released locally with asynchronous backhaul (doc/performance.md
"Zero-RTT dispatch").
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, Optional

from namazu_tpu.endpoint.local import LocalEndpoint
from namazu_tpu.obs import context as _context
from namazu_tpu.signal.action import Action
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.log import get_logger

log = get_logger("transceiver")


class Transceiver:
    def __init__(self, entity_id: str):
        self.entity_id = entity_id
        self._waiters: Dict[str, "queue.SimpleQueue[Action]"] = {}
        self._lock = threading.Lock()

    def start(self) -> None:
        pass

    def shutdown(self) -> None:
        pass

    def send_event(self, event: Event) -> "queue.SimpleQueue[Action]":
        """Send ``event``; returns a queue that will receive the answering
        action(s). The queue is registered before sending.

        Only use for events whose answer is *propagated* back (deferred
        events, and ProcSetEvent which is answered out-of-band). For
        observation-only events (LogEvent, NopEvent) use
        :meth:`send_notification` — their default NopAction is
        orchestrator-side-only and never comes back, so a registered
        waiter would leak.

        The queue is a ``SimpleQueue`` (C implementation, reentrant
        put): a waiter is minted per event, so its construction cost is
        part of the event plane's per-event budget
        (doc/performance.md).
        """
        # causality plane (obs/context.py): the span context is minted
        # HERE — the inspector-side interception point — so it rides
        # every wire the event takes (no-op when observability is off)
        _context.ensure(event)
        ch: "queue.SimpleQueue[Action]" = queue.SimpleQueue()
        with self._lock:
            self._waiters[event.uuid] = ch
        try:
            self._post(event)
        except Exception:
            with self._lock:
                self._waiters.pop(event.uuid, None)
            raise
        return ch

    def send_events(self, events) -> "list[queue.SimpleQueue]":
        """Batch variant of :meth:`send_event` for inspectors that
        intercept bursts: every waiter is registered under ONE lock
        before anything reaches the wire, then the burst posts through
        :meth:`_post_many` (transports with a batch wire — the edge
        dispatcher's vectorized decide, the coalesced batch POST —
        amortize their per-event overhead there). Same contract as
        send_event: deferred events only. On error no waiter remains
        registered."""
        events = list(events)
        # batch mint: one clock tick + one enabled check for the whole
        # burst (the zero-RTT path's per-event budget, obs/context.py)
        _context.mint_many(events)
        chans: "list[queue.SimpleQueue]" = []
        with self._lock:
            for event in events:
                ch: "queue.SimpleQueue" = queue.SimpleQueue()
                self._waiters[event.uuid] = ch
                chans.append(ch)
        try:
            self._post_many(events)
        except Exception:
            with self._lock:
                for event in events:
                    self._waiters.pop(event.uuid, None)
            raise
        return chans

    def _post_many(self, events) -> None:
        """Transport hook for :meth:`send_events`; default = the
        per-event loop."""
        for event in events:
            self._post(event)

    def send_events_burst(self, events) -> "BurstHandle":
        """The serving-plane burst API (doc/performance.md "Binary
        wire + sharded edge"): like :meth:`send_events`, but the whole
        burst shares ONE channel and the edge's ripe group is answered
        with a single :class:`~namazu_tpu.inspector.edge.BurstAccept`
        verdict instead of per-event actions — the per-event waiter
        queue, registry insert, and action mint disappear from the
        zero-RTT path. Central-wire and parked events still arrive on
        the channel as individual actions. For burst inspectors
        (rawpacket GSO bursts, the bench) that release the whole group
        on its verdict; per-event consumers keep :meth:`send_events`.
        Same contract: deferred events only."""
        events = list(events)
        _context.mint_many(events)
        chan: "queue.SimpleQueue" = queue.SimpleQueue()
        self._post_burst(events, chan)
        return BurstHandle(chan, len(events))

    def _post_burst(self, events, chan) -> None:
        """Transport hook for :meth:`send_events_burst`. The default
        (and the central subset of edge transports) registers the
        shared channel per uuid so wire actions route to it; edge
        transports hand the eligible subset to
        ``EdgeDispatcher.try_dispatch_burst``, which delivers grouped
        verdicts straight to the channel."""
        edge = getattr(self, "_edge", None)
        if edge is not None:
            eligible, central = edge.partition(events)
        else:
            eligible, central = [], events
        if central:
            self._register_chan(central, chan)
            try:
                self._post_many(central)
            except Exception:
                self._unregister_chan(central)
                raise
        if eligible:
            leftover = edge.try_dispatch_burst(
                eligible, chan,
                lambda parked: self._register_chan(parked, chan))
            if leftover:
                # the table was withdrawn between partition and
                # dispatch: central wire, loss-free
                self._register_chan(leftover, chan)
                try:
                    self._post_many(leftover)
                except Exception:
                    self._unregister_chan(leftover)
                    raise

    def _register_chan(self, events, chan) -> None:
        with self._lock:
            w = self._waiters
            for event in events:
                w[event.uuid] = chan

    def _unregister_chan(self, events) -> None:
        with self._lock:
            pop = self._waiters.pop
            for event in events:
                pop(event.uuid, None)

    def send_notification(self, event: Event) -> None:
        """Send an observation-only event without awaiting any action."""
        _context.ensure(event)
        self._post(event)

    def forget(self, event: Event) -> None:
        """Drop the waiter for ``event`` (e.g. after a local timeout)."""
        with self._lock:
            self._waiters.pop(event.uuid, None)

    def _post(self, event: Event) -> None:
        raise NotImplementedError

    # called by the receive path
    def dispatch_action(self, action: Action) -> None:
        with self._lock:
            ch = self._waiters.pop(action.event_uuid, None)
        if ch is None:
            log.warning(
                "%s: action for unknown event %s (%r)",
                self.entity_id, action.event_uuid[:8], action,
            )
            return
        ch.put(action)

    def dispatch_actions(self, actions) -> None:
        """Batch variant of :meth:`dispatch_action`: every waiter is
        resolved under ONE lock acquisition, then the hand-offs happen
        outside it — the edge dispatcher's burst delivery path, where
        a per-action lock round would dominate the zero-RTT budget."""
        with self._lock:
            pop = self._waiters.pop
            resolved = [(pop(a.event_uuid, None), a) for a in actions]
        for ch, action in resolved:
            if ch is None:
                log.warning(
                    "%s: action for unknown event %s (%r)",
                    self.entity_id, action.event_uuid[:8], action,
                )
            else:
                ch.put(action)


class BurstHandle:
    """The join side of :meth:`Transceiver.send_events_burst`: one
    channel receiving grouped :class:`BurstAccept` verdicts (counting
    ``count`` events each) and individual actions (counting 1) until
    the whole burst is answered."""

    __slots__ = ("chan", "expected")

    def __init__(self, chan, expected: int) -> None:
        self.chan = chan
        self.expected = expected

    def get_all(self, timeout: Optional[float] = None) -> list:
        """Every verdict for the burst, blocking up to ``timeout``
        (``queue.Empty`` on expiry). The list holds BurstAccept groups
        and/or per-event actions; the counts always total
        ``expected``."""
        import time as _time

        out: list = []
        answered = 0
        deadline = (None if timeout is None
                    else _time.monotonic() + timeout)
        get = self.chan.get
        while answered < self.expected:
            if deadline is None:
                item = get()
            else:
                item = get(timeout=max(0.0,
                                       deadline - _time.monotonic()))
            out.append(item)
            answered += getattr(item, "count", 1)
        return out


class UnackedReplayMixin:
    """Client side of the reconnect-and-replay window, shared by the
    wire transceivers (REST, uds): a bounded insertion-ordered ring of
    posted-but-unanswered deferred events, popped as their actions
    arrive and re-offered after a transport recovery (server-side
    dedupe makes the replay idempotent — doc/robustness.md). Subclasses
    provide ``batch_max``, a ``_replay_armed`` flag their receive loop
    sets on transport errors, and :meth:`_replay_chunk`."""

    #: bound on the posted-but-unanswered ring (an orchestrator would
    #: have to park this many of ONE entity's deferred events for
    #: replay coverage to shrink)
    UNACKED_CAP = 1024

    def _init_unacked(self) -> None:
        from collections import OrderedDict

        self._unacked: "OrderedDict[str, Event]" = OrderedDict()
        self._unacked_lock = threading.Lock()

    def _note_posted(self, events) -> None:
        """Track successfully-posted deferred events until their action
        arrives (the reconnect-and-replay window)."""
        with self._unacked_lock:
            for event in events:
                if getattr(event, "deferred", False):
                    self._unacked[event.uuid] = event
            while len(self._unacked) > self.UNACKED_CAP:
                self._unacked.popitem(last=False)

    def dispatch_action(self, action) -> None:
        # the event is answered: it leaves the replay window before the
        # waiter hand-off (a replay racing this ack at worst re-posts an
        # already-answered uuid, which the dedupe ring absorbs)
        with self._unacked_lock:
            self._unacked.pop(action.event_uuid, None)
        super().dispatch_action(action)

    def dispatch_actions(self, actions) -> None:
        with self._unacked_lock:
            pop = self._unacked.pop
            for action in actions:
                pop(action.event_uuid, None)
        super().dispatch_actions(actions)

    def _replay_chunk(self, chunk, entity: str) -> None:
        """One ``batch_max``-bounded re-post on the subclass's wire."""
        raise NotImplementedError

    def _replay_unacked(self) -> None:
        """Re-post every posted-but-unanswered deferred event after the
        server came back: against the same process the dedupe ring
        answers ``duplicate``; against a restarted one the
        journal-seeded ring dedupes recovered events and accepts the
        rest fresh — either way the events exist server-side exactly
        once afterwards. Best-effort: a replay that fails rides the
        next reconnect (the loop re-arms on the next poll error)."""
        with self._unacked_lock:
            events = list(self._unacked.values())
        if not events:
            return
        log.warning("transport recovered; replaying %d unacked "
                    "event(s) (server-side dedupe makes this "
                    "idempotent)", len(events))
        by_entity: "dict[str, list]" = {}
        for event in events:
            by_entity.setdefault(event.entity_id, []).append(event)
        for entity, batch in by_entity.items():
            for i in range(0, len(batch), self.batch_max):
                try:
                    self._replay_chunk(batch[i:i + self.batch_max],
                                       entity)
                except Exception as e:
                    log.debug("unacked replay failed (%s); will retry "
                              "on the next reconnect", e)
                    self._replay_armed = True
                    return


class LocalTransceiver(Transceiver):
    """In-process transceiver over a LocalEndpoint."""

    def __init__(self, entity_id: str, endpoint: LocalEndpoint):
        super().__init__(entity_id)
        self._endpoint = endpoint

    def start(self) -> None:
        self._endpoint.connect(self.entity_id, self.dispatch_action)

    def shutdown(self) -> None:
        self._endpoint.disconnect(self.entity_id)

    def _post(self, event: Event) -> None:
        if event.entity_id != self.entity_id:
            raise ValueError(
                f"event entity {event.entity_id!r} != transceiver {self.entity_id!r}"
            )
        self._endpoint.post_event(event)


def new_transceiver(
    url: str,
    entity_id: str,
    local_endpoint: Optional[LocalEndpoint] = None,
    edge: bool = False,
    edge_shards: int = 0,
    codec: str = "auto",
) -> Transceiver:
    """Factory, parity transceiver.go:21-31. ``edge_shards`` > 1 joins
    the process-global shard pool; ``codec`` is the per-connection
    wire-codec preference (doc/performance.md "Binary wire + sharded
    edge")."""
    if url.startswith("local://"):
        if local_endpoint is None:
            raise ValueError("local:// requires a LocalEndpoint instance")
        return LocalTransceiver(entity_id, local_endpoint)
    if url.startswith(("http://", "https://")):
        from namazu_tpu.inspector.rest_transceiver import RestTransceiver

        return RestTransceiver(entity_id, url, edge=edge,
                               edge_shards=edge_shards, codec=codec)
    if url.startswith("uds://"):
        from namazu_tpu.inspector.uds_transceiver import UdsTransceiver

        return UdsTransceiver(entity_id, url[len("uds://"):], edge=edge,
                              edge_shards=edge_shards, codec=codec)
    if url.startswith("shm://"):
        # the uds control wire + a shared-memory ring for the event
        # direction (endpoint/shm.py): the path names the uds socket
        from namazu_tpu.inspector.uds_transceiver import UdsTransceiver

        return UdsTransceiver(entity_id, url[len("shm://"):],
                              edge=edge, edge_shards=edge_shards,
                              codec=codec, shm=True)
    if url.startswith("agent://"):
        from namazu_tpu.inspector.agent_transceiver import AgentTransceiver

        host, _, port = url[len("agent://"):].rpartition(":")
        return AgentTransceiver(entity_id, host or "127.0.0.1", int(port))
    raise ValueError(f"unsupported transceiver url {url!r}")
