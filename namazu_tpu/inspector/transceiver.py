"""Transceivers: the inspector-side RPC clients.

Parity: /root/reference/nmz/inspector/transceiver (transceiver.go:15-31):
``send_event`` registers a per-event action queue *before* the event leaves
the process (closing the race noted in localtransceiver.go:40-44), returns
that queue, and a receive loop correlates incoming actions back by their
``event_uuid``.

``new_transceiver(url, entity_id)`` dispatches on scheme: ``local://`` for
the in-process endpoint (autopilot/tests), ``http(s)://`` for REST.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, Optional

from namazu_tpu.endpoint.local import LocalEndpoint
from namazu_tpu.signal.action import Action
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.log import get_logger

log = get_logger("transceiver")


class Transceiver:
    def __init__(self, entity_id: str):
        self.entity_id = entity_id
        self._waiters: Dict[str, "queue.Queue[Action]"] = {}
        self._lock = threading.Lock()

    def start(self) -> None:
        pass

    def shutdown(self) -> None:
        pass

    def send_event(self, event: Event) -> "queue.Queue[Action]":
        """Send ``event``; returns a queue that will receive the answering
        action(s). The queue is registered before sending.

        Only use for events whose answer is *propagated* back (deferred
        events, and ProcSetEvent which is answered out-of-band). For
        observation-only events (LogEvent, NopEvent) use
        :meth:`send_notification` — their default NopAction is
        orchestrator-side-only and never comes back, so a registered
        waiter would leak.
        """
        ch: "queue.Queue[Action]" = queue.Queue()
        with self._lock:
            self._waiters[event.uuid] = ch
        try:
            self._post(event)
        except Exception:
            with self._lock:
                self._waiters.pop(event.uuid, None)
            raise
        return ch

    def send_notification(self, event: Event) -> None:
        """Send an observation-only event without awaiting any action."""
        self._post(event)

    def forget(self, event: Event) -> None:
        """Drop the waiter for ``event`` (e.g. after a local timeout)."""
        with self._lock:
            self._waiters.pop(event.uuid, None)

    def _post(self, event: Event) -> None:
        raise NotImplementedError

    # called by the receive path
    def dispatch_action(self, action: Action) -> None:
        with self._lock:
            ch = self._waiters.pop(action.event_uuid, None)
        if ch is None:
            log.warning(
                "%s: action for unknown event %s (%r)",
                self.entity_id, action.event_uuid[:8], action,
            )
            return
        ch.put(action)


class LocalTransceiver(Transceiver):
    """In-process transceiver over a LocalEndpoint."""

    def __init__(self, entity_id: str, endpoint: LocalEndpoint):
        super().__init__(entity_id)
        self._endpoint = endpoint

    def start(self) -> None:
        self._endpoint.connect(self.entity_id, self.dispatch_action)

    def shutdown(self) -> None:
        self._endpoint.disconnect(self.entity_id)

    def _post(self, event: Event) -> None:
        if event.entity_id != self.entity_id:
            raise ValueError(
                f"event entity {event.entity_id!r} != transceiver {self.entity_id!r}"
            )
        self._endpoint.post_event(event)


def new_transceiver(
    url: str,
    entity_id: str,
    local_endpoint: Optional[LocalEndpoint] = None,
) -> Transceiver:
    """Factory, parity transceiver.go:21-31."""
    if url.startswith("local://"):
        if local_endpoint is None:
            raise ValueError("local:// requires a LocalEndpoint instance")
        return LocalTransceiver(entity_id, local_endpoint)
    if url.startswith(("http://", "https://")):
        from namazu_tpu.inspector.rest_transceiver import RestTransceiver

        return RestTransceiver(entity_id, url)
    if url.startswith("agent://"):
        from namazu_tpu.inspector.agent_transceiver import AgentTransceiver

        host, _, port = url[len("agent://"):].rpartition(":")
        return AgentTransceiver(entity_id, host or "127.0.0.1", int(port))
    raise ValueError(f"unsupported transceiver url {url!r}")
