"""Process inspector: watch a process tree, let the policy re-schedule it.

Parity: /root/reference/nmz/inspector/proc/proc.go:53-172 — every
``watch_interval`` the inspector snapshots the target's descendant LWP set,
sends a ``ProcSetEvent``, awaits the policy's ``ProcSetSchedAction``, and
applies the per-thread scheduler attributes via sched_setattr(2) (EPERM and
vanished threads are logged and skipped).

This is the highest-leverage inspector for flaky-test reproduction
(YARN-4548 et al., BASELINE.md) because it needs no packet/filesystem
interception — just procfs and one syscall.
"""

from __future__ import annotations

import queue as _queue
import threading
from typing import Optional

from namazu_tpu.inspector.transceiver import Transceiver
from namazu_tpu.signal.action import ProcSetSchedAction
from namazu_tpu.signal.event import ProcSetEvent
from namazu_tpu.utils import linuxsched, procfs
from namazu_tpu.utils.log import get_logger

log = get_logger("inspector.proc")


class ProcInspector:
    def __init__(
        self,
        transceiver: Transceiver,
        root_pid: int,
        entity_id: str = "_nmz_proc_inspector",
        watch_interval: float = 1.0,
        action_timeout: float = 10.0,
        apply_sched: bool = True,
    ):
        self.trans = transceiver
        self.root_pid = root_pid
        self.entity_id = entity_id
        self.watch_interval = watch_interval
        self.action_timeout = action_timeout
        self.apply_sched = apply_sched
        self._stop = threading.Event()
        self.watch_count = 0
        self.apply_errors = 0

    # -- main loop -------------------------------------------------------

    def serve(self) -> None:
        """Blocking watch loop; returns when stop() is called or the
        target disappears (parity: Serve, proc.go:53-91)."""
        self.trans.start()
        while not self._stop.wait(self.watch_interval):
            pids = [self.root_pid, *procfs.descendant_lwps(self.root_pid)]
            pids = sorted(set(pids))
            if not procfs.lwps(self.root_pid):
                log.info("target pid %d is gone; stopping", self.root_pid)
                return
            self.on_watch(pids)

    def stop(self) -> None:
        self._stop.set()

    # -- one tick --------------------------------------------------------

    def on_watch(self, pids: list[int]) -> None:
        self.watch_count += 1
        event = ProcSetEvent.create(self.entity_id, pids)
        ch = self.trans.send_event(event)
        try:
            action = ch.get(timeout=self.action_timeout)
        except _queue.Empty:
            # policy chose not to answer (e.g. passthrough); nothing to do
            self.trans.forget(event)
            log.debug("no sched action within %.1fs", self.action_timeout)
            return
        if isinstance(action, ProcSetSchedAction):
            self.on_action(action)
        else:
            log.debug("ignoring non-sched action %r", action)

    def on_action(self, action: ProcSetSchedAction) -> None:
        """Apply per-thread attrs (parity: onAction, proc.go:148-172)."""
        if not self.apply_sched:
            return
        for pid_str, attrs in action.attrs.items():
            try:
                linuxsched.set_attr(int(pid_str), attrs)
            except (linuxsched.SchedError, ValueError) as e:
                self.apply_errors += 1
                log.debug("sched_setattr pid %s: %s", pid_str, e)


def serve_with_command(
    transceiver: Transceiver,
    cmd: list[str],
    entity_id: str = "_nmz_proc_inspector",
    watch_interval: float = 1.0,
    stdout=None,
    stderr=None,
) -> int:
    """Spawn ``cmd``, fuzz its process tree until it exits, return its exit
    status (parity: the ``-cmd`` mode of cli/inspectors/proc.go:58-137)."""
    import subprocess

    child = subprocess.Popen(cmd, stdout=stdout, stderr=stderr)
    inspector = ProcInspector(
        transceiver, child.pid, entity_id=entity_id,
        watch_interval=watch_interval,
    )
    t = threading.Thread(target=inspector.serve, daemon=True)
    t.start()
    try:
        rc = child.wait()
    finally:
        inspector.stop()
        t.join(timeout=5)
    return rc
