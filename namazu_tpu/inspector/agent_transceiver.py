"""Python client for the guest-agent framed-TCP protocol.

The Python counterpart of the C++ agent (native/agent): useful for Python
testee processes and as the protocol reference implementation. URL scheme:
``agent://host:port`` (see new_transceiver).
"""

from __future__ import annotations

import socket
import threading

from namazu_tpu.endpoint.agent import read_frame, write_frame
from namazu_tpu.inspector.transceiver import Transceiver
from namazu_tpu.signal.action import Action
from namazu_tpu.signal.base import signal_from_jsonable
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.log import get_logger

log = get_logger("transceiver.agent")


class AgentTransceiver(Transceiver):
    def __init__(self, entity_id: str, host: str, port: int):
        super().__init__(entity_id)
        self._addr = (host, port)
        self._sock: socket.socket | None = None
        self._send_lock = threading.Lock()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self._sock is not None:
            return
        self._sock = socket.create_connection(self._addr, timeout=30)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._thread = threading.Thread(
            target=self._receive_loop, name=f"agent-recv-{self.entity_id}",
            daemon=True,
        )
        self._thread.start()

    def shutdown(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _post(self, event: Event) -> None:
        if self._sock is None:
            self.start()
        with self._send_lock:
            write_frame(self._sock, event.to_jsonable())

    def _receive_loop(self) -> None:
        sock = self._sock
        while sock is not None:
            frame = read_frame(sock)
            if frame is None:
                return
            try:
                action = signal_from_jsonable(frame)
            except Exception as e:
                log.warning("bad action frame: %s", e)
                continue
            if isinstance(action, Action):
                self.dispatch_action(action)
