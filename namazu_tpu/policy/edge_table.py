"""Table publication: the zero-RTT dispatch plane's versioned source.

The TPU policy's steady-state decision is a pure function of the event
hint — fnv64a bucket -> delay-table lookup (policy/replayable.py,
policy/tpu.py). That makes the decision *publishable*: the orchestrator
versions the currently-installed hash->delay table and serves it to
endpoints and transceivers (``GET /api/v3/policy/table``, the
``table`` op on the UDS wire, and piggybacked version headers on batch
responses), so an edge holding a current table computes each event's
delay locally and releases it without phoning home first
(doc/performance.md "Zero-RTT dispatch").

:class:`TablePublisher` is that source of truth. The contract:

* ``version`` is **monotonic** and bumps on *every* state change —
  every search-plane install (eligible or not), every suspend/resume.
  An edge comparing its held version against any piggybacked version
  can therefore always detect staleness, and no event is ever decided
  under an ambiguous version (each decision captures the version of
  the exact table object it used).
* ``current()`` returns ``(version, doc_or_None)``. ``None`` means
  "this version has no publishable table" — the policy installed a
  fault-bearing or reorder-mode table, orchestration is disabled, or
  nothing was ever installed. Edges holding no doc fall back
  transparently to the central (PR 5 batched) wire, so non-table
  policies and cold-start windows are untouched.

The published doc is plain JSON::

    {"version": V, "mode": "delay", "H": H,
     "max_interval": S, "delays": [float x H]}

Decision semantics are pinned bit-for-bit: the edge computes
``delays[fnv64a(hint) % H]`` — exactly the central
``TPUSearchPolicy._delay_for`` — and JSON round-trips IEEE doubles
exactly, so an edge-decided run and a central run over the same seed
produce identical delays (the trace-differ equivalence test).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Tuple

from namazu_tpu import obs

__all__ = ["TablePublisher", "TABLE_VERSION_HEADER"]

#: the HTTP header piggybacking the current table version on batch
#: POST / batch poll responses (the UDS wire carries the same value as
#: a ``table_version`` response field)
TABLE_VERSION_HEADER = "X-Nmz-Table-Version"


class TablePublisher:
    """Thread-safe versioned holder of the publishable delay table."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._version = 0
        self._doc: Optional[Dict[str, Any]] = None
        self._suspended = False

    @property
    def version(self) -> int:
        # int read is GIL-atomic: the hot path (per-decision version
        # tagging) must not pay a lock for it
        return self._version

    def publish(self, delays, H: int, max_interval: float) -> int:
        """Install ``delays`` as the new publishable table; returns the
        new version. ``delays`` is any float sequence of length H."""
        doc = {
            "mode": "delay",
            "H": int(H),
            "max_interval": float(max_interval),
            "delays": [float(x) for x in delays],
            # install stamp for nmz_table_propagation_seconds: a
            # same-host edge that adopts this doc observes
            # monotonic() - installed_mono (cross-host docs skip the
            # observation — monotonic clocks don't compare)
            "installed_mono": time.monotonic(),
        }
        with self._lock:
            self._version += 1
            doc["version"] = self._version
            self._doc = doc
            version = self._version
        obs.table_version(version)
        return version

    def publish_none(self) -> int:
        """The current install is NOT edge-eligible (fault-bearing,
        reorder mode): bump the version and withdraw the doc, so edges
        holding an older table notice within one batch and fall back to
        the central wire — loss-free."""
        with self._lock:
            self._version += 1
            self._doc = None
            version = self._version
        obs.table_version(version)
        return version

    def suspend(self) -> None:
        """Hide the doc (orchestration disabled): edges must stop
        deciding locally — central decisions now come from the
        passthrough ``dumb`` policy, not the table."""
        with self._lock:
            if self._suspended:
                return
            self._suspended = True
            self._version += 1
            version = self._version
        obs.table_version(version)

    def resume(self) -> None:
        """Re-expose the held doc (orchestration re-enabled)."""
        with self._lock:
            if not self._suspended:
                return
            self._suspended = False
            self._version += 1
            if self._doc is not None:
                self._doc = dict(self._doc, version=self._version)
            version = self._version
        obs.table_version(version)

    def current(self) -> Tuple[int, Optional[Dict[str, Any]]]:
        """``(version, doc_or_None)`` — the doc always carries its own
        version (a fetched table can never be mis-attributed)."""
        with self._lock:
            if self._suspended:
                return self._version, None
            return self._version, self._doc
