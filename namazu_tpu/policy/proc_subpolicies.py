"""Process-scheduling sub-policies used by the random policy.

Given the PID set of a ProcSetEvent, produce per-PID scheduler attributes.
Parity with /root/reference/nmz/explorepolicy/random/{mild,extreme,
dirichlet}.go. Attribute dicts are consumed by the proc inspector's
``sched_setattr(2)`` shim (namazu_tpu.inspector.proc).
"""

from __future__ import annotations

import os
import random
from typing import Any, Dict, List, Sequence


AttrMap = Dict[str, Dict[str, Any]]


class ProcSubPolicy:
    NAME = "abstract"

    def __init__(self, rng: random.Random):
        self.rng = rng

    def load_params(self, params: Dict[str, Any]) -> None:
        pass

    def attrs_for(self, pids: Sequence[int]) -> AttrMap:
        raise NotImplementedError


class MildProcPolicy(ProcSubPolicy):
    """SCHED_NORMAL or SCHED_BATCH with a random nice value.

    Parity: mild.go:29-55.
    """

    NAME = "mild"

    def attrs_for(self, pids: Sequence[int]) -> AttrMap:
        out: AttrMap = {}
        for pid in pids:
            policy = self.rng.choice(["SCHED_NORMAL", "SCHED_BATCH"])
            out[str(pid)] = {"policy": policy, "nice": self.rng.randrange(-20, 20)}
        return out


class ExtremeProcPolicy(ProcSubPolicy):
    """A few prioritized threads get real-time SCHED_RR; the rest are
    demoted to SCHED_BATCH — the harshest legal starvation.

    Parity: extreme.go:29-61 (``prioritized`` default 3).
    """

    NAME = "extreme"

    def __init__(self, rng: random.Random):
        super().__init__(rng)
        self.prioritized = 3

    def load_params(self, params: Dict[str, Any]) -> None:
        self.prioritized = int(params.get("prioritized", self.prioritized))

    def attrs_for(self, pids: Sequence[int]) -> AttrMap:
        pids = list(pids)
        k = min(self.prioritized, len(pids))
        chosen = set(self.rng.sample(pids, k)) if k else set()
        out: AttrMap = {}
        for pid in pids:
            if pid in chosen:
                out[str(pid)] = {
                    "policy": "SCHED_RR",
                    "rt_priority": 1 + self.rng.randrange(0, 10),
                }
            else:
                out[str(pid)] = {"policy": "SCHED_BATCH", "nice": 0}
        return out


class DirichletProcPolicy(ProcSubPolicy):
    """SCHED_DEADLINE runtimes drawn from a Dirichlet distribution, so the
    CPU shares of the testee's threads are randomly but fairly skewed.

    Parity: dirichlet.go:38-86 — runtime_i = base * r_i * efficiency *
    n_cpu with r ~ Dirichlet(1); with ``reset_probability`` everything is
    reset to SCHED_NORMAL to let the system recover.
    """

    NAME = "dirichlet"

    def __init__(self, rng: random.Random):
        super().__init__(rng)
        self.base_ns = 10_000_000  # 10ms period base
        self.efficiency = 0.8
        self.reset_probability = 0.1

    def load_params(self, params: Dict[str, Any]) -> None:
        self.base_ns = int(params.get("base_ns", self.base_ns))
        self.efficiency = float(params.get("efficiency", self.efficiency))
        self.reset_probability = float(
            params.get("reset_probability", self.reset_probability)
        )

    def _dirichlet(self, n: int) -> List[float]:
        # Dirichlet(1,...,1) via normalized exponentials; no numpy needed
        xs = [self.rng.expovariate(1.0) for _ in range(n)]
        s = sum(xs) or 1.0
        return [x / s for x in xs]

    def attrs_for(self, pids: Sequence[int]) -> AttrMap:
        pids = list(pids)
        if not pids:
            return {}
        if self.rng.random() < self.reset_probability:
            return {str(p): {"policy": "SCHED_NORMAL", "nice": 0} for p in pids}
        ncpu = os.cpu_count() or 1
        shares = self._dirichlet(len(pids))
        out: AttrMap = {}
        for pid, r in zip(pids, shares):
            runtime = max(1024, int(self.base_ns * r * self.efficiency * ncpu))
            runtime = min(runtime, self.base_ns)
            out[str(pid)] = {
                "policy": "SCHED_DEADLINE",
                "runtime_ns": runtime,
                "deadline_ns": self.base_ns,
                "period_ns": self.base_ns,
            }
        return out


PROC_SUBPOLICIES = {
    cls.NAME: cls for cls in (MildProcPolicy, ExtremeProcPolicy, DirichletProcPolicy)
}


def create_proc_subpolicy(name: str, rng: random.Random) -> ProcSubPolicy:
    try:
        return PROC_SUBPOLICIES[name](rng)
    except KeyError:
        raise ValueError(
            f"unknown proc sub-policy {name!r}; known: {sorted(PROC_SUBPOLICIES)}"
        ) from None
