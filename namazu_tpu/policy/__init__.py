"""Exploration policies: the pluggable scheduler brains.

Capability parity with /root/reference/nmz/explorepolicy (interface.go:24-40,
explorepolicy.go:25-37): a policy receives every intercepted event via
``queue_event`` (which must never block) and emits actions on its
``action_out`` queue whenever it decides an event should be released,
faulted, or a process set re-scheduled.

Built-ins:

* ``dumb``       — passthrough with a fixed interval.
* ``random``     — delay each event uniformly in [min,max]; probabilistic
                   faults; proc sub-policies mild/extreme/dirichlet;
                   periodic shell injection.
* ``replayable`` — semi-deterministic delays hashed from (seed, replay hint).
* ``tpu_search`` — the JAX/TPU schedule-search policy (namazu_tpu.policy.tpu);
                   registered lazily on first use to keep jax out of the
                   control plane's import path.

Out-of-tree policies register themselves with :func:`register_policy` —
the plugin boundary user experiments rely on (parity:
/root/reference/example/template/mypolicy.go).
"""

from namazu_tpu.policy.base import (
    ExplorePolicy,
    PolicyError,
    register_policy,
    create_policy,
    known_policies,
)
from namazu_tpu.policy.dumb import DumbPolicy
from namazu_tpu.policy.random_policy import RandomPolicy
from namazu_tpu.policy.replayable import ReplayablePolicy

__all__ = [
    "ExplorePolicy",
    "PolicyError",
    "register_policy",
    "create_policy",
    "known_policies",
    "DumbPolicy",
    "RandomPolicy",
    "ReplayablePolicy",
]
