"""Config-driven out-of-tree policy loading.

The reference's plugin story is "compile your own main() that calls
RegisterPolicy then delegates to the CLI"
(/root/reference/example/template/mypolicy.go:73-80) — workable for Go,
but it means every custom policy ships a whole binary. Python can do
better: the ``policy_plugins`` config key names modules or ``.py`` files
(relative paths resolve against the experiment's materials dir, so
``init`` versions the plugin with the experiment) that ``run`` imports
before creating the policy; each plugin registers itself at import via
:func:`namazu_tpu.policy.register_policy`, exactly like the built-ins.

The reference-style flow still works too — a plugin file with a
``__main__`` block delegating to ``cli_main`` is its own driver
(examples/template/materials/mypolicy.py shows both).
"""

from __future__ import annotations

import hashlib
import importlib
import importlib.util
import os
import sys
from typing import Optional

from namazu_tpu.utils.log import get_logger

log = get_logger("policy.plugins")

#: plugins already executed, keyed by CONTENT digest (basename +
#: sha256 of the file, or the module path) — loads are idempotent so
#: that multiple ``run`` invocations inside one process (the ab
#: harness, the test suite) don't re-execute module bodies and trip the
#: registry's duplicate-name guard. Content keying matters because
#: ``init`` copies the plugin into every storage's materials dir: the
#: same plugin loaded from two storages is one plugin, not a duplicate
#: registration
_LOADED: set = set()


def _plugin_digest(path: str) -> str:
    with open(path, "rb") as f:
        sha = hashlib.sha256(f.read()).hexdigest()
    return f"{os.path.basename(path)}:{sha}"


def load_policy_plugins(cfg, materials_dir: Optional[str] = None) -> None:
    """Import every entry of the config's ``policy_plugins`` list.

    Entries ending in ``.py`` are loaded as files (relative to
    ``materials_dir`` when given); anything else is imported as a module
    path. A broken plugin fails the run loudly — a silently missing
    policy would let the experiment fall back to nothing.
    """
    plugins = cfg.get("policy_plugins", []) or []
    if isinstance(plugins, str):
        plugins = [plugins]
    for spec in plugins:
        spec = str(spec)
        if spec.endswith(".py"):
            path = spec
            if not os.path.isabs(path) and materials_dir:
                cand = os.path.join(materials_dir, path)
                if os.path.exists(cand):
                    path = cand
            path = os.path.abspath(path)
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"policy plugin {spec!r} not found (looked at "
                    f"{path}; relative paths resolve against the "
                    "materials dir)")
            digest = _plugin_digest(path)
            if digest in _LOADED:
                continue
            # content-suffixed module name: two DIFFERENT plugins sharing
            # a basename must not evict each other from sys.modules
            name = ("nmz_policy_plugin_"
                    + os.path.splitext(os.path.basename(path))[0]
                    + "_" + digest.rsplit(":", 1)[1][:12])
            loader_spec = importlib.util.spec_from_file_location(name, path)
            module = importlib.util.module_from_spec(loader_spec)
            # registered in sys.modules BEFORE exec so dataclasses,
            # pickling, and self-imports inside the plugin resolve
            sys.modules[name] = module
            try:
                loader_spec.loader.exec_module(module)
            except BaseException:
                sys.modules.pop(name, None)
                raise
            _LOADED.add(digest)
            log.info("loaded policy plugin %s", path)
        else:
            if spec in _LOADED:
                continue
            importlib.import_module(spec)
            _LOADED.add(spec)
            log.info("loaded policy plugin module %s", spec)
