"""tpu_search policy: replay the best schedule found by the TPU search.

The BASELINE.json north-star component: behind the same ``register_policy``
plugin boundary as every other policy, but its delays are not random — they
come from a per-hint-bucket delay table evolved by the island GA
(namazu_tpu/models/search.py) against the experiment's recorded history.

Division of labor (latency budget, SURVEY.md section 7):

* **off the critical path**: at policy start (and between runs), a
  background thread featurizes stored traces, adds them to the novelty/
  failure archives, runs GA generations on the device mesh, and installs
  the best ``delays[H]`` / ``faults[H]`` tables atomically;
* **on the critical path**: each event costs one fnv64a hash + one table
  lookup, then rides the same ScheduledQueue as every other policy. Until
  the first search finishes, delays fall back to the replayable policy's
  hash(seed, hint) — so the policy is never worse than `replayable`.

Fault decisions are deterministic per (seed, hint) so a found schedule
replays exactly.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from namazu_tpu import obs
from namazu_tpu.policy.base import QueueBackedPolicy, register_policy
from namazu_tpu.policy.edge_table import TablePublisher
from namazu_tpu.policy.replayable import (
    fnv64a,
    fnv64a_many,
    hint_delay,
    hint_delays,
)
from namazu_tpu.signal.action import ProcSetSchedAction
from namazu_tpu.signal.event import Event, ProcSetEvent
from namazu_tpu.policy.proc_subpolicies import create_proc_subpolicy
from namazu_tpu.utils.config import parse_duration
from namazu_tpu.utils.log import get_logger

log = get_logger("policy.tpu")


class TPUSearchPolicy(QueueBackedPolicy):
    NAME = "tpu_search"

    def __init__(self) -> None:
        super().__init__()
        self.seed = 0
        self.max_interval = 0.1
        self.generations = 64
        self.population = 4096
        self.H = 256
        self.L = 0  # trace-length cap; 0 = encode full traces (no drop)
        self.K = 256
        self.migrate_k = 8
        # fused search loop (doc/performance.md "Fused search loop"):
        # the whole generation loop runs device-side in fused_chunk-
        # generation scans with donated buffers and device-resident
        # traces/archives — bit-exact with the per-generation path
        # (pinned by test), so the knob is a dispatch-shape choice, not
        # a semantics one. fused = false restores the pre-fusion loop.
        self.fused = True
        self.fused_chunk = 16
        # device-trace capture knob (doc/observability.md "Profiling"):
        # non-empty = the FIRST fused evolve of this search dumps a
        # jax.profiler device trace under <dir>/device_trace, folding
        # device time into the nmz_search_phase_seconds host-side story.
        # One-shot per search object; "" (default) = off.
        self.device_trace_dir = ""
        # migration cadence, decoupled from the generation count: the
        # intra-host ICI ring permutes every migrate_every generations;
        # on a hybrid host x chip mesh (dcn_hosts > 1) the cross-host
        # ring only every dcn_migrate_every. Both default 1 — the
        # pre-cadence behavior bit-for-bit, and the same default the
        # sidecar's params builder uses — so an upgrade never silently
        # changes a multi-host search; set dcn_migrate_every = 4 on a
        # DCN mesh to keep the slow fabric off the critical path
        # (parallel/distributed.py hier_rings, doc/performance.md)
        self.migrate_every = 1
        self.dcn_migrate_every = 1
        self.n_devices: Optional[int] = None
        self.checkpoint_path = ""
        self.search_on_start = True
        self.search_join_timeout = 120.0  # shutdown waits this long
        # persistent search sidecar address ("host:port"; "" = search
        # in-process). The sidecar (namazu_tpu/sidecar.py) holds the
        # compiled search and device state across `run` processes, so a
        # per-run search request costs one ingest + warm generations
        # instead of rebuild + jit warm-up.
        self.sidecar = ""
        # evolve every Nth run (1 = every run). The installed schedule
        # always comes from the checkpoint (cheap np.load), but the
        # evolve+ingest+save cycle costs seconds of wall-clock per `run`
        # process; on experiments whose runs last ~2 s that overhead
        # halves repros/hour. N>1 amortizes it: N-1 install-only runs,
        # then one evolution over the batch of new outcomes.
        self.search_every = 1
        self.max_fault = 0.0
        self.search_backend = "ga"  # "ga" (island GA) | "mcts" (config 5)
        # JAX platform for the search plane ("" = inherit the process
        # default). Policy searches run inside short-lived `run`
        # processes; on images where claiming the TPU can wedge for
        # minutes (see bench.py's init probe) a config-2-sized search is
        # far better off on CPU — set platform = "cpu" there and keep
        # the TPU for big standalone searches.
        self.platform = ""
        self.dcn_hosts = 0  # >1: hybrid host x chip mesh (multi-host DCN)
        # release modes (BASELINE config 3): "delay" replays the table as
        # literal per-hint delays; "reorder" treats it as per-hint
        # *priorities* — events buffered for reorder_window seconds are
        # released in priority order, a true permutation even when delays
        # could not invert the arrivals
        self.release_mode = "delay"
        self.reorder_window = 0.05
        self.reorder_gap = 0.002
        # (prio, seq, t_arrive, event) under _pending_lock
        self._pending: list = []
        self._pending_lock = threading.Lock()
        self._pending_seq = 0
        self._reorder_thread: Optional[threading.Thread] = None
        self._stop_reorder = threading.Event()
        # window clock anchor = monotonic arrival of the FIRST queued
        # event; the scorer anchors windows at the trace's first arrival
        # (ops/schedule.py order_release_times), so both planes cut
        # window boundaries at the same offsets
        self._anchor: Optional[float] = None
        self._anchor_set = threading.Event()
        # injectable clock: every reorder-window decision (arrival
        # stamping, window-boundary ticks, closed-window drains) reads
        # time through this hook, so the realized-vs-scored order
        # invariant is testable with a scripted clock and zero real
        # sleeps instead of margin-widened wall-clock waits
        self._now = time.monotonic
        self.mcts_simulations = 256
        self.mcts_tree_depth = 24
        self.mcts_levels = 8
        self.mcts_rollouts = 64
        self.surrogate_topk = 16  # 0 = fitness argmax only (no surrogate)
        # cross-batch failure-signature pool directory ("" = off); see
        # models/failure_pool.py. Relative paths anchor to the PARENT of
        # the storage dir (sibling experiments share one pool; anchoring
        # inside the storage would make every batch an island again).
        self.failure_pool = ""
        # knowledge-service address "host:port" ("" = off): the global
        # failure-knowledge plane (doc/knowledge.md). A cold run pulls
        # the fleet's warm-start (pooled signatures + the scenario's
        # best delay table) before its own history exists; every ingest
        # streams failures up; the shared surrogate ranks candidates
        # during the local model's cold-start window. Outages degrade to
        # local-only search — never to a failed run.
        self.knowledge = ""
        # scenario fingerprint override; "" = derived from the config's
        # run/validate scripts + hint space + H + release mode, so N
        # campaigns of one example land on one warm-start key without
        # coordination
        self.knowledge_scenario = ""
        self.scenario = ""
        # novelty anneal (GA backend): explore at full w_novelty until
        # the failure archive holds this many DISTINCT signatures, then
        # scale novelty down as the archive grows (SearchConfig docs).
        # 0 = static weights (pre-anneal behavior).
        self.min_failure_signatures = 0
        self.novelty_floor = 0.25
        # causality guidance (doc/search.md): make relation coverage —
        # which happens-before orderings the campaign has exercised —
        # a search objective. Off by default, and active only while the
        # obs plane is on (obs_enabled = false degrades to the exact
        # pre-guidance blind search — the guidance plane consumes
        # recorded structure, and with recording off it must cost and
        # change nothing).
        self.guidance_enabled = False
        self.guidance_bonus = 0.5
        self.guidance_width = 0  # 0 = guidance.DEFAULT_WIDTH
        self.guidance_window = 0  # 0 = guidance.DEFAULT_WINDOW
        # fitness weights (ops/schedule.py ScoreWeights). For pure
        # repro-rate maximization set w_novelty=0 so the search chases
        # the failure signature alone; the defaults balance exploration
        # (novel interleavings) against exploitation (bug affinity).
        self.w_novelty = 1.0
        self.w_bug = 1.0
        self.w_delay_cost = 0.01
        self.w_fault_cost = 0.05
        # precedence smoothing (seconds): the temporal resolution of the
        # feature embedding. Match it to the bug class's timing scale —
        # ms-level tau saturates on any ordering match, so the search
        # feels no pressure to reproduce the failure's timing MAGNITUDES
        # (a leader-election window is hundreds of ms, not an RTT)
        self.tau = 0.005
        # counterfactual anchor: "recent" = most recent success traces
        # (multi-trace averaging, good for novelty search); "envelope" =
        # per-bucket min-arrival envelope over successes. Traces now
        # record true event ARRIVALS (Action.event_arrived), so either
        # mode anchors on the system's interleaving, not the recording
        # policy's jitter; envelope remains useful as the tightest
        # cross-run lower bound for repro-rate maximization.
        self.reference_mode = "recent"
        self.proc_policy_name = "mild"
        import random as _random

        self._rng = _random.Random(0)
        self._proc_policy = create_proc_subpolicy("mild", self._rng)
        # installed schedule tables: ONE (delays, faults, version)
        # snapshot, rebound atomically — a decision reading it pairs a
        # delay with the version of the exact table that produced it,
        # even mid-install (the _delays/_faults properties are derived
        # views for the non-decision call sites)
        self._installed = None
        # zero-RTT dispatch (doc/performance.md): the versioned
        # publication of the installed delay table. The orchestrator
        # plugs this into its hub; endpoints serve it to edges. Every
        # install (eligible or not) bumps the version, so edges notice
        # staleness within one batch.
        self.table_publisher = TablePublisher()
        self._fault_coin = None  # cached per-(seed, H), see _coin_table
        self._search = None
        self._search_thread: Optional[threading.Thread] = None
        self._search_lock = threading.Lock()
        # set when the run is ending (shutdown/wait_for_search): the
        # sidecar evolve parks on this so it never competes with the
        # testee for CPU during the decisive window — its product is for
        # the NEXT run, which install-from-checkpoint covers
        self._run_ending = threading.Event()

    # -- config ----------------------------------------------------------

    def load_config(self, config) -> None:
        p = config.policy_param
        self.seed = int(p("seed", 0))
        self._rng.seed(self.seed)
        self._fault_coin = None  # seed/H may change below
        self.max_interval = parse_duration(p("max_interval", 100))
        self.generations = int(p("generations", self.generations))
        self.population = int(p("population", self.population))
        self.H = int(p("hint_buckets", self.H))
        self.L = int(p("trace_length", self.L))
        self.K = int(p("feature_pairs", self.K))
        self.migrate_k = int(p("migrate_k", self.migrate_k))
        self.fused = bool(p("fused", self.fused))
        self.fused_chunk = max(1, int(p("fused_chunk", self.fused_chunk)))
        self.device_trace_dir = str(
            p("device_trace_dir", self.device_trace_dir) or "")
        self.migrate_every = max(1, int(p("migrate_every",
                                          self.migrate_every)))
        self.dcn_migrate_every = max(1, int(p("dcn_migrate_every",
                                              self.dcn_migrate_every)))
        nd = p("devices", None)
        self.n_devices = int(nd) if nd is not None else None
        self.checkpoint_path = str(p("checkpoint", "") or "")
        self.search_on_start = bool(p("search_on_start", True))
        self.search_join_timeout = parse_duration(
            p("search_join_timeout", self.search_join_timeout * 1000))
        self.search_every = max(1, int(p("search_every", self.search_every)))
        self.sidecar = str(p("sidecar", self.sidecar) or "")
        if self.sidecar and not str(p("checkpoint", "") or ""):
            # the sidecar evolve runs at end-of-run and its product ships
            # to the NEXT run via the checkpoint; without one every
            # request is wasted work whose install lands in an exiting
            # process. Fail fast like the other config knobs.
            raise ValueError(
                "sidecar mode requires a checkpoint (the evolved schedule "
                "reaches the next run through it); set checkpoint = "
                "\"search.npz\""
            )
        self.max_fault = float(p("max_fault", 0.0))
        self.platform = str(p("platform", self.platform))
        self.search_backend = str(p("search_backend", self.search_backend))
        if self.search_backend not in ("ga", "mcts"):
            # fail fast: an exception inside the background search thread
            # would be logged-and-swallowed, silently degrading to hash
            # delays for the whole experiment
            raise ValueError(
                f"unknown search_backend {self.search_backend!r} "
                "(expected 'ga' or 'mcts')"
            )
        self.mcts_simulations = int(p("mcts_simulations",
                                      self.mcts_simulations))
        self.mcts_tree_depth = int(p("mcts_tree_depth",
                                     self.mcts_tree_depth))
        self.mcts_levels = int(p("mcts_levels", self.mcts_levels))
        self.mcts_rollouts = int(p("mcts_rollouts", self.mcts_rollouts))
        self.surrogate_topk = int(p("surrogate_topk", self.surrogate_topk))
        self.failure_pool = os.path.expanduser(os.path.expandvars(
            str(p("failure_pool", self.failure_pool) or "")))
        self.knowledge = str(p("knowledge", self.knowledge) or "")
        self.knowledge_scenario = str(
            p("knowledge_scenario", self.knowledge_scenario) or "")
        self.min_failure_signatures = int(
            p("min_failure_signatures", self.min_failure_signatures))
        self.novelty_floor = float(p("novelty_floor", self.novelty_floor))
        self.guidance_enabled = bool(p("guidance", self.guidance_enabled))
        self.guidance_bonus = float(
            p("guidance_bonus", self.guidance_bonus))
        self.guidance_width = int(
            p("guidance_bitmap_width", self.guidance_width))
        self.guidance_window = int(
            p("guidance_window", self.guidance_window))
        if (self.min_failure_signatures > 0
                and self.search_backend == "mcts"):
            log.warning(
                "novelty anneal (min_failure_signatures=%d) applies to "
                "the GA backend only; the mcts backend scores with "
                "static weights", self.min_failure_signatures)
        self.dcn_hosts = int(p("dcn_hosts", self.dcn_hosts))
        self.w_novelty = float(p("w_novelty", self.w_novelty))
        self.w_bug = float(p("w_bug", self.w_bug))
        self.w_delay_cost = float(p("w_delay_cost", self.w_delay_cost))
        self.w_fault_cost = float(p("w_fault_cost", self.w_fault_cost))
        self.tau = parse_duration(p("tau", self.tau * 1000))
        self.reference_mode = str(p("reference_mode", self.reference_mode))
        if self.reference_mode not in ("recent", "envelope"):
            raise ValueError(
                f"unknown reference_mode {self.reference_mode!r} "
                "(expected 'recent' or 'envelope')"
            )
        self.release_mode = str(p("release_mode", self.release_mode))
        if self.release_mode not in ("delay", "reorder"):
            raise ValueError(
                f"unknown release_mode {self.release_mode!r} "
                "(expected 'delay' or 'reorder')"
            )
        self.reorder_window = parse_duration(
            p("reorder_window", self.reorder_window * 1000))
        self.reorder_gap = parse_duration(
            p("reorder_gap", self.reorder_gap * 1000))
        if self.release_mode == "reorder" and self.reorder_window <= 0:
            # window=0 would mean "one global window" to the scorer but a
            # busy-spinning, continuously-draining loop to the control
            # plane — maximal scored/executed disagreement plus a pegged
            # CPU. Fail fast like the other enum knobs.
            raise ValueError(
                "reorder_window must be > 0 in reorder mode "
                f"(got {self.reorder_window})"
            )
        name = str(p("proc_policy", self.proc_policy_name))
        self.proc_policy_name = name
        self._proc_policy = create_proc_subpolicy(name, self._rng)
        self._proc_policy.load_params(p("proc_policy_param", {}) or {})
        # last: the fingerprint folds in knobs parsed above (H,
        # release_mode), so it must see their final values
        self.scenario = (self.knowledge_scenario
                         or self._scenario_fingerprint(config))

    # -- hot path ---------------------------------------------------------

    def _bucket(self, hint: str) -> int:
        return fnv64a(hint.encode()) % self.H

    @property
    def _delays(self):
        installed = self._installed
        return installed[0] if installed is not None else None

    @property
    def _faults(self):
        installed = self._installed
        return installed[1] if installed is not None else None

    def _decision_ctx(self):
        """ONE atomic read of the installed snapshot, shared by a whole
        decision (or decision batch): ``(snapshot_or_None, source tag,
        record extra)``. Deriving the delay AND the recorded
        ``table_version`` from the same snapshot means a concurrent
        install can never produce a record whose version belongs to a
        different table than its delay."""
        installed = self._installed
        if installed is None:
            return None, "hash", {}
        return installed, "table", {"table_version": installed[2]}

    def _delay_from(self, installed, hint: str) -> float:
        if installed is None:
            return hint_delay(str(self.seed), hint, self.max_interval)
        return float(installed[0][self._bucket(hint)])

    def _delay_for(self, hint: str) -> float:
        return self._delay_from(self._installed, hint)

    def _delays_from_many(self, installed, hints):
        """Vectorized :meth:`_delay_for` over a batch of hints: one
        fnv64a pass over the whole batch (numpy loop over byte
        positions, policy/replayable.py fnv64a_many) and one fancy-index
        gather from the installed table — value-identical to the scalar
        path, without its per-event Python hash loop. Returns a float
        ndarray of shape ``[len(hints)]``."""
        import numpy as _np

        if installed is None:
            return hint_delays(str(self.seed), hints, self.max_interval)
        buckets = fnv64a_many([h.encode() for h in hints]) \
            % _np.uint64(self.H)
        return _np.asarray(installed[0])[buckets.astype(_np.int64)]

    def _delays_for_many(self, hints):
        return self._delays_from_many(self._installed, hints)

    def _coin_table(self):
        """Per-bucket fault coin, computed once per (seed, H) — the SAME
        array the scorer's drop_mask uses (one source of truth in
        ops/trace_encoding.fault_coin), so the replayed drops are the
        drops the schedule was scored with, and the hot path pays one
        table lookup instead of a string hash per event."""
        cached = self._fault_coin
        if cached is None or cached.shape[0] != self.H:
            from namazu_tpu.ops.trace_encoding import fault_coin

            cached = self._fault_coin = fault_coin(self.seed, self.H)
        return cached

    def _fault_for(self, hint: str) -> bool:
        faults = self._faults
        if faults is None or self.max_fault <= 0:
            return False
        bucket = self._bucket(hint)
        p = float(faults[bucket])
        if p <= 0:
            return False
        return float(self._coin_table()[bucket]) < p

    def _table_source(self) -> str:
        """Where the current hot-path table values come from — the
        flight recorder's causal tag for each decision."""
        return "hash" if self._delays is None else "table"

    # -- table install + publication (zero-RTT dispatch) -----------------

    def _install_tables(self, delays, faults, source: str) -> None:
        """The ONE install seam: publish for the edge plane first (that
        mints the version), then swap the hot-path snapshot — table and
        its version rebound together, so decisions racing the install
        see either the old pair or the new pair, never a mix."""
        obs.schedule_install(source)
        obs.record_install(source)
        version = self._publish_table(delays, faults)
        self._installed = (delays, faults, version)

    def install_table(self, delays, faults=None,
                      source: str = "manual") -> None:
        """Public install (bench/tests/chaos harness): installs
        ``delays`` exactly like a search-plane install would, including
        the edge publication."""
        import numpy as _np

        delays = _np.asarray(delays, dtype=_np.float64)
        if delays.shape != (self.H,):
            raise ValueError(
                f"delays shape {delays.shape} != (H={self.H},)")
        self._install_tables(delays, faults, source)

    def _publish_table(self, delays, faults) -> int:
        """Publish ``delays`` when it is edge-eligible — the
        steady-state decision must be the pure hint->delay function the
        edge replicates. Fault-bearing or reorder-mode installs publish
        a *withdrawal* instead (version bump, no doc): edges fall back
        to the central wire, loss-free. Returns the minted version."""
        eligible = (delays is not None and self.release_mode == "delay"
                    and (faults is None or self.max_fault <= 0))
        if eligible:
            return self.table_publisher.publish(delays, self.H,
                                                self.max_interval)
        return self.table_publisher.publish_none()

    def queue_event(self, event: Event) -> None:
        self.start()
        if isinstance(event, ProcSetEvent):
            attrs = self._proc_policy.attrs_for(event.pids)
            obs.record_decision(event, self.name, kind="procset",
                                proc_policy=self.proc_policy_name)
            self._emit(ProcSetSchedAction.for_procset(event, attrs))
            return
        if self.release_mode == "reorder":
            # table value = priority (hash fallback until a search lands);
            # the window thread releases pending events in priority order
            if self._stop_reorder.is_set():
                # raced with shutdown's final flush: release immediately
                # so no transceiver hangs on a never-emitted action
                self._emit(self._action_for(event))
                return
            installed, source, extra = self._decision_ctx()
            prio = self._delay_from(installed, event.replay_hint())
            obs.record_decision(
                event, self.name, mode="reorder", priority=prio,
                source=source,
                generation=obs.current_generation_id(),
                **extra)
            now = self._now()
            with self._pending_lock:
                if self._anchor is None:
                    self._anchor = now
                    self._anchor_set.set()
                self._pending.append((prio, self._pending_seq, now, event))
                self._pending_seq += 1
            if self._stop_reorder.is_set():
                # shutdown flushed between our check and the append —
                # drain again (idempotent) so the event is not stranded
                self._drain_pending(gap=0.0)
            return
        installed, source, extra = self._decision_ctx()
        delay = self._delay_from(installed, event.replay_hint())
        obs.record_decision(event, self.name, mode="delay", delay=delay,
                            source=source,
                            generation=obs.current_generation_id(),
                            **extra)
        self._queue.put_at(event, delay)

    def _queue_events_batch(self, events) -> list:
        """Batch decision point (the orchestrator's event loop hands
        over its drained batch): the fnv64a-bucket -> delay-table lookup
        runs vectorized over the whole batch, then the result feeds the
        release machinery in ONE lock acquisition — ``put_at_many`` on
        the delay queue, or one ``_pending_lock`` append run for the
        reorder window. Decision VALUES are identical to the sequential
        path (same hash, same table, same record_decision detail); only
        the per-event Python overhead is gone. Returns the rejected
        events (poison procsets — the vectorized path itself is
        all-or-nothing)."""
        rejected = []
        plain = []
        for event in events:
            if isinstance(event, ProcSetEvent):
                # answered out-of-band via the proc subpolicy; rides the
                # scalar path (no table lookup to vectorize). Isolated:
                # a poison procset must not lose the rest of the batch
                try:
                    self.queue_event(event)
                except Exception:
                    log.exception("procset event %r rejected (batch "
                                  "continues)", event)
                    rejected.append(event)
            else:
                plain.append(event)
        if not plain:
            return rejected
        if self._stop_reorder.is_set() and self.release_mode == "reorder":
            # raced with shutdown's final flush: scalar path releases
            # each event immediately
            for event in plain:
                try:
                    self.queue_event(event)
                except Exception:
                    log.exception("event %r rejected during reorder "
                                  "shutdown flush", event)
                    rejected.append(event)
            return rejected
        installed, source, extra = self._decision_ctx()
        vals = self._delays_from_many(
            installed, [ev.replay_hint() for ev in plain])
        generation = obs.current_generation_id()
        if self.release_mode == "reorder":
            for event, prio in zip(plain, vals):
                obs.record_decision(
                    event, self.name, mode="reorder",
                    priority=float(prio), source=source,
                    generation=generation, **extra)
            now = self._now()
            with self._pending_lock:
                if self._anchor is None:
                    self._anchor = now
                    self._anchor_set.set()
                for event, prio in zip(plain, vals):
                    self._pending.append(
                        (float(prio), self._pending_seq, now, event))
                    self._pending_seq += 1
            if self._stop_reorder.is_set():
                self._drain_pending(gap=0.0)
            return rejected
        for event, delay in zip(plain, vals):
            obs.record_decision(event, self.name, mode="delay",
                                delay=float(delay), source=source,
                                generation=generation, **extra)
        self._queue.put_at_many(
            (event, float(delay)) for event, delay in zip(plain, vals))
        return rejected

    def _action_for(self, event: Event):
        if self._fault_for(event.replay_hint()):
            fault = event.default_fault_action()
            if fault is not None:
                return fault
        return event.default_action()

    # -- search plane -----------------------------------------------------

    def start(self) -> None:
        super().start()
        # _start_lock makes the spawns idempotent under concurrent
        # queue_event callers (the base class guards only its own thread)
        with self._start_lock:
            if (self.release_mode == "reorder"
                    and self._reorder_thread is None):
                self._reorder_thread = self._spawn(self._reorder_loop,
                                                   "reorder")
            if self.search_on_start and self._search_thread is None:
                self._search_thread = self._spawn(self._search_once,
                                                  "search")

    # -- reorder window ---------------------------------------------------

    def _drain_pending(self, gap: float,
                       boundary: Optional[float] = None) -> None:
        """Release pending events whose window has closed.

        ``boundary`` (monotonic time) limits the drain to events that
        arrived before it — i.e. to *closed* windows only; ``None`` takes
        everything (shutdown flush). The batch is released in
        (window, priority, arrival) order: exactly the permutation the
        scorer's ``order_release_times`` assigns to these arrivals, so the
        realized interleaving IS the scored one."""
        anchor, w = self._anchor, self.reorder_window
        with self._pending_lock:
            if boundary is None:
                batch, self._pending = self._pending, []
            else:
                batch = [p for p in self._pending if p[2] < boundary]
                self._pending = [p for p in self._pending
                                 if p[2] >= boundary]

        def win(t: float) -> int:
            if anchor is None or w <= 0:
                return 0
            return int((t - anchor) // w)

        batch.sort(key=lambda p: (win(p[2]), p[0], p[1]))
        for i, (_prio, _seq, _t, event) in enumerate(batch):
            # during shutdown, stop pacing so a large in-flight batch
            # cannot outlive the join window and lose its tail
            if i and gap > 0 and not self._stop_reorder.is_set():
                time.sleep(gap)
            obs.record_released(event, self.name)
            obs.queue_dwell(self.name, event.entity_id,
                            obs.latency(event, "enqueued"))
            self._emit(self._action_for(event))

    def _reorder_loop(self) -> None:
        """Tick at absolute window boundaries ``anchor + k*window`` and
        release only the windows that closed — not whatever happens to be
        pending at wake-up, which would batch events across the scorer's
        window boundaries."""
        w = self.reorder_window
        # phase 1: wait for the first event to anchor the window clock
        while not self._stop_reorder.is_set():
            if self._anchor_set.wait(timeout=0.05):
                break
        # phase 2: aligned ticks
        while not self._stop_reorder.is_set():
            anchor = self._anchor
            now = self._now()
            k = int((now - anchor) // w) + 1
            if self._stop_reorder.wait(max(0.0, anchor + k * w - now)):
                break
            self._drain_pending(self.reorder_gap,
                                boundary=anchor + k * w)

    def _build_search(self):
        if self.platform:
            # env alone is NOT enough: this image's sitecustomize imports
            # jax at interpreter start, and jax snapshots JAX_PLATFORMS
            # into its config defaults at import time. config.update is
            # the post-import lever; it must run before the first backend
            # initialization (which is exactly why this sits at the top
            # of _build_search — nothing in the control plane touches a
            # backend). Probing the current backend here would itself
            # trigger initialization, i.e. the wedge we are avoiding.
            os.environ["JAX_PLATFORMS"] = self.platform  # child processes
            import jax

            try:
                jax.config.update("jax_platforms", self.platform)
            except Exception as e:  # backend already up: keep it
                log.warning("could not switch jax platform to %r: %s",
                            self.platform, e)
        from namazu_tpu.models.ga import GAConfig
        from namazu_tpu.models.search import (
            MCTSSearch,
            ScheduleSearch,
            SearchConfig,
            make_score_weights,
        )

        # one home for the subtle mode-dependent weight construction,
        # shared with the sidecar (models/search.py make_score_weights)
        weights = make_score_weights(
            release_mode=self.release_mode,
            w_novelty=self.w_novelty, w_bug=self.w_bug,
            w_delay_cost=self.w_delay_cost,
            w_fault_cost=self.w_fault_cost, tau=self.tau,
            reorder_gap=self.reorder_gap,
            reorder_window=self.reorder_window,
        )
        cfg = SearchConfig(
            H=self.H, L=self.L, K=self.K,
            population=self.population,
            migrate_k=self.migrate_k,
            seed=self.seed,
            ga=GAConfig(max_delay=self.max_interval,
                        max_fault=self.max_fault),
            weights=weights,
            surrogate_topk=self.surrogate_topk,
            min_failure_signatures=self.min_failure_signatures,
            novelty_floor=self.novelty_floor,
            guidance_bonus=self.guidance_bonus,
            fused=self.fused,
            fused_chunk=self.fused_chunk,
            migrate_every=self.migrate_every,
            dcn_migrate_every=self.dcn_migrate_every,
            device_trace_dir=self.device_trace_dir,
        )
        mesh = None
        if self.dcn_hosts > 1:
            # multi-host: join the jax.distributed ring (no-op when the
            # NMZ_TPU_COORDINATOR env triple is absent, e.g. virtual-host
            # dry runs) and shard over a hybrid host x chip mesh
            from namazu_tpu.parallel.distributed import (
                initialize_from_env,
                make_hybrid_mesh,
            )

            import jax

            initialize_from_env()
            # honor the `devices` knob (same subset the flat path uses);
            # in a multi-process run slice per process — a flat
            # jax.devices()[:n] can take 4 chips from host 0 and 2 from
            # host 1, which make_hybrid_mesh would (rightly) reject
            devs = None
            if self.n_devices is not None:
                pc = jax.process_count()
                if pc > 1:
                    if self.n_devices % pc != 0:
                        raise ValueError(
                            f"devices={self.n_devices} must divide evenly "
                            f"across {pc} processes"
                        )
                    per = self.n_devices // pc
                    by_proc: dict = {}
                    for d in sorted(jax.devices(),
                                    key=lambda d: (d.process_index, d.id)):
                        by_proc.setdefault(d.process_index, []).append(d)
                    short = {p: len(ds) for p, ds in by_proc.items()
                             if len(ds) < per}
                    if short:
                        raise ValueError(
                            f"devices={self.n_devices} needs {per} chips "
                            f"per process but some have fewer: {short}"
                        )
                    devs = [d for p in sorted(by_proc)
                            for d in by_proc[p][:per]]
                else:
                    devs = jax.devices()[: self.n_devices]
            mesh = make_hybrid_mesh(n_hosts=self.dcn_hosts, devices=devs)
        if self.search_backend == "mcts":
            if self.surrogate_topk > 0:
                log.warning(
                    "surrogate re-ranking (surrogate_topk=%d) applies to "
                    "the GA backend only; the mcts backend returns its "
                    "fitness argmax", self.surrogate_topk)
            if self._guidance_active():
                log.warning(
                    "causality guidance (guidance=true) biases the GA "
                    "backend's pick/mutation only; the mcts backend "
                    "still feeds the coverage map and metrics")
            from namazu_tpu.models.mcts import MCTSConfig

            mcts_cfg = MCTSConfig(
                tree_depth=self.mcts_tree_depth,
                n_levels=self.mcts_levels,
                simulations=self.mcts_simulations,
                rollouts=self.mcts_rollouts,
                max_delay=self.max_interval,
                max_fault=self.max_fault,
            )
            search = MCTSSearch(cfg, mcts_cfg=mcts_cfg, mesh=mesh,
                                n_devices=self.n_devices)
        else:
            search = ScheduleSearch(cfg, mesh=mesh,
                                    n_devices=self.n_devices)
        if self._guidance_active():
            # wired BEFORE any checkpoint load/ingest so the archive's
            # DAG-shape feature fragments stay slot-aligned
            search.enable_guidance(self.guidance_width or None,
                                   self.guidance_window or None)
        return search

    def _guidance_active(self) -> bool:
        """Guidance runs only when asked for AND the obs plane is on:
        the coverage signature is derived from recorded structure, so
        ``obs_enabled = false`` degrades to the exact pre-guidance
        blind search instead of guiding on phantom data."""
        return self.guidance_enabled and obs.metrics.enabled()

    def _checkpoint(self) -> str:
        """Checkpoint path; a relative path anchors to the experiment's
        storage dir (stable across `run` invocations from any cwd)."""
        p = self.checkpoint_path
        if (p and not os.path.isabs(p)
                and getattr(self._storage, "dir", None)):
            return os.path.join(self._storage.dir, p)
        return p

    def _install_from_checkpoint(self, ckpt: str) -> bool:
        """Install the checkpointed best tables from the raw npz, without
        touching any jax machinery. The testee's decisive window (a
        leader election, a reader's grace period) is typically over
        within the first few hundred ms of the run; building the search
        object first (imports, mesh, jit setup) loses that race and the
        whole run silently executes hash-fallback delays."""
        import numpy as _np

        from namazu_tpu.ops.trace_encoding import (
            HINT_SPACE,
            checkpoint_hint_space,
        )

        try:
            with _np.load(ckpt) as z:
                if "best_delays" not in z or "generations_run" not in z:
                    return False
                if int(z["generations_run"]) <= 0:
                    return False
                space = checkpoint_hint_space(z)
                if space != HINT_SPACE:
                    log.warning(
                        "checkpoint %s is from hint space %r (this build: "
                        "%r); not installing its schedule", ckpt, space,
                        HINT_SPACE)
                    return False
                fit = (float(z["best_fitness"])
                       if "best_fitness" in z else float("nan"))
                if not _np.isfinite(fit):
                    return False
                delays = _np.array(z["best_delays"])
                if delays.shape != (self.H,):
                    log.warning(
                        "checkpoint %s has best_delays of shape %s but "
                        "hint_buckets=%d; not installing", ckpt,
                        delays.shape, self.H)
                    return False
                faults = (_np.array(z["best_faults"])
                          if "best_faults" in z else None)
        except Exception:
            log.exception("unreadable checkpoint %s", ckpt)
            return False
        self._install_tables(delays, faults, "checkpoint")
        log.info("installed checkpointed schedule (fitness %.4f) from %s",
                 fit, ckpt)
        return True

    def _search_once(self) -> None:
        """Background: ingest history, evolve, install the best tables."""
        try:
            ckpt = self._checkpoint()
            installed = False
            if ckpt and os.path.exists(ckpt) and self._delays is None:
                # cheap install FIRST (np.load only), then the heavy build
                installed = self._install_from_checkpoint(ckpt)
            if not installed and self._delays is None and self.knowledge:
                # truly cold run (no checkpoint product): the fleet's
                # best table for this scenario beats the hash fallback —
                # the whole point of the knowledge plane (doc/knowledge.md)
                self._knowledge_warmstart_table()
            if installed and self.search_every > 1:
                storage = self._storage
                try:
                    n = storage.nr_stored_histories() if storage else 0
                except Exception:
                    n = 0
                if n % self.search_every != 0:
                    log.info(
                        "install-only run (search_every=%d, %d stored "
                        "runs); next evolution at %d",
                        self.search_every, n,
                        -(-n // self.search_every) * self.search_every)
                    return
            if self.sidecar:
                try:
                    # park until the run ends: a warm sidecar evolve is
                    # fast enough to land INSIDE the testee's decisive
                    # window, and on small hosts the CPU it burns there
                    # skews the very timing being fuzzed. The evolve's
                    # product ships via the checkpoint to the next run,
                    # so end-of-run is the right moment (and the
                    # reference's division of labor: exploration work
                    # happens between experiments, SURVEY.md 3.1).
                    self._run_ending.wait()
                    self._sidecar_search(ckpt)
                    return
                except Exception:
                    log.exception(
                        "sidecar %s unreachable/failed; falling back to "
                        "the in-process search", self.sidecar)
            with self._search_lock:
                if self._search is None:
                    self._search = self._build_search()
                    if ckpt and os.path.exists(ckpt):
                        try:
                            self._search.load(ckpt)
                            log.info("loaded search checkpoint %s (gen %d)",
                                     ckpt, self._search.generations_run)
                        except Exception:
                            # incompatible (hint space, backend, shape) or
                            # corrupt: evolve fresh rather than abort the
                            # whole search; the save below replaces it
                            log.exception(
                                "checkpoint %s not loadable; starting a "
                                "fresh search", ckpt)
                    self._wire_remote_surrogate(self._search)
                search = self._search
            if search.generations_run > 0 and self._delays is None:
                # install the checkpointed best NOW: the testee's decisive
                # window (e.g. a leader election) is typically over within
                # the first second of the run, long before this thread's
                # own evolution finishes — so each run replays the
                # schedule found by the end of the *previous* run, and
                # this run's evolution product ships in the checkpoint
                import numpy as _np

                b = search.best()
                if _np.isfinite(b.fitness):
                    self._install_tables(b.delays, b.faults, "checkpoint")
                    log.info(
                        "installed checkpointed schedule (fitness %.4f) "
                        "before this run's search", b.fitness)
            with obs.search_phase("ingest"):
                references = self._ingest_history(search)
            if not references:
                log.info("no stored history yet; keeping hash-based delays")
                return
            best = search.run(references, generations=self.generations)
            with obs.search_phase("install"):
                self._install_tables(best.delays, best.faults, "search")
            log.info("installed searched schedule (fitness %.4f, gen %d)",
                     best.fitness, search.generations_run)
            if ckpt:
                search.save(ckpt)
            self._knowledge_push_best(best.delays, best.fitness)
        except Exception:
            log.exception("schedule search failed; hash-based delays remain")

    MAX_REFERENCE_TRACES = 4
    MAX_SEED_GENOMES = 16

    def _failure_seed(self, trace):
        """See models/ingest.py failure_seed (shared with the sidecar)."""
        from namazu_tpu.models.ingest import failure_seed

        return failure_seed(trace, self.H, self.max_interval)

    def _search_params(self) -> dict:
        """Flat JSON-able search knobs — what the sidecar needs to build
        an equivalent backend (sidecar.build_search_from_params)."""
        return {
            "H": self.H, "L": self.L, "K": self.K,
            "population": self.population,
            "migrate_k": self.migrate_k,
            "fused": self.fused,
            "fused_chunk": self.fused_chunk,
            "device_trace_dir": self.device_trace_dir,
            "migrate_every": self.migrate_every,
            "dcn_migrate_every": self.dcn_migrate_every,
            "seed": self.seed,
            "max_interval": self.max_interval,
            "max_fault": self.max_fault,
            "surrogate_topk": self.surrogate_topk,
            "min_failure_signatures": self.min_failure_signatures,
            "novelty_floor": self.novelty_floor,
            "search_backend": self.search_backend,
            "guidance": self._guidance_active(),
            "guidance_bonus": self.guidance_bonus,
            "guidance_width": self.guidance_width,
            "guidance_window": self.guidance_window,
            "mcts_tree_depth": self.mcts_tree_depth,
            "mcts_levels": self.mcts_levels,
            "mcts_simulations": self.mcts_simulations,
            "mcts_rollouts": self.mcts_rollouts,
            "release_mode": self.release_mode,
            "w_novelty": self.w_novelty, "w_bug": self.w_bug,
            "w_delay_cost": self.w_delay_cost,
            "w_fault_cost": self.w_fault_cost,
            "tau": self.tau,
            "reorder_gap": self.reorder_gap,
            "reorder_window": self.reorder_window,
            "devices": self.n_devices,
        }

    def _sidecar_search(self, ckpt: str) -> None:
        """Delegate the evolve cycle to the persistent sidecar and
        install what it returns. Raises on any failure — the caller
        falls back to the in-process search."""
        import numpy as _np

        from namazu_tpu.sidecar import request

        storage_dir = getattr(self._storage, "dir", None)
        if not storage_dir:
            raise RuntimeError(
                "sidecar search needs a directory-backed storage")
        resp = request(self.sidecar, {
            "op": "search",
            "key": os.path.abspath(storage_dir),
            "storage": os.path.abspath(storage_dir),
            "search_params": self._search_params(),
            "ingest_params": self._ingest_params()._asdict(),
            "generations": self.generations,
            "checkpoint": os.path.abspath(ckpt) if ckpt else "",
        }, timeout=max(self.search_join_timeout, 30.0))
        if not resp.get("ok"):
            raise RuntimeError(f"sidecar: {resp.get('error', 'failed')}")
        if resp.get("no_history"):
            log.info("sidecar: no stored history yet; keeping current "
                     "delays")
            return
        self._install_tables(_np.asarray(resp["delays"], _np.float32),
                             _np.asarray(resp["faults"], _np.float32),
                             "sidecar")
        log.info("installed sidecar schedule (fitness %.4f, gen %d)",
                 resp["fitness"], resp["generations_run"])
        self._knowledge_push_best(self._delays, float(resp["fitness"]))

    # -- global failure-knowledge plane (doc/knowledge.md) ---------------

    def _scenario_fingerprint(self, config) -> str:
        """Warm-start key: campaigns of one experiment — same run/
        validate scripts, hint space, bucket count, release mode — must
        land on one knowledge-service scenario without coordination,
        and experiments with different oracles must never share a delay
        table (their fitness scales aren't comparable)."""
        import hashlib
        import json as _json

        from namazu_tpu.signal.base import HINT_SPACE

        basis = [str(config.get("run", "")),
                 str(config.get("validate", "")),
                 HINT_SPACE, int(self.H), self.release_mode]
        return hashlib.sha256(
            _json.dumps(basis).encode()).hexdigest()[:16]

    def _knowledge_tenant(self) -> str:
        d = getattr(self._storage, "dir", None)
        return os.path.basename(os.path.abspath(d)) if d else "anon"

    def _knowledge_client(self):
        """The process-shared client for this policy's service/tenant/
        scenario triple, or None when the knowledge plane is off."""
        if not self.knowledge:
            return None
        from namazu_tpu.knowledge import shared_client

        return shared_client(self.knowledge,
                             tenant=self._knowledge_tenant(),
                             scenario=self.scenario)

    def _knowledge_warmstart_table(self) -> bool:
        """Cold-run hot-path warm-start: install the scenario's best
        fleet delay table when nothing better exists yet (no checkpoint,
        no own search product). Returns whether a table was installed;
        outages/empty services return False and hash fallback remains —
        a knowledge outage must never fail (or even delay) a run."""
        client = self._knowledge_client()
        if client is None:
            return False
        try:
            table = client.scenario_table(self.H)
        except Exception:
            log.exception("knowledge warm-start failed; keeping "
                          "hash-based delays")
            return False
        if table is None:
            return False
        self._install_tables(table["delays"], self._faults, "knowledge")
        obs.knowledge_warmstart("table")
        log.info("installed knowledge warm-start schedule (fitness "
                 "%.4f, scenario %s)", table["fitness"], self.scenario)
        return True

    def _knowledge_push_best(self, delays, fitness: float) -> None:
        """Publish this run's evolved best so the NEXT cold campaign of
        this scenario warm-starts from it (service keeps the highest
        fitness per scenario). Best-effort."""
        client = self._knowledge_client()
        if client is None:
            return
        import numpy as _np

        if delays is None or not _np.isfinite(fitness):
            return
        try:
            client.push(best={
                "delays": [float(x) for x in _np.asarray(delays)],
                "fitness": float(fitness), "H": self.H,
            })
        except Exception:
            log.exception("could not push best schedule to the "
                          "knowledge service")

    def _wire_remote_surrogate(self, search) -> None:
        """Give the search the shared-surrogate hook: candidate features
        go to the knowledge service scoped by this search's own pair
        fingerprint (features never cross feature spaces). Consulted
        only while the local surrogate is too thin (models/search.py
        _surrogate_pick)."""
        client = self._knowledge_client()
        if client is None:
            return

        from namazu_tpu.knowledge.client import pairs_fingerprint

        def hook(feats, _client=client, _search=search):
            return _client.predict(
                feats, pairs_fp=pairs_fingerprint(_search.pairs))

        search.remote_surrogate = hook

    def _failure_pool_path(self) -> str:
        """Pool dir; a relative path anchors to the storage dir's PARENT
        so sibling experiment storages (e.g. A/B batches under one root)
        share one pool."""
        p = self.failure_pool
        if (p and not os.path.isabs(p)
                and getattr(self._storage, "dir", None)):
            parent = os.path.dirname(
                os.path.abspath(self._storage.dir))
            return os.path.join(parent, p)
        return p

    def _ingest_params(self):
        from namazu_tpu.models.ingest import IngestParams

        return IngestParams(
            H=self.H, L=self.L,
            release_mode=self.release_mode,
            reference_mode=self.reference_mode,
            max_interval=self.max_interval,
            max_reference_traces=self.MAX_REFERENCE_TRACES,
            max_seed_genomes=self.MAX_SEED_GENOMES,
            order_mode_max_l=self.ORDER_MODE_MAX_L,
            failure_pool=self._failure_pool_path(),
            knowledge=self.knowledge,
            knowledge_tenant=self._knowledge_tenant(),
            knowledge_scenario=self.scenario,
            guidance=self._guidance_active(),
            guidance_width=self.guidance_width,
            guidance_window=self.guidance_window,
        )
    # order mode scores dense (a windowed permutation needs the whole
    # trace in one lexsort — ops/schedule.py), so uncapped encoding would
    # materialize [population, L] intermediates per generation; cap the
    # encoded length in reorder mode unless the user set one explicitly
    ORDER_MODE_MAX_L = 4096

    def _ingest_history(self, search):
        """Feed stored traces into the archives; return the reference
        traces to evolve against — shared implementation with the
        persistent search sidecar (models/ingest.py, which carries the
        full design rationale)."""
        from namazu_tpu.models.ingest import ingest_history

        return ingest_history(search, self._storage, self._ingest_params())

    def shutdown(self) -> None:
        """With a checkpoint configured, let an in-flight search finish
        (bounded) before the run ends — the searched schedule + checkpoint
        are the run's product for the next `run` invocation's policy to
        pick up. Without one the result could not outlive the process, so
        don't hold the shutdown."""
        if self._reorder_thread is not None:
            self._stop_reorder.set()
            self._reorder_thread.join(timeout=10)
            self._drain_pending(gap=0.0)  # flush, loss-free shutdown
        self._run_ending.set()  # release a parked sidecar evolve
        t = self._search_thread
        if t is not None and self.checkpoint_path:
            t.join(timeout=self.search_join_timeout)
        super().shutdown()

    def wait_for_search(self, timeout: float = 120.0) -> bool:
        """Block until the background search installed a schedule (tests)."""
        self._run_ending.set()
        t = self._search_thread
        if t is None:
            return self._delays is not None
        t.join(timeout=timeout)
        return self._delays is not None


register_policy(TPUSearchPolicy.NAME, TPUSearchPolicy)
