"""Replayable policy: semi-deterministic delays hashed from replay hints.

Parity: /root/reference/nmz/explorepolicy/replayable/replayablepolicy.go:
100-126 — delay = fnv64a(seed || event.replay_hint()) % max_interval, so a
run can be replayed without recording anything: same seed + same semantic
event stream => same relative delays => (approximately) the same
interleaving. The seed is overridable via the NMZ_TPU_REPLAY_SEED
environment variable (reference: NMZ_REPLAY_SEED).

This hint->delay table is exactly the representation the TPU search plane
optimizes: the tpu_search policy generalizes this policy by *learning* the
per-hint delays instead of hashing them.
"""

from __future__ import annotations

import os

from namazu_tpu.policy.base import QueueBackedPolicy, register_policy
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.config import parse_duration

FNV64_OFFSET = 0xCBF29CE484222325
FNV64_PRIME = 0x100000001B3


def fnv64a(data: bytes) -> int:
    h = FNV64_OFFSET
    for b in data:
        h ^= b
        h = (h * FNV64_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


def hint_delay(seed: str, hint: str, max_interval: float) -> float:
    """Deterministic delay in [0, max_interval) for a replay hint."""
    if max_interval <= 0:
        return 0.0
    h = fnv64a((seed + "\x00" + hint).encode())
    # quantize to ms like the reference (delays are ms-granular)
    max_ms = max(1, int(max_interval * 1000))
    return (h % max_ms) / 1000.0


class ReplayablePolicy(QueueBackedPolicy):
    NAME = "replayable"

    def __init__(self) -> None:
        super().__init__()
        self.seed = os.environ.get("NMZ_TPU_REPLAY_SEED", "0")
        self.max_interval = 0.1

    def load_config(self, config) -> None:
        p = config.policy_param
        self.max_interval = parse_duration(p("max_interval", 100))
        seed = p("seed", None)
        env_seed = os.environ.get("NMZ_TPU_REPLAY_SEED")
        if env_seed is not None:
            self.seed = env_seed
        elif seed is not None:
            self.seed = str(seed)

    def queue_event(self, event: Event) -> None:
        self.start()
        delay = hint_delay(self.seed, event.replay_hint(), self.max_interval)
        self._queue.put_at(event, delay)


register_policy(ReplayablePolicy.NAME, ReplayablePolicy)
