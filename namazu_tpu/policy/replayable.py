"""Replayable policy: semi-deterministic delays hashed from replay hints.

Parity: /root/reference/nmz/explorepolicy/replayable/replayablepolicy.go:
100-126 — delay = fnv64a(seed || event.replay_hint()) % max_interval, so a
run can be replayed without recording anything: same seed + same semantic
event stream => same relative delays => (approximately) the same
interleaving. The seed is overridable via the NMZ_TPU_REPLAY_SEED
environment variable (reference: NMZ_REPLAY_SEED).

This hint->delay table is exactly the representation the TPU search plane
optimizes: the tpu_search policy generalizes this policy by *learning* the
per-hint delays instead of hashing them.
"""

from __future__ import annotations

import os

from namazu_tpu.policy.base import QueueBackedPolicy, register_policy
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.config import parse_duration

FNV64_OFFSET = 0xCBF29CE484222325
FNV64_PRIME = 0x100000001B3


def fnv64a(data: bytes) -> int:
    h = FNV64_OFFSET
    for b in data:
        h ^= b
        h = (h * FNV64_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


def hint_delay(seed: str, hint: str, max_interval: float) -> float:
    """Deterministic delay in [0, max_interval) for a replay hint."""
    if max_interval <= 0:
        return 0.0
    h = fnv64a((seed + "\x00" + hint).encode())
    # quantize to ms like the reference (delays are ms-granular)
    max_ms = max(1, int(max_interval * 1000))
    return (h % max_ms) / 1000.0


def fnv64a_many(datas):
    """Vectorized :func:`fnv64a` over a list of byte strings.

    The hash is sequential per string, so the numpy loop runs over BYTE
    POSITIONS (max string length, tens of iterations for replay hints)
    instead of per event — the event-plane batch path hashes a whole
    batch of hints without a per-event Python loop. Bit-exact with the
    scalar fnv64a (uint64 arithmetic wraps mod 2**64 on both sides).
    Returns a uint64 ndarray of shape ``[len(datas)]``.
    """
    import numpy as np

    n = len(datas)
    if n == 0:
        return np.zeros(0, dtype=np.uint64)
    lens = np.fromiter((len(d) for d in datas), dtype=np.int64, count=n)
    maxlen = int(lens.max()) if n else 0
    h = np.full(n, FNV64_OFFSET, dtype=np.uint64)
    if maxlen == 0:
        return h
    joined = np.frombuffer(b"".join(datas), dtype=np.uint8)
    offsets = np.zeros(n, dtype=np.int64)
    np.cumsum(lens[:-1], out=offsets[1:])
    pos = np.arange(maxlen, dtype=np.int64)
    mask = pos[None, :] < lens[:, None]               # [n, maxlen]
    idx = np.where(mask, offsets[:, None] + pos[None, :], 0)
    padded = joined[idx].astype(np.uint64)            # [n, maxlen]
    prime = np.uint64(FNV64_PRIME)
    with np.errstate(over="ignore"):
        for j in range(maxlen):
            mixed = (h ^ padded[:, j]) * prime
            h = np.where(mask[:, j], mixed, h)
    return h


def hint_delays(seed: str, hints, max_interval: float):
    """Vectorized :func:`hint_delay` over a list of hint strings —
    identical values, one hash pass for the whole batch. Returns a
    float64 ndarray of shape ``[len(hints)]``."""
    import numpy as np

    if max_interval <= 0:
        return np.zeros(len(hints), dtype=np.float64)
    prefix = (seed + "\x00").encode()
    h = fnv64a_many([prefix + hint.encode() for hint in hints])
    max_ms = np.uint64(max(1, int(max_interval * 1000)))
    return (h % max_ms).astype(np.float64) / 1000.0


class ReplayablePolicy(QueueBackedPolicy):
    NAME = "replayable"

    def __init__(self) -> None:
        super().__init__()
        self.seed = os.environ.get("NMZ_TPU_REPLAY_SEED", "0")
        self.max_interval = 0.1

    def load_config(self, config) -> None:
        p = config.policy_param
        self.max_interval = parse_duration(p("max_interval", 100))
        seed = p("seed", None)
        env_seed = os.environ.get("NMZ_TPU_REPLAY_SEED")
        if env_seed is not None:
            self.seed = env_seed
        elif seed is not None:
            self.seed = str(seed)

    def queue_event(self, event: Event) -> None:
        self.start()
        delay = hint_delay(self.seed, event.replay_hint(), self.max_interval)
        self._queue.put_at(event, delay)


register_policy(ReplayablePolicy.NAME, ReplayablePolicy)
