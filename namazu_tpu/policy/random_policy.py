"""Random policy: the reference's default fuzzer.

Parity: /root/reference/nmz/explorepolicy/random/randompolicy.go:93-346.

* every event is delayed uniformly in ``[min_interval, max_interval]``
  (entities listed in ``prioritized_entities`` get 0.8x the delay);
* on release, with probability ``fault_action_probability`` the event's
  fault action (drop packet / EIO) is chosen instead of its default;
* ``ProcSetEvent``s bypass the delay queue and are answered immediately by
  a proc sub-policy (mild / extreme / dirichlet);
* optionally a shell command is injected every ``shell_action_interval``
  (crash injection, parity randompolicy.go:281-298).

Unlike the reference, a ``seed`` parameter makes the policy's random
choices reproducible: delay sampling, fault coin-flips and proc attrs are
all derived from it (the delay queue's RNG is reseeded with seed+1 by
``load_config``).
"""

from __future__ import annotations

import random
import threading
from typing import Optional

from namazu_tpu.policy.base import QueueBackedPolicy, register_policy
from namazu_tpu.policy.proc_subpolicies import create_proc_subpolicy
from namazu_tpu.signal.action import ProcSetSchedAction, ShellAction
from namazu_tpu.signal.event import Event, ProcSetEvent
from namazu_tpu.utils.config import parse_duration
from namazu_tpu.utils.log import get_logger

log = get_logger("policy.random")


class RandomPolicy(QueueBackedPolicy):
    NAME = "random"

    PRIORITIZED_SPEEDUP = 0.8  # parity: randompolicy.go:332-346

    def __init__(self, seed: Optional[int] = None) -> None:
        super().__init__(seed=None if seed is None else seed + 1)
        self.rng = random.Random(seed)
        self.min_interval = 0.0
        self.max_interval = 0.0
        self.prioritized_entities: set[str] = set()
        self.fault_action_probability = 0.0
        self.shell_action_interval = 0.0
        self.shell_action_command = ""
        self.proc_policy_name = "mild"
        self._proc_policy = create_proc_subpolicy("mild", self.rng)
        self._stop = threading.Event()
        self._shell_thread: Optional[threading.Thread] = None

    def load_config(self, config) -> None:
        p = config.policy_param
        seed = p("seed", None)
        if seed is not None:
            self.rng.seed(int(seed))
            self._queue.reseed(int(seed) + 1)
        self.min_interval = parse_duration(p("min_interval", 0))
        self.max_interval = parse_duration(p("max_interval", 0))
        if self.max_interval < self.min_interval:
            self.max_interval = self.min_interval
        self.prioritized_entities = set(p("prioritized_entities", []) or [])
        self.fault_action_probability = float(p("fault_action_probability", 0.0))
        self.shell_action_interval = parse_duration(p("shell_action_interval", 0))
        self.shell_action_command = str(p("shell_action_command", "") or "")
        name = str(p("proc_policy", self.proc_policy_name))
        self.proc_policy_name = name
        self._proc_policy = create_proc_subpolicy(name, self.rng)
        self._proc_policy.load_params(p("proc_policy_param", {}) or {})

    # -- event intake ----------------------------------------------------

    def queue_event(self, event: Event) -> None:
        self.start()
        if isinstance(event, ProcSetEvent):
            # answered immediately; the *content* is the fuzz, not the delay
            attrs = self._proc_policy.attrs_for(event.pids)
            self._emit(ProcSetSchedAction.for_procset(event, attrs))
            return
        lo, hi = self.min_interval, self.max_interval
        if event.entity_id in self.prioritized_entities:
            lo *= self.PRIORITIZED_SPEEDUP
            hi *= self.PRIORITIZED_SPEEDUP
        self._queue.put(event, lo, hi)

    def _queue_events_batch(self, events):
        """Batch intake: ProcSet events keep the immediate-answer path
        (isolated per event); the rest enter the delay queue under ONE
        lock via put_many, whose delay sampling draws from the same RNG
        in the same order as sequential puts — a seeded run stays
        reproducible whether the orchestrator handed events over singly
        or in batches."""
        rejected = []
        delayed = []
        for event in events:
            if isinstance(event, ProcSetEvent):
                try:
                    attrs = self._proc_policy.attrs_for(event.pids)
                    self._emit(
                        ProcSetSchedAction.for_procset(event, attrs))
                except Exception:
                    log.exception("procset event %r rejected (batch "
                                  "continues)", event)
                    rejected.append(event)
                continue
            lo, hi = self.min_interval, self.max_interval
            if event.entity_id in self.prioritized_entities:
                lo *= self.PRIORITIZED_SPEEDUP
                hi *= self.PRIORITIZED_SPEEDUP
            delayed.append((event, lo, hi))
        if delayed:
            self._queue.put_many(delayed)
        return rejected

    # -- workers ---------------------------------------------------------

    def start(self) -> None:
        super().start()
        if (
            self._shell_thread is None
            and self.shell_action_interval > 0
            and self.shell_action_command
        ):
            self._shell_thread = self._spawn(self._shell_loop, "shell")

    def _action_for(self, event: Event):
        # parity: makeActionForEvent, randompolicy.go:300-317
        if self.fault_action_probability > 0 and (
            self.rng.random() < self.fault_action_probability
        ):
            fault = event.default_fault_action()
            if fault is not None:
                return fault
        return event.default_action()

    def _shell_loop(self) -> None:
        while not self._stop.wait(self.shell_action_interval):
            self._emit(ShellAction.create(self.shell_action_command))

    def shutdown(self) -> None:
        self._stop.set()
        super().shutdown()


register_policy(RandomPolicy.NAME, RandomPolicy)
