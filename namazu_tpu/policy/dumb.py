"""Dumb policy: passthrough with a fixed interval.

Parity: /root/reference/nmz/explorepolicy/dumb/dumbpolicy.go:41-103. Every
event's default action is emitted after a fixed ``interval`` (default 0).
With interval 0 this is a pure passthrough that still serializes events
through one queue — exactly what the orchestrator uses when orchestration
is disabled.
"""

from __future__ import annotations

from namazu_tpu.policy.base import QueueBackedPolicy, register_policy
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.config import parse_duration


class DumbPolicy(QueueBackedPolicy):
    NAME = "dumb"

    def __init__(self) -> None:
        super().__init__()
        self.interval = 0.0

    def load_config(self, config) -> None:
        iv = config.policy_param("interval", None)
        if iv is not None:
            self.interval = parse_duration(iv)

    def queue_event(self, event: Event) -> None:
        self.start()
        self._queue.put(event, self.interval, self.interval)

    def _queue_events_batch(self, events):
        """Batch intake: one queue-lock acquisition for a drained batch
        (this policy serves the orchestration-disabled hot path, so the
        batch fan-through matters here as much as in the real fuzzers)."""
        self._queue.put_many(
            (event, self.interval, self.interval) for event in events)
        return []


register_policy(DumbPolicy.NAME, DumbPolicy)
