"""Policy interface and registry."""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, Iterable, Optional, TYPE_CHECKING

from namazu_tpu import obs
from namazu_tpu.signal.action import Action
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.log import get_logger
from namazu_tpu.utils.sched_queue import QueueClosed, ScheduledQueue

log = get_logger("policy")

if TYPE_CHECKING:  # pragma: no cover
    from namazu_tpu.storage.base import HistoryStorage
    from namazu_tpu.utils.config import Config


class PolicyError(Exception):
    pass


class _PolicyDone:
    """Sentinel a policy emits on ``action_out`` after shutdown has flushed
    every remaining action — lets the orchestrator drain without racing."""

    def __repr__(self) -> str:  # pragma: no cover
        return "<POLICY_DONE>"


POLICY_DONE = _PolicyDone()


class ExplorePolicy:
    """Base class for exploration policies.

    Contract (parity with the reference's ExplorePolicy interface,
    /root/reference/nmz/explorepolicy/interface.go:24-40, and the
    non-blocking warning in its README.md:304):

    * ``queue_event`` MUST return quickly — never block on I/O or sleep.
    * actions appear on ``action_out`` (a thread-safe queue) in the order
      the policy decides to release them; that order IS the fuzz. An
      ``action_out`` item is one :class:`Action` OR a list of them (a
      burst released together — the consumer flattens in order); the
      batch form exists so a burst costs one queue hand-off, not one
      thread wakeup per action.
    * ``load_config`` may be called again at runtime for dynamic reload.
    """

    NAME = "abstract"

    def __init__(self) -> None:
        self.action_out: "queue.Queue[Action]" = queue.Queue()
        self._storage: Optional["HistoryStorage"] = None

    @property
    def name(self) -> str:
        return self.NAME

    def load_config(self, config: "Config") -> None:
        """Read ``explore_policy_param.*`` keys. Unknown keys are ignored
        (parity: the reference tolerates unknown params,
        randompolicy_test.go:49-91)."""

    def set_history_storage(self, storage: "HistoryStorage") -> None:
        self._storage = storage

    def queue_event(self, event: Event) -> None:
        raise NotImplementedError

    def queue_events(self, events: Iterable[Event]) -> "list[Event]":
        """Batch entry point: decide a whole batch in one call; returns
        the events the policy REJECTED (empty when all queued — the
        orchestrator skips lifecycle marks for rejected events, keeping
        batched and per-event telemetry identical). The default just
        loops; policies with a vectorizable decision (the TPU policy's
        bucket -> table lookup) override the batch hook so the
        orchestrator's event loop can hand them a drained batch without
        a per-event Python round trip.

        Failures are isolated per event, matching the per-event path's
        semantics: one poison event must not take down the rest of the
        drained batch."""
        rejected = []
        for event in events:
            try:
                self.queue_event(event)
            except Exception:
                log.exception(
                    "policy %s rejected event %r (rest of the batch "
                    "continues)", self.name, event)
                rejected.append(event)
        return rejected

    def force_release_entity(self, entity_id: str) -> int:
        """Release any events parked for ``entity_id`` immediately;
        returns how many were released. Called by the orchestrator's
        liveness watchdog when the entity is declared dead — the default
        is a no-op for policies without a delay queue."""
        return 0

    def start(self) -> None:
        """Start worker threads (idempotent)."""

    def shutdown(self) -> None:
        """Stop worker threads, flush pending actions, then emit
        :data:`POLICY_DONE` on ``action_out``."""
        self.action_out.put(POLICY_DONE)  # type: ignore[arg-type]

    # -- helpers for subclasses -----------------------------------------

    def _emit(self, action: Action) -> None:
        self.action_out.put(action)

    def _spawn(self, target: Callable[[], None], name: str) -> threading.Thread:
        t = threading.Thread(target=target, name=f"{self.name}-{name}", daemon=True)
        t.start()
        return t


class QueueBackedPolicy(ExplorePolicy):
    """Shared machinery for policies built around one ScheduledQueue: an
    idempotent start, a dequeue worker mapping each released event to an
    action via :meth:`_action_for`, and a flushing shutdown."""

    def __init__(self, seed: Optional[int] = None,
                 time_source=None) -> None:
        super().__init__()
        # the delay queue reads the process TimeSource by default: a
        # `run --virtual-clock` installs a VirtualTimeSource before the
        # policy is constructed, and the queue's parked deadlines
        # become the fast-forward coordinator's jump targets
        # (utils/timesource.py)
        self._queue = ScheduledQueue(seed=seed, obs_name=self.name,
                                     time_source=time_source)
        self._started = False
        self._start_lock = threading.Lock()
        self._dequeue_thread: Optional[threading.Thread] = None

    def start(self) -> None:
        with self._start_lock:
            if self._started:
                return
            self._started = True
            self._dequeue_thread = self._spawn(self._dequeue_loop, "dequeue")

    def queue_events(self, events: Iterable[Event]) -> "list[Event]":
        """Shared batch preamble (one home for the list/size/start
        boilerplate): single events ride the isolated scalar loop,
        larger batches go through :meth:`_queue_events_batch`."""
        events = list(events)
        if len(events) <= 1:
            return super().queue_events(events)
        self.start()
        return self._queue_events_batch(events)

    def _queue_events_batch(self, events: "list[Event]") -> "list[Event]":
        """Batch hook for >= 2 events (``start()`` already called):
        queue the whole batch, ideally under one queue-lock
        acquisition; returns the rejected events. Default: the
        isolated scalar loop."""
        return super().queue_events(events)

    #: how many simultaneously-ripe releases one dequeue pass may drain
    #: (and the largest burst list emitted on action_out)
    DEQUEUE_BATCH_MAX = 256

    def _dequeue_loop(self) -> None:
        while True:
            try:
                events = self._queue.get_batch(self.DEQUEUE_BATCH_MAX)
            except QueueClosed:
                return
            actions = []
            for event in events:
                # the released span feeds the causality plane's
                # parking/dispatch segment split (obs/causality.py);
                # the shared span dict makes it visible on the action
                obs.mark(event, "released")
                obs.record_released(event, self.name)
                obs.queue_dwell(self.name, event.entity_id,
                                obs.latency(event, "enqueued"))
                actions.append(self._action_for(event))
            if len(actions) == 1:
                self._emit(actions[0])
            else:
                # one queue hand-off for the whole burst (list form of
                # the action_out contract)
                self.action_out.put(actions)

    def _action_for(self, event: Event) -> Action:
        return event.default_action()

    def force_release_entity(self, entity_id: str) -> int:
        events = self._queue.expedite(
            lambda ev: getattr(ev, "entity_id", None) == entity_id,
            collect=True)
        # attribute the non-policy release: the chaos invariant checker
        # and `tools trace diff` must be able to tell "the watchdog
        # freed this" from "the policy chose this" (doc/robustness.md)
        for event in events:
            obs.record_decision(event, self.name, source="watchdog")
        return len(events)

    def shutdown(self) -> None:
        """Release all still-delayed events immediately, wait for the
        dequeue worker to flush their actions, then signal POLICY_DONE."""
        self._queue.close(immediate=True)
        t = self._dequeue_thread
        if t is not None:
            t.join(timeout=10)
        # dwell is normally observed at dequeue; events still resident
        # here (worker never started, died, or outlived the join window)
        # would otherwise vanish from the histogram — exactly the
        # long-stuck tail an operator most needs to see
        for event in self._queue.drain_remaining():
            entity = getattr(event, "entity_id", "")
            if entity:
                obs.queue_dwell(self.name, entity,
                                obs.latency(event, "enqueued"))
        super().shutdown()


PolicyFactory = Callable[[], ExplorePolicy]

_POLICIES: Dict[str, PolicyFactory] = {}


def register_policy(name: str, factory: PolicyFactory) -> None:
    """Register a policy factory (parity: RegisterPolicy,
    /root/reference/nmz/explorepolicy/explorepolicy.go:25-31)."""
    if name in _POLICIES:
        raise PolicyError(f"policy {name!r} already registered")
    _POLICIES[name] = factory


def create_policy(name: str) -> ExplorePolicy:
    """Instantiate a registered policy (parity: CreatePolicy,
    explorepolicy.go:33-37). The TPU search policy is registered lazily so
    that control-plane-only deployments never import jax."""
    if name == "tpu_search" and name not in _POLICIES:
        try:
            from namazu_tpu.policy import tpu as _tpu  # noqa: F401  (self-registers)
        except ImportError as e:
            raise PolicyError(f"tpu_search policy unavailable: {e}") from e
    try:
        factory = _POLICIES[name]
    except KeyError:
        raise PolicyError(
            f"unknown policy {name!r}; known: {sorted(_POLICIES)}"
        ) from None
    return factory()


def known_policies() -> Iterable[str]:
    return sorted(_POLICIES)
