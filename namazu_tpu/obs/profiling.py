"""Continuous sampling profiler (doc/observability.md "Profiling").

Every process class (orchestrator, campaign ``run`` children, edge
inspectors, uds/shm endpoints, the knowledge sidecar, the campaign
supervisor) runs one of these: a timer-driven stack sampler over
``sys._current_frames()`` that folds samples into a bounded
collapsed-stack table keyed by the plane taxonomy the rest of the obs
plane already speaks — ``edge`` / ``policy`` / ``wire`` / ``search`` /
``host_io`` (everything else: ``other``).

Cost contract (same as the recorder): with ``obs_enabled = false`` (or
``profile_enabled = false``) nothing starts and every module-level
helper is a single global ``None`` check. Enabled, the sampler costs
one ``sys._current_frames()`` walk per interval (default 100 Hz) —
measured ≤2% on the edge pipeline bench (``bench.py --pipeline`` A/B
vs ``--no-profile``).

Locking contract (the recorder-interplay rule): the sample path NEVER
takes the metrics-registry lock — or any lock shared with application
code. Samples append to a plain list (atomic under the GIL, the
"lock-free buffer"); a separate fold thread swaps the buffer out and
folds it into the collapsed table under the profiler's own private
lock. Only the fold thread — never the sampler — publishes fold stats
to the metrics registry. ``tests/test_profiling.py`` pins zero
deadlocks under concurrent registry hammering.

Exports: collapsed stacks (Brendan-Gregg folded text), speedscope JSON
(``GET /profile``), and a differential-selection delta payload that
rides the TelemetryRelay wire (absolute cumulative counts, fingerprints
acked only after a successful push — the PR 9 exactly-once contract
extended to profiles).
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

#: wire schema for the delta payload riding TelemetryRelay docs
SCHEMA = "nmz-profile-v1"

#: plane taxonomy — the same axis the recorder/causality planes use
PLANES = ("edge", "policy", "wire", "search", "host_io", "other")

#: default sampling period: 100 Hz keeps per-sample cost (~tens of µs
#: for a dozen threads) well under the 2% overhead contract
DEFAULT_INTERVAL_S = 0.01
#: fold cadence: how often the drain thread folds the sample buffer
DEFAULT_FOLD_INTERVAL_S = 0.5
#: bounded collapsed table: distinct stacks beyond this fold into a
#: per-plane ``(overflow)`` bucket and are counted, never dropped silently
DEFAULT_MAX_STACKS = 512
DEFAULT_MAX_DEPTH = 48

#: path fragments → plane, first match wins scanning leaf → root.
#: Fragments are matched against '/'-normalized co_filename.
_PLANE_PATHS = (
    ("namazu_tpu/inspector/edge", "edge"),
    ("namazu_tpu/policy/", "policy"),
    ("namazu_tpu/endpoint/", "wire"),
    ("namazu_tpu/signal/", "wire"),
    ("namazu_tpu/inspector/", "wire"),   # transceivers / signal wires
    ("namazu_tpu/obs/federation", "wire"),
    ("namazu_tpu/storage/", "host_io"),
    ("namazu_tpu/chaos/journal", "host_io"),
    ("namazu_tpu/models/", "search"),
    ("namazu_tpu/ops/", "search"),
    ("namazu_tpu/parallel/", "search"),
    ("namazu_tpu/guidance/", "search"),
    ("namazu_tpu/knowledge", "search"),
)

#: function names that pin a plane regardless of module (the fused
#: search loop's host lane lives in models/search.py but is host_io)
_PLANE_FUNCS = {
    "_drain_host_lane": "host_io",
    "_host_refill": "host_io",
}

_OVERFLOW_FRAME = "(overflow)"


def _norm_path(p: str) -> str:
    return p.replace("\\", "/")


def _relname(path: str) -> str:
    """Stable short name for a source file: repo-relative under
    ``namazu_tpu/`` (or the repo root), basename otherwise — so two
    rigs' profiles align frame-for-frame in profdiff."""
    p = _norm_path(path)
    i = p.rfind("namazu_tpu/")
    if i >= 0:
        return p[i:]
    parts = p.rsplit("/", 2)
    if len(parts) >= 2:
        return "/".join(parts[-2:])
    return p


class Profiler:
    """One per process. Two daemon threads: ``-sample`` walks
    ``sys._current_frames()`` on a timer and appends raw ``(tid,
    [code, ...])`` samples to a plain list; ``-fold`` periodically swaps
    that list out and folds it into the bounded collapsed table."""

    def __init__(self, job: str = "", *,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 fold_interval_s: float = DEFAULT_FOLD_INTERVAL_S,
                 max_stacks: int = DEFAULT_MAX_STACKS,
                 max_depth: int = DEFAULT_MAX_DEPTH) -> None:
        self.job = job or "proc"
        self.interval_s = max(0.001, float(interval_s))
        self.fold_interval_s = max(0.01, float(fold_interval_s))
        self.max_stacks = int(max_stacks)
        self.max_depth = int(max_depth)
        # sample path state: appended by the sampler thread only; the
        # fold thread swaps the whole list (both ops atomic under the
        # GIL — no lock on the sample path, ever)
        self._buf: List[Tuple[int, list]] = []
        # profiler-private lock guarding ONLY the folded table; taken
        # by the fold thread and by readers, never by the sampler
        self._lock = threading.Lock()
        self._stacks: Dict[Tuple[str, Tuple[str, ...]], int] = {}
        self._samples = 0
        self._dropped = 0          # samples folded into (overflow)
        self._own: set = set()     # sampler+fold thread idents (skipped)
        self._tags: Dict[int, str] = {}   # tid → plane override
        self._names: Dict[object, Tuple[str, Optional[str]]] = {}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._started = False

    # -- lifecycle ----------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._stop.clear()
        for name, fn in (("sample", self._sample_loop),
                         ("fold", self._fold_loop)):
            t = threading.Thread(target=fn, name=f"nmz-prof-{name}",
                                 daemon=True)
            self._threads.append(t)
        for t in self._threads:
            t.start()

    def stop(self, timeout: float = 2.0) -> None:
        if not self._started:
            return
        self._stop.set()
        for t in self._threads:
            t.join(timeout=timeout)
        self._threads = []
        self._started = False
        self._fold_once()   # drain whatever the sampler left behind

    def running(self) -> bool:
        return self._started

    def drain(self) -> None:
        """Synchronously fold whatever the sampler has buffered — for
        readers (bench epilogue, tests) that must not wait out a fold
        interval before a snapshot reflects recent samples."""
        self._fold_once()

    # -- sample path (NO foreign locks) -------------------------------

    def _sample_loop(self) -> None:
        self._own.add(threading.get_ident())
        stop, max_depth = self._stop, self.max_depth
        while not stop.wait(self.interval_s):
            try:
                frames = sys._current_frames()
            except Exception:
                continue
            own = self._own
            buf = self._buf   # re-read: the fold thread swaps it
            for tid, frame in frames.items():
                if tid in own:
                    continue
                stack = []
                f = frame
                while f is not None and len(stack) < max_depth:
                    stack.append(f.f_code)
                    f = f.f_back
                buf.append((tid, stack))
            del frames

    # -- fold path (may take the registry lock, off the sample path) ---

    def _fold_loop(self) -> None:
        self._own.add(threading.get_ident())
        while not self._stop.wait(self.fold_interval_s):
            self._fold_once()

    def _fold_once(self) -> None:
        # swap is atomic under the GIL; a sampler iteration holding the
        # old list may append a few more entries after the swap — those
        # are statistical dust (≤ one sample period per fold), accepted
        buf, self._buf = self._buf, []
        if not buf:
            return
        tags = dict(self._tags)
        folded: Dict[Tuple[str, Tuple[str, ...]], int] = {}
        for tid, codes in buf:
            key = self._fold_stack(tid, codes, tags)
            folded[key] = folded.get(key, 0) + 1
        dropped = 0
        with self._lock:
            st = self._stacks
            for key, n in folded.items():
                if key in st:
                    st[key] += n
                elif len(st) < self.max_stacks:
                    st[key] = n
                else:
                    # bounded table: fold into a per-plane overflow
                    # bucket (visible in exports) instead of dropping
                    dropped += n
                    ok = (key[0], (_OVERFLOW_FRAME,))
                    st[ok] = st.get(ok, 0) + n
            self._samples += sum(folded.values())
            self._dropped += dropped
        self._publish_fold_stats()

    def _fold_stack(self, tid: int, codes: list, tags: Dict[int, str]
                    ) -> Tuple[str, Tuple[str, ...]]:
        names_leaf_first: List[str] = []
        plane = None
        cache = self._names
        for code in codes:   # leaf → root
            ent = cache.get(code)
            if ent is None:
                path = _norm_path(code.co_filename)
                name = f"{_relname(path)}:{code.co_name}"
                p = _PLANE_FUNCS.get(code.co_name)
                if p is None:
                    for frag, pl in _PLANE_PATHS:
                        if frag in path:
                            p = pl
                            break
                if len(cache) > 8192:   # generated-code safety valve
                    cache.clear()
                ent = (name, p)
                cache[code] = ent
            names_leaf_first.append(ent[0])
            if plane is None and ent[1] is not None:
                plane = ent[1]
        if plane is None:
            plane = tags.get(tid, "other")
        return plane, tuple(reversed(names_leaf_first))

    def _publish_fold_stats(self) -> None:
        # fold-thread only — allowed to take the registry lock
        try:
            from namazu_tpu.obs import metrics
            if not metrics.enabled():
                return
            reg = metrics.get()
            g = reg.gauge("nmz_profile_samples_total",
                          "cumulative profiler samples folded")
            g.set(float(self._samples))
            reg.gauge("nmz_profile_stacks",
                      "distinct collapsed stacks held").set(
                float(len(self._stacks)))
            if self._dropped:
                reg.gauge("nmz_profile_overflow_samples_total",
                          "samples folded into the bounded-table "
                          "overflow bucket").set(float(self._dropped))
        except Exception:
            pass

    # -- tagging -------------------------------------------------------

    def tag_thread(self, tid: int, plane: str) -> None:
        """Pin a plane for a thread whose stacks don't resolve by module
        (e.g. a FramedServer worker parked in the selector)."""
        if plane in PLANES:
            self._tags[tid] = plane

    # -- exports -------------------------------------------------------

    def snapshot(self) -> dict:
        """Absolute cumulative payload — the profdiff/file interchange
        form and the base of the wire delta."""
        with self._lock:
            stacks = [{"plane": k[0], "stack": list(k[1]), "count": c}
                      for k, c in self._stacks.items()]
            samples, dropped = self._samples, self._dropped
        stacks.sort(key=lambda s: -s["count"])
        return {"schema": SCHEMA, "job": self.job,
                "interval_s": self.interval_s,
                "samples_total": samples, "dropped": dropped,
                "stacks": stacks}

    def collapsed(self) -> str:
        """Brendan-Gregg folded text: ``plane;root;...;leaf count``."""
        snap = self.snapshot()
        lines = [";".join([s["plane"]] + s["stack"]) + f" {s['count']}"
                 for s in snap["stacks"]]
        return "\n".join(lines) + ("\n" if lines else "")

    def speedscope(self) -> dict:
        return speedscope_from_payload(self.snapshot())

    def top_self_frame(self) -> Optional[dict]:
        """Dominant self-time frame: the leaf with the most samples.
        Feeds the /fleet PROF column."""
        selfs = self_times(self.snapshot())
        if not selfs:
            return None
        frame, count = max(selfs.items(), key=lambda kv: kv[1])
        total = sum(selfs.values())
        return {"frame": frame, "count": count,
                "share": (count / total) if total else 0.0}

    def reset_counts(self) -> None:
        with self._lock:
            self._stacks.clear()
            self._samples = 0
            self._dropped = 0
        self._buf = []


# -- payload helpers (pure functions, shared with profdiff) ------------

def self_times(payload: dict) -> Dict[str, int]:
    """Leaf self-sample counts per frame from a ``nmz-profile-v1``
    payload (the quantity profdiff ranks deltas on)."""
    out: Dict[str, int] = {}
    for s in payload.get("stacks") or []:
        stack = s.get("stack") or []
        if not stack:
            continue
        leaf = stack[-1]
        out[leaf] = out.get(leaf, 0) + int(s.get("count", 0))
    return out


def frame_planes(payload: dict) -> Dict[str, str]:
    """frame → plane (first plane seen claiming the frame as leaf)."""
    out: Dict[str, str] = {}
    for s in payload.get("stacks") or []:
        stack = s.get("stack") or []
        if stack:
            out.setdefault(stack[-1], s.get("plane", "other"))
    return out


def speedscope_from_payload(payload: dict) -> dict:
    """Render a payload as a speedscope "sampled" profile. Weights are
    seconds (count × sampling interval); each stack gets a synthetic
    ``plane:<name>`` root so the flamegraph groups by plane."""
    interval = float(payload.get("interval_s") or DEFAULT_INTERVAL_S)
    frames: List[dict] = []
    index: Dict[str, int] = {}

    def fidx(name: str) -> int:
        i = index.get(name)
        if i is None:
            i = len(frames)
            index[name] = i
            frames.append({"name": name})
        return i

    samples, weights = [], []
    total = 0.0
    for s in payload.get("stacks") or []:
        names = [f"plane:{s.get('plane', 'other')}"] + list(
            s.get("stack") or [])
        w = int(s.get("count", 0)) * interval
        samples.append([fidx(n) for n in names])
        weights.append(w)
        total += w
    prof = {"type": "sampled",
            "name": f"{payload.get('job') or 'proc'} cpu",
            "unit": "seconds", "startValue": 0, "endValue": total,
            "samples": samples, "weights": weights}
    return {"$schema": "https://www.speedscope.app/file-format-schema.json",
            "shared": {"frames": frames},
            "profiles": [prof], "activeProfileIndex": 0,
            "exporter": "namazu-tpu", "name": payload.get("job") or "proc"}


def payload_from_collapsed(text: str, job: str = "") -> dict:
    """Parse folded text back into a payload (profdiff file input)."""
    stacks = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        path, _, count = line.rpartition(" ")
        try:
            n = int(count)
        except ValueError:
            continue
        segs = path.split(";")
        if segs and segs[0] in PLANES:
            plane, segs = segs[0], segs[1:]
        else:
            plane = "other"
        if segs:
            stacks.append({"plane": plane, "stack": segs, "count": n})
    return {"schema": SCHEMA, "job": job, "interval_s": DEFAULT_INTERVAL_S,
            "samples_total": sum(s["count"] for s in stacks),
            "dropped": 0, "stacks": stacks}


def payload_from_speedscope(doc: dict) -> dict:
    """Invert :func:`speedscope_from_payload` (profdiff file input)."""
    frames = [f.get("name", "?") for f in
              (doc.get("shared") or {}).get("frames") or []]
    profs = doc.get("profiles") or []
    stacks: Dict[Tuple[str, Tuple[str, ...]], int] = {}
    interval = DEFAULT_INTERVAL_S
    for prof in profs:
        if prof.get("type") != "sampled":
            continue
        for idxs, w in zip(prof.get("samples") or [],
                           prof.get("weights") or []):
            names = [frames[i] for i in idxs if 0 <= i < len(frames)]
            plane = "other"
            if names and names[0].startswith("plane:"):
                plane = names[0][len("plane:"):]
                names = names[1:]
            if not names:
                continue
            key = (plane, tuple(names))
            # weights are seconds; undo the count×interval scaling
            stacks[key] = stacks.get(key, 0) + max(
                1, int(round(float(w) / interval)))
    out = [{"plane": k[0], "stack": list(k[1]), "count": c}
           for k, c in stacks.items()]
    return {"schema": SCHEMA, "job": doc.get("name") or "",
            "interval_s": interval,
            "samples_total": sum(s["count"] for s in out),
            "dropped": 0, "stacks": out}


# -- wire delta (PR 9 differential-selection contract) -----------------

class ProfileDelta:
    """Differential selection for the profile payload riding the
    TelemetryRelay doc: absolute cumulative counts, only stacks whose
    count changed since the last ACKED push are sent, and fingerprints
    advance only via :meth:`mark_acked` — a dropped push resends the
    same absolutes, a duplicate replay is deduped by the doc's ``seq``
    watermark, so the aggregator converges exactly-once."""

    #: bound per push; unsent changed stacks simply ride a later cycle
    MAX_STACKS_PER_PUSH = 512

    def __init__(self, prof: Profiler) -> None:
        self._prof = prof
        self._acked: Dict[Tuple[str, Tuple[str, ...]], int] = {}

    def encode(self) -> Tuple[Optional[dict], dict]:
        snap = self._prof.snapshot()
        changed = []
        fps: Dict[Tuple[str, Tuple[str, ...]], int] = {}
        for s in snap["stacks"]:
            key = (s["plane"], tuple(s["stack"]))
            if self._acked.get(key) == s["count"]:
                continue
            changed.append(s)
            fps[key] = s["count"]
            if len(changed) >= self.MAX_STACKS_PER_PUSH:
                break
        if not changed:
            return None, {}
        payload = {"schema": SCHEMA, "job": snap["job"],
                   "interval_s": snap["interval_s"],
                   "samples_total": snap["samples_total"],
                   "dropped": snap["dropped"], "stacks": changed}
        return payload, fps

    def mark_acked(self, fps: dict) -> None:
        self._acked.update(fps)

    def reset(self) -> None:
        self._acked.clear()


# -- process-global wiring (single-check no-op contract) ---------------

_PROFILER: Optional[Profiler] = None
_LOCK = threading.Lock()


def enabled() -> bool:
    return _PROFILER is not None


def profiler() -> Optional[Profiler]:
    return _PROFILER


def _profile_switched_off(cfg=None) -> bool:
    env = os.environ.get("NMZ_PROFILE", "").strip().lower()
    if env in ("0", "false", "off", "no"):
        return True
    if cfg is not None:
        try:
            v = cfg.get("profile_enabled")
        except Exception:
            v = None
        if v is not None and not bool(v):
            return True
    return False


def ensure_profiler(job: str, *, interval_s: Optional[float] = None,
                    cfg=None) -> Optional[Profiler]:
    """Idempotently start this process's profiler (mirrors
    ``federation.ensure_self_relay``). No-op — one enabled() check —
    when obs is off, and honored off-switches: ``profile_enabled =
    false`` / ``NMZ_PROFILE=0``. First caller names the job; later
    calls return the running instance unchanged."""
    global _PROFILER
    if _PROFILER is not None:
        return _PROFILER
    from namazu_tpu.obs import metrics
    if not metrics.enabled() or _profile_switched_off(cfg):
        return None
    if interval_s is None:
        try:
            interval_s = float(
                os.environ.get("NMZ_PROFILE_INTERVAL_S", "") or
                (cfg.get("profile_interval_s") if cfg is not None else 0)
                or DEFAULT_INTERVAL_S)
        except (TypeError, ValueError):
            interval_s = DEFAULT_INTERVAL_S
    with _LOCK:
        if _PROFILER is None:
            p = Profiler(job, interval_s=interval_s)
            p.start()
            _PROFILER = p
    return _PROFILER


def tag_current_thread(plane: str) -> None:
    """Plane hint for the calling thread; single global check when the
    profiler is off."""
    p = _PROFILER
    if p is not None:
        p.tag_thread(threading.get_ident(), plane)


def payload() -> Optional[dict]:
    p = _PROFILER
    return p.snapshot() if p is not None else None


def render_collapsed() -> str:
    p = _PROFILER
    return p.collapsed() if p is not None else ""


def speedscope_doc() -> Optional[dict]:
    p = _PROFILER
    return p.speedscope() if p is not None else None


def reset() -> None:
    """Test hygiene (mirrors ``federation.reset``): stop and forget the
    process profiler."""
    global _PROFILER
    with _LOCK:
        p, _PROFILER = _PROFILER, None
    if p is not None:
        p.stop()
