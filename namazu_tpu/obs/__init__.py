"""Observability plane: metrics registry + event-lifecycle spans.

``namazu_tpu.obs`` is the one import the rest of the stack uses:

* :mod:`namazu_tpu.obs.metrics` — thread-safe registry (counters,
  gauges, fixed-bucket histograms), Prometheus text renderer, global
  enable/disable with a shared no-op fallback;
* :mod:`namazu_tpu.obs.spans` — lifecycle stamping (interception ->
  decision -> dispatch -> ack) and the domain metric vocabulary.

Exposure: ``GET /metrics`` (Prometheus text) and ``GET /metrics.json``
on the REST endpoint (endpoint/rest.py), plus ``nmz-tpu tools metrics``
(cli/tools_cmd.py). Disable with ``obs_enabled = false`` in the
experiment config. Metric names and label conventions are documented in
doc/observability.md.
"""

from __future__ import annotations

from namazu_tpu.obs import metrics
from namazu_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    MetricError,
    MetricsRegistry,
    NullRegistry,
    configure,
    enabled,
    get,
    registry,
    reset,
    set_registry,
)
from namazu_tpu.obs.spans import (  # noqa: F401
    action_dispatched,
    carry,
    event_intercepted,
    latency,
    mark,
    policy_decision,
    queue_dwell,
    rest_ack,
    rest_request,
    sched_queue_depth,
    sched_queue_wait,
    schedule_install,
    scorer_throughput,
    scorer_throughput_value,
    search_round,
    sidecar_request,
    span,
)


def configure_from_config(config) -> None:
    """Apply the ``obs_enabled`` config key to the process-global flag
    (called by the orchestrator before any endpoint starts).

    Only an EXPLICIT key touches the flag: the switch is process-global
    (default on), and in multi-orchestrator processes — the ab harness,
    the test suite — a second orchestrator built from a default config
    must not silently re-enable telemetry someone disabled (or freeze
    the counters a live ``/metrics`` is serving)."""
    if config.is_set("obs_enabled"):
        metrics.configure(bool(config.get("obs_enabled")))


def render_prometheus() -> str:
    """Prometheus text of the default registry (the /metrics body)."""
    return metrics.registry().render_prometheus()


def registry_jsonable() -> dict:
    """JSON form of the default registry (the /metrics.json body and
    the ``nmz-tpu tools metrics`` dump)."""
    return metrics.registry().to_jsonable()
