"""Observability plane: metrics registry + spans + flight recorder.

``namazu_tpu.obs`` is the one import the rest of the stack uses:

* :mod:`namazu_tpu.obs.metrics` — thread-safe registry (counters,
  gauges, fixed-bucket histograms), Prometheus text renderer, global
  enable/disable with a shared no-op fallback;
* :mod:`namazu_tpu.obs.spans` — lifecycle stamping (interception ->
  decision -> dispatch -> ack), the domain metric vocabulary, and the
  search-plane phase profiler (``search_phase``);
* :mod:`namazu_tpu.obs.recorder` — the flight recorder: bounded per-run
  event-timeline capture with run-correlated structured records;
* :mod:`namazu_tpu.obs.export` — Chrome-trace/Perfetto + NDJSON
  exporters and the dispatch-order differ over recorded runs;
* :mod:`namazu_tpu.obs.analytics` — the experiment plane: cross-run
  exploration coverage, reproduction-rate stats, search convergence +
  stall detection, fault-localization ranking;
* :mod:`namazu_tpu.obs.report` — Markdown/NDJSON renderers for the
  analytics payload.

Exposure: ``GET /metrics`` + ``/metrics.json``, ``GET /traces`` +
``/traces/<run_id>``, ``GET /analytics``, and ``GET /healthz`` on the
REST endpoint (endpoint/rest.py), plus ``nmz-tpu tools metrics``,
``nmz-tpu tools trace {list,dump,diff,export}``, and ``nmz-tpu tools
report`` (cli/tools_cmd.py). Disable with ``obs_enabled = false`` in
the experiment config. Metric names, the trace record schema, the
analytics payload schema, and run-id correlation rules are documented
in doc/observability.md.
"""

from __future__ import annotations

from namazu_tpu.obs import analytics, export, metrics, recorder, report  # noqa: F401
from namazu_tpu.obs.recorder import (  # noqa: F401
    FlightRecorder,
    begin_run,
    current_generation_id,
    current_run_id,
    end_run,
    record_acked,
    record_decided,
    record_decision,
    record_dispatched,
    record_edge,
    record_enqueued,
    record_generation,
    record_install,
    record_intercepted,
    record_released,
)
from namazu_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    MetricError,
    MetricsRegistry,
    NullRegistry,
    configure,
    enabled,
    get,
    registry,
    reset,
    set_registry,
)
from namazu_tpu.obs.spans import (  # noqa: F401
    action_dispatched,
    action_unroutable,
    carry,
    chaos_fault_injected,
    edge_decision,
    entity_stalled,
    event_batch,
    event_intercepted,
    experiment_stats,
    ingress_rejected,
    journal_events,
    journal_recovered,
    knowledge_outage,
    knowledge_pull,
    knowledge_push,
    knowledge_service_stats,
    knowledge_surrogate_round,
    knowledge_warmstart,
    latency,
    mark,
    policy_decision,
    queue_dwell,
    rest_ack,
    rest_request,
    sched_queue_depth,
    sched_queue_wait,
    schedule_install,
    scorer_throughput,
    scorer_throughput_value,
    search_phase,
    search_round,
    search_stall,
    sidecar_request,
    span,
    table_version,
    transport_retry_after,
    transport_rtt,
)


def configure_from_config(config) -> None:
    """Apply the ``obs_enabled`` config key to the process-global flag
    (called by the orchestrator before any endpoint starts).

    Only an EXPLICIT key touches the flag: the switch is process-global
    (default on), and in multi-orchestrator processes — the ab harness,
    the test suite — a second orchestrator built from a default config
    must not silently re-enable telemetry someone disabled (or freeze
    the counters a live ``/metrics`` is serving)."""
    if config.is_set("obs_enabled"):
        metrics.configure(bool(config.get("obs_enabled")))


def render_prometheus() -> str:
    """Prometheus text of the default registry (the /metrics body)."""
    return metrics.registry().render_prometheus()


def registry_jsonable() -> dict:
    """JSON form of the default registry (the /metrics.json body and
    the ``nmz-tpu tools metrics`` dump)."""
    return metrics.registry().to_jsonable()


def trace_summaries() -> list:
    """Recorded-run summaries (the ``GET /traces`` body)."""
    return recorder.recorder().summaries()


def trace_run(run_id: str):
    """The recorded :class:`~namazu_tpu.obs.recorder.RunTrace` for
    ``run_id`` ("latest" = most recently begun), or None."""
    return recorder.recorder().run(run_id)


def set_analytics_storage(dir_path) -> None:
    """Register the experiment storage dir the live ``GET /analytics``
    route aggregates over (``nmz-tpu run`` calls this with its storage;
    None unregisters)."""
    analytics.set_storage_dir(dir_path)


def set_knowledge_address(addr) -> None:
    """Register the knowledge-service address whose pool/tenant stats
    the live analytics payload folds in (``run --knowledge`` calls
    this; None unregisters)."""
    analytics.set_knowledge_address(addr)


def analytics_payload(top: int = analytics.DEFAULT_TOP,
                      window: int = analytics.DEFAULT_WINDOW) -> dict:
    """The experiment-analytics document (the ``GET /analytics`` body):
    the registered storage joined with this process's recorded runs."""
    return analytics.payload(top=top, window=window)
