"""Observability plane: metrics registry + spans + flight recorder.

``namazu_tpu.obs`` is the one import the rest of the stack uses:

* :mod:`namazu_tpu.obs.metrics` — thread-safe registry (counters,
  gauges, fixed-bucket histograms), Prometheus text renderer, global
  enable/disable with a shared no-op fallback;
* :mod:`namazu_tpu.obs.spans` — lifecycle stamping (interception ->
  decision -> dispatch -> ack), the domain metric vocabulary, and the
  search-plane phase profiler (``search_phase``);
* :mod:`namazu_tpu.obs.recorder` — the flight recorder: bounded per-run
  event-timeline capture with run-correlated structured records;
* :mod:`namazu_tpu.obs.export` — Chrome-trace/Perfetto + NDJSON
  exporters and the dispatch-order differ over recorded runs;
* :mod:`namazu_tpu.obs.analytics` — the experiment plane: cross-run
  exploration coverage, reproduction-rate stats, search convergence +
  stall detection, fault-localization ranking;
* :mod:`namazu_tpu.obs.report` — Markdown/NDJSON renderers for the
  analytics payload.

Exposure: ``GET /metrics`` + ``/metrics.json``, ``GET /traces`` +
``/traces/<run_id>``, ``GET /analytics``, and ``GET /healthz`` on the
REST endpoint (endpoint/rest.py), plus ``nmz-tpu tools metrics``,
``nmz-tpu tools trace {list,dump,diff,export}``, and ``nmz-tpu tools
report`` (cli/tools_cmd.py). Disable with ``obs_enabled = false`` in
the experiment config. Metric names, the trace record schema, the
analytics payload schema, and run-id correlation rules are documented
in doc/observability.md.
"""

from __future__ import annotations

from namazu_tpu.obs import (  # noqa: F401
    analytics,
    causality,
    context,
    export,
    federation,
    metrics,
    profdiff,
    profiling,
    recorder,
    report,
    slo,
)
from namazu_tpu.obs.recorder import (  # noqa: F401
    FlightRecorder,
    begin_run,
    current_generation_id,
    current_run_id,
    end_run,
    record_acked,
    record_annotation,
    record_decided,
    record_decision,
    record_dispatched,
    record_edge,
    record_enqueued,
    record_generation,
    record_install,
    record_intercepted,
    record_released,
)
from namazu_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    MetricError,
    MetricsRegistry,
    NullRegistry,
    configure,
    enabled,
    get,
    registry,
    reset,
    set_registry,
)
from namazu_tpu.obs.spans import (  # noqa: F401
    action_dispatched,
    action_unroutable,
    campaign_progress,
    campaign_slot,
    carry,
    chaos_fault_injected,
    codec_negotiated,
    edge_backhaul_lag,
    edge_decision,
    edge_parked,
    edge_table_staleness,
    edge_table_version_held,
    entity_stalled,
    event_batch,
    event_intercepted,
    event_stage,
    event_stage_many,
    experiment_stats,
    fleet_admission_rejected,
    fleet_migration,
    fleet_occupancy,
    fleet_pool_stats,
    ingress_rejected,
    journal_events,
    journal_recovered,
    knowledge_fanin,
    knowledge_outage,
    knowledge_pull,
    knowledge_push,
    knowledge_service_stats,
    knowledge_surrogate_round,
    knowledge_warmstart,
    latency,
    mark,
    policy_decision,
    queue_dwell,
    relation_coverage,
    rest_ack,
    rest_request,
    sched_queue_depth,
    sched_queue_wait,
    shm_ring_full,
    schedule_install,
    scorer_throughput,
    scorer_throughput_value,
    search_device_trace,
    search_phase,
    search_progress,
    search_round,
    search_stall,
    sidecar_request,
    slo_breach,
    slo_burn,
    tenancy_events,
    tenancy_parked,
    tenancy_reclaim,
    tenancy_runs,
    rest_conn_pool,
    span,
    span_delta,
    table_propagation,
    table_version,
    telemetry_forward_dropped,
    telemetry_push,
    transport_retry_after,
    transport_rtt,
    triage_dossier_pull,
    triage_minimized,
    triage_probe,
    vclock_pinned,
    vclock_speedup,
    triage_signatures,
    wire_bytes,
)


def configure_from_config(config) -> None:
    """Apply the ``obs_enabled`` config key to the process-global flag
    (called by the orchestrator before any endpoint starts).

    Only an EXPLICIT key touches the flag: the switch is process-global
    (default on), and in multi-orchestrator processes — the ab harness,
    the test suite — a second orchestrator built from a default config
    must not silently re-enable telemetry someone disabled (or freeze
    the counters a live ``/metrics`` is serving)."""
    if config.is_set("obs_enabled"):
        metrics.configure(bool(config.get("obs_enabled")))
        if not metrics.enabled():
            # the profiler rides the obs switch: turning the plane off
            # also stops an already-started sampler (obs/profiling.py)
            profiling.reset()
    # fleet telemetry federation keys (telemetry_enabled, SLO specs,
    # staleness/eviction windows) — same explicit-keys-only rule
    federation.configure_from_config(config)


def render_prometheus() -> str:
    """Prometheus text of the default registry (the /metrics body).
    Sampled gauges (edge staleness/parked depth, knowledge occupancy)
    are refreshed first — a direct read must not serve values up to a
    relay push interval old."""
    federation.run_collectors()
    return metrics.registry().render_prometheus()


def registry_jsonable() -> dict:
    """JSON form of the default registry (the /metrics.json body and
    the ``nmz-tpu tools metrics`` dump); sampled gauges refreshed
    first, same as :func:`render_prometheus`."""
    federation.run_collectors()
    return metrics.registry().to_jsonable()


def trace_summaries() -> list:
    """Recorded-run summaries (the ``GET /traces`` body)."""
    return recorder.recorder().summaries()


def trace_run(run_id: str):
    """The recorded :class:`~namazu_tpu.obs.recorder.RunTrace` for
    ``run_id`` ("latest" = most recently begun), or None."""
    return recorder.recorder().run(run_id)


def set_analytics_storage(dir_path) -> None:
    """Register the experiment storage dir the live ``GET /analytics``
    route aggregates over (``nmz-tpu run`` calls this with its storage;
    None unregisters)."""
    analytics.set_storage_dir(dir_path)


def set_knowledge_address(addr) -> None:
    """Register the knowledge-service address whose pool/tenant stats
    the live analytics payload folds in (``run --knowledge`` calls
    this; None unregisters)."""
    analytics.set_knowledge_address(addr)


def analytics_payload(top: int = analytics.DEFAULT_TOP,
                      window: int = analytics.DEFAULT_WINDOW) -> dict:
    """The experiment-analytics document (the ``GET /analytics`` body):
    the registered storage joined with this process's recorded runs."""
    return analytics.payload(top=top, window=window)


def progress_payload() -> dict:
    """The campaign-progress document (the ``GET /progress`` body):
    sequential repro-rate statistics, band verdict, and ETA forecasts
    over the registered storage — always served, zeros before the first
    run lands."""
    return analytics.progress_payload()


def causality_run_payload(run_id: str):
    """The ``GET /causality/<run_id>`` body (happens-before graph +
    critical-path attribution), or None for an unknown run."""
    run = recorder.recorder().run(run_id)
    if run is None:
        return None
    return causality.run_payload(run)


#: memoized fault-localization ranking for the why route:
#: (storage dir, run count, top) -> analyzer ranking. analyze_storage
#: reads every stored run's coverage file — repeating that per
#: GET /causality/<a>/<b> would turn a ranking hint into full-storage
#: I/O in the request handler; the ranking only changes when a run
#: completes, which the run count witnesses.
_why_suspicious_cache: dict = {}


def _why_suspicious(top: int):
    d = analytics.storage_dir()
    if not d:
        return None
    try:
        from namazu_tpu.analyzer import analyze_storage
        from namazu_tpu.storage import load_storage

        st = load_storage(d)
        try:
            key = (d, st.nr_stored_histories(), top)
            if key in _why_suspicious_cache:
                return _why_suspicious_cache[key]
            ranking = analyze_storage(st, top=top)
        finally:
            st.close()
        _why_suspicious_cache.clear()  # one storage, one live key
        _why_suspicious_cache[key] = ranking
        return ranking
    except Exception:  # localization is a ranking hint, never a 500
        return None


def causality_why_payload(run_a: str, run_b: str, top: int = 20):
    """The ``GET /causality/<a>/<b>`` body (ordering-relation flips +
    per-run causality summaries), or None when either run is unknown.
    The analyzer's fault-localization ranking (from the registered
    analytics storage, when one exists) feeds the flip scoring."""
    a = recorder.recorder().run(run_a)
    b = recorder.recorder().run(run_b)
    if a is None or b is None:
        return None
    docs_a, _, rid_a = causality.docs_of_run(a)
    docs_b, _, rid_b = causality.docs_of_run(b)
    return causality.why_payload(docs_a, docs_b, rid_a, rid_b,
                                 top=top,
                                 suspicious=_why_suspicious(top))


def note_telemetry_push(doc) -> dict:
    """Merge one pushed telemetry doc into this process's fleet
    aggregator (the ``POST /api/v3/telemetry`` body; raises ValueError
    on a malformed doc). A disabled plane acks-and-discards — the
    ``telemetry_enabled = false`` kill switch holds on the serving
    side too."""
    if not federation.enabled():
        return {"ok": True, "disabled": True}
    return federation.aggregator().note_push(doc)


def fleet_payload() -> dict:
    """The fleet status document (the ``GET /fleet`` body)."""
    return federation.aggregator().payload()


def fleet_prometheus() -> str:
    """The whole fleet as one Prometheus text exposition (the
    ``GET /fleet?format=prom`` body)."""
    return federation.aggregator().prometheus()


def profile_payload():
    """This process's sampling profile as the ``nmz-profile-v1``
    payload (the ``GET /profile?format=json`` body), or None when the
    profiler is off."""
    return profiling.payload()


def profile_collapsed() -> str:
    """This process's profile as folded collapsed-stack text (the
    ``GET /profile?format=collapsed`` body); empty when off."""
    return profiling.render_collapsed()


def profile_speedscope():
    """This process's profile as a speedscope JSON document (the
    default ``GET /profile`` body), or None when the profiler is
    off."""
    return profiling.speedscope_doc()
