"""Declarative SLOs over the federated metric stream (obs/federation.py).

The fleet aggregator merges per-process metric pushes; this module sits
on top of that stream and answers the operator question the raw
registry cannot: *is the fleet keeping its latency promises right
now?*  Objectives are declared in the experiment config::

    [[slo]]
    name = "dispatch_p99"
    kind = "latency"                      # histogram-fraction objective
    metric = "nmz_event_e2e_seconds"
    threshold_s = 1.0                     # "good" = observation <= this
    target = 0.99                         # fraction that must be good
    window_s = 60

    [[slo]]
    name = "edge_staleness"
    kind = "staleness"                    # fleet-max-gauge objective
    metric = "nmz_edge_table_staleness_seconds"
    threshold_s = 30

and default to :data:`DEFAULT_SLOS` (dispatch p99, edge backhaul
reconcile lag p99, edge table staleness) when the config declares none.

**Burn rate** is the standard error-budget burn: for a latency
objective, ``bad_fraction / (1 - target)`` over the sliding window —
burn 1.0 means the budget is being consumed exactly as fast as it
accrues, anything above is a breach; for a staleness objective,
``fleet_max(gauge) / threshold``. Burn is published as
``nmz_slo_burn{slo}`` on every evaluation, breach TRANSITIONS count in
``nmz_slo_breaches_total{slo}``, land as one flight-recorder annotation
record (``kind="slo"``, obs/recorder.py) and one run-tagged warning,
and the full objective table rides the ``/fleet`` payload (and, when
objectives were declared explicitly, the ``/analytics`` payload so
``tools report`` shows compliance per run).

The window is fed with histogram *bucket deltas* the aggregator
computes while merging pushes — no second pass over the fleet state,
and a replayed push (deduped by seq upstream) can never double-feed a
window.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

from namazu_tpu.obs import recorder, spans
from namazu_tpu.utils.log import get_logger

log = get_logger("obs.slo")

__all__ = ["SLOSpec", "SLOEvaluator", "DEFAULT_SLOS", "specs_from_config"]

KIND_LATENCY = "latency"
KIND_STALENESS = "staleness"


class SLOSpec:
    """One declared objective (immutable)."""

    __slots__ = ("name", "kind", "metric", "threshold_s", "target",
                 "window_s")

    def __init__(self, name: str, metric: str, threshold_s: float,
                 kind: str = KIND_LATENCY, target: float = 0.99,
                 window_s: float = 60.0) -> None:
        if kind not in (KIND_LATENCY, KIND_STALENESS):
            raise ValueError(f"slo {name!r}: unknown kind {kind!r} "
                             f"(known: {KIND_LATENCY}, {KIND_STALENESS})")
        if not name or not metric:
            raise ValueError("slo needs a name and a metric")
        self.name = str(name)
        self.kind = kind
        self.metric = str(metric)
        self.threshold_s = float(threshold_s)
        self.target = min(max(float(target), 0.0), 0.999999)
        self.window_s = max(1.0, float(window_s))

    def to_jsonable(self) -> Dict[str, Any]:
        return {"name": self.name, "kind": self.kind,
                "metric": self.metric, "threshold_s": self.threshold_s,
                "target": self.target, "window_s": self.window_s}


#: the objectives every fleet gets unless the config declares its own:
#: generous thresholds — they exist to catch a *degrading* fleet, not to
#: turn healthy CI runs red
DEFAULT_SLOS: List[SLOSpec] = [
    # latency thresholds sit ON metrics.DEFAULT_BUCKETS bounds: "good"
    # is counted at bucket granularity, so a threshold inside a bucket
    # (e.g. 2.0 in the (1.0, 2.5] bucket) would count legitimately-
    # good observations as bad and breach a healthy fleet
    SLOSpec("dispatch_p99", spans.EVENT_E2E, threshold_s=1.0,
            target=0.99, window_s=60.0),
    SLOSpec("backhaul_lag_p99", spans.EDGE_BACKHAUL_LAG, threshold_s=2.5,
            target=0.99, window_s=60.0),
    SLOSpec("edge_staleness", spans.EDGE_TABLE_STALENESS,
            kind=KIND_STALENESS, threshold_s=30.0),
]


def specs_from_config(raw: Sequence[Dict[str, Any]]) -> List[SLOSpec]:
    """Parse the config's ``slo`` table list; raises ValueError on a
    malformed entry (a silently-ignored objective would report a
    meaningless green)."""
    specs = []
    for i, entry in enumerate(raw or []):
        if not isinstance(entry, dict):
            raise ValueError(f"slo entry {i} is not a table")
        try:
            specs.append(SLOSpec(
                name=entry["name"], metric=entry["metric"],
                threshold_s=entry["threshold_s"],
                kind=str(entry.get("kind", KIND_LATENCY)),
                target=float(entry.get("target", 0.99)),
                window_s=float(entry.get("window_s", 60.0))))
        except KeyError as e:
            raise ValueError(f"slo entry {i} is missing {e}") from None
    return specs


class _Window:
    """Sliding (t, good, total) window for one latency objective."""

    __slots__ = ("window_s", "entries", "good", "total")

    def __init__(self, window_s: float) -> None:
        self.window_s = window_s
        self.entries: deque = deque()
        self.good = 0
        self.total = 0

    def add(self, t: float, good: int, total: int) -> None:
        if total <= 0:
            return
        self.entries.append((t, good, total))
        self.good += good
        self.total += total

    def prune(self, now: float) -> None:
        cutoff = now - self.window_s
        entries = self.entries
        while entries and entries[0][0] < cutoff:
            _, g, n = entries.popleft()
            self.good -= g
            self.total -= n


class SLOEvaluator:
    """Burn-rate computation over the aggregator's merge stream.

    ``explicit`` records whether the specs came from config (vs the
    built-in defaults): only explicitly-declared objectives fold into
    the ``/analytics`` payload, so the golden REST-vs-CLI parity of the
    analytics document survives in fleets that never declared any."""

    def __init__(self, specs: Sequence[SLOSpec],
                 explicit: bool = False) -> None:
        self.specs = list(specs)
        names = [s.name for s in self.specs]
        dupes = sorted({n for n in names if names.count(n) > 1})
        if dupes:
            # two same-named objectives would share one window and
            # blend their good-counts — both rows would report a
            # fabricated burn (a copy-pasted [[slo]] block that only
            # changed threshold_s must fail loudly, not read green)
            raise ValueError(f"duplicate slo name(s): {', '.join(dupes)}")
        self.explicit = explicit
        self._lock = threading.Lock()
        self._windows: Dict[str, _Window] = {
            s.name: _Window(s.window_s) for s in self.specs
            if s.kind == KIND_LATENCY}
        self._by_metric: Dict[str, List[SLOSpec]] = {}
        for s in self.specs:
            if s.kind == KIND_LATENCY:
                self._by_metric.setdefault(s.metric, []).append(s)
        self._breached: Dict[str, bool] = {}
        self._breaches: Dict[str, int] = {}

    def watches(self, metric: str) -> bool:
        """Whether any latency objective consumes this histogram (the
        aggregator only computes bucket deltas for watched metrics)."""
        return metric in self._by_metric

    def note_hist_delta(self, metric: str, uppers: Sequence[float],
                        bucket_deltas: Sequence[int],
                        now: Optional[float] = None) -> None:
        """Feed one merged push's raw bucket deltas (len(uppers)+1,
        last = the +Inf overflow) into every objective watching
        ``metric``."""
        specs = self._by_metric.get(metric)
        if not specs:
            return
        now = time.monotonic() if now is None else now
        total = int(sum(bucket_deltas))
        if total <= 0:
            return
        with self._lock:
            for spec in specs:
                # "good" = observations in buckets whose upper bound is
                # <= the threshold (bucket granularity is the histogram
                # contract; pick thresholds on bucket bounds for exact
                # accounting)
                cut = bisect.bisect_right(list(uppers), spec.threshold_s)
                good = int(sum(bucket_deltas[:cut]))
                win = self._windows[spec.name]
                win.add(now, good, total)
                # prune on ingest too: an evaluator nobody reads
                # (evaluate() only runs on /fleet or analytics reads)
                # must not grow its window deque without bound
                win.prune(now)

    def evaluate(self, max_gauge: Callable[[str], Optional[float]],
                 now: Optional[float] = None) -> List[Dict[str, Any]]:
        """The objective table (one row per SLO): burn, breach flag,
        window occupancy. Publishes ``nmz_slo_burn``; breach
        transitions count, warn, and stamp a flight-recorder
        annotation. ``max_gauge(name)`` resolves a staleness
        objective's fleet-max gauge value (None = no producer reports
        it — burn 0, not a breach)."""
        now = time.monotonic() if now is None else now
        rows: List[Dict[str, Any]] = []
        transitions: List[Dict[str, Any]] = []
        with self._lock:
            for spec in self.specs:
                row = spec.to_jsonable()
                if spec.kind == KIND_LATENCY:
                    win = self._windows[spec.name]
                    win.prune(now)
                    good, total = win.good, win.total
                    bad_frac = ((total - good) / total) if total else 0.0
                    burn = bad_frac / (1.0 - spec.target)
                    row.update(good=good, total=total,
                               bad_fraction=round(bad_frac, 6))
                else:
                    value = max_gauge(spec.metric)
                    burn = ((float(value) / spec.threshold_s)
                            if value is not None and spec.threshold_s > 0
                            else 0.0)
                    row.update(value=value)
                breached = burn >= 1.0
                row.update(burn=round(burn, 4), breached=breached)
                was = self._breached.get(spec.name, False)
                self._breached[spec.name] = breached
                if breached and not was:
                    self._breaches[spec.name] = \
                        self._breaches.get(spec.name, 0) + 1
                    transitions.append(dict(row))
                elif was and not breached:
                    transitions.append(dict(row, recovered=True))
                row["breaches"] = self._breaches.get(spec.name, 0)
                rows.append(row)
        # metrics/recorder/log OUTSIDE the lock: none of them may ever
        # block a concurrent merge
        for row in rows:
            spans.slo_burn(row["name"], row["burn"])
        for row in transitions:
            if row.get("recovered"):
                log.info("SLO %s recovered (burn %.2f)", row["name"],
                         row["burn"])
                continue
            spans.slo_breach(row["name"])
            recorder.record_annotation(
                "slo", slo=row["name"], burn=row["burn"], breached=True,
                threshold_s=row["threshold_s"])
            log.warning(
                "SLO %s BREACHED: burn %.2f over %gs window (metric %s, "
                "threshold %gs)", row["name"], row["burn"],
                row["window_s"], row["metric"], row["threshold_s"])
        return rows
