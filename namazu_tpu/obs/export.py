"""Exporters for recorded runs: Chrome-trace/Perfetto JSON and NDJSON.

A :class:`~namazu_tpu.obs.recorder.RunTrace` renders three ways:

* :func:`chrome_trace` — the Chrome Trace Event format (the JSON both
  ``chrome://tracing`` and https://ui.perfetto.dev load directly): one
  track (pid/tid pair) per entity, one per policy, and one for the
  search plane's generation rounds + schedule installs. Every event's
  ``args`` carries the full structured record, so the decision that
  caused a delay is one click away in the UI.
* :func:`to_ndjson` — newline-delimited JSON, one record per line with
  run-relative timestamps (µs precision), stable across identical
  scripted runs, so two runs diff with plain ``diff``.
* :func:`order_lines` / :func:`diff_runs` — the realized dispatch
  ORDER only (entity + event class + hint), the thing Namazu exists to
  control; :func:`diff_runs` renders two runs' orders as a unified
  diff.

All exporters work off ``RunTrace.snapshot()`` — one lock acquisition,
then pure rendering — so they are safe against writers mid-run.
"""

from __future__ import annotations

import difflib
import json
from typing import Any, Dict, List

# Chrome-trace process ids: one synthetic "process" per plane so the
# viewer groups entity tracks, policy tracks, and the search plane's
# generation track into three collapsible blocks.
PID_ENTITIES = 1
PID_POLICIES = 2
PID_SEARCH = 3

_PROCESS_NAMES = {
    PID_ENTITIES: "entities",
    PID_POLICIES: "policies",
    PID_SEARCH: "search plane",
}


def _us(snapshot: Dict[str, Any], mono: float) -> int:
    """Monotonic stamp -> integer µs offset from the run's start."""
    return max(0, int(round((mono - snapshot["started_mono"]) * 1e6)))


class _Tracks:
    """Stable (pid, name) -> integer tid assignment + metadata events."""

    def __init__(self) -> None:
        self._tids: Dict[tuple, int] = {}
        self._per_pid: Dict[int, int] = {}
        self.meta: List[Dict[str, Any]] = []

    def tid(self, pid: int, name: str) -> int:
        key = (pid, name)
        tid = self._tids.get(key)
        if tid is None:
            tid = self._per_pid.get(pid, 0) + 1
            self._per_pid[pid] = tid
            self._tids[key] = tid
            self.meta.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": name},
            })
        return tid


def chrome_trace(run) -> Dict[str, Any]:
    """Render a recorded run as a Chrome Trace Event JSON document."""
    snap = run.snapshot()
    tracks = _Tracks()
    events: List[Dict[str, Any]] = []

    for entry in snap["records"]:
        rec, doc = entry["rec"], entry["json"]
        t = rec.t
        first = rec.first_stamp()
        if first is None:
            continue
        last = max(t.values())
        # entity track: the event's whole life, interception -> last
        # stamp. Async begin/end pairs ('b'/'e', keyed by the event
        # uuid), NOT complete 'X' slices: several events are in flight
        # per entity at once — the very concurrency this recorder exists
        # to show — and 'X' slices on one tid must be strictly nested,
        # so partially-overlapping spans would mis-render in the viewer.
        entity = rec.entity or "_unknown"
        name = rec.event_class or "event"
        if rec.hint:
            name = f"{name}:{rec.hint}"
        name = name[:120]
        tid = tracks.tid(PID_ENTITIES, entity)
        events.append({
            "name": name, "cat": "event", "ph": "b", "id": rec.event_id,
            "pid": PID_ENTITIES, "tid": tid,
            "ts": _us(snap, first), "args": doc,
        })
        events.append({
            "name": name, "cat": "event", "ph": "e", "id": rec.event_id,
            "pid": PID_ENTITIES, "tid": tid,
            "ts": max(_us(snap, last), _us(snap, first)),
        })
        # policy track: decision -> release/dispatch, i.e. the injected
        # schedule itself (the span Namazu is in the business of
        # shaping). Also async pairs: a policy holds many delayed events
        # concurrently, so these spans overlap by construction. The
        # 'decision' cat keeps the pair distinct from the entity pair
        # sharing the same id (async matching is by cat + id + name).
        if rec.policy and "decided" in t:
            end = t.get("released", t.get("dispatched", t["decided"]))
            pname = (rec.hint or name)[:120]
            ptid = tracks.tid(PID_POLICIES, rec.policy)
            events.append({
                "name": pname, "cat": "decision", "ph": "b",
                "id": rec.event_id,
                "pid": PID_POLICIES, "tid": ptid,
                "ts": _us(snap, t["decided"]),
                "args": {"event": rec.event_id, "entity": rec.entity,
                         "decision": dict(rec.decision)},
            })
            events.append({
                "name": pname, "cat": "decision", "ph": "e",
                "id": rec.event_id,
                "pid": PID_POLICIES, "tid": ptid,
                "ts": max(_us(snap, end), _us(snap, t["decided"])),
            })

    for g in snap["generations"]:
        if g.get("kind") == "generation":
            tid = tracks.tid(PID_SEARCH, f"generations:{g['backend']}")
            events.append({
                "name": f"gen {g['gen_start']}..{g['gen_end']}",
                "cat": "search",
                "ph": "X",
                "pid": PID_SEARCH,
                "tid": tid,
                "ts": _us(snap, g["t_start"]),
                "dur": max(0, _us(snap, g["t_end"]) - _us(snap, g["t_start"])),
                "args": {"backend": g["backend"],
                         "best_fitness": g.get("best_fitness")},
            })
        elif g.get("kind") == "install":
            tid = tracks.tid(PID_SEARCH, "installs")
            events.append({
                "name": f"install:{g['source']}",
                "cat": "search",
                "ph": "i",
                "s": "p",
                "pid": PID_SEARCH,
                "tid": tid,
                "ts": _us(snap, g["t"]),
                "args": {"source": g["source"],
                         "generation": g.get("generation")},
            })

    events.sort(key=lambda e: (e["ts"], e["pid"], e["tid"]))
    meta = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": pname},
    } for pid, pname in sorted(_PROCESS_NAMES.items())]
    return {
        "traceEvents": meta + tracks.meta + events,
        "displayTimeUnit": "ms",
        "metadata": {
            "run_id": snap["run_id"],
            "started_unix": round(snap["started_wall"], 6),
            "records": len(snap["records"]),
            "dropped_records": snap["dropped_records"],
        },
    }


def to_ndjson(run) -> str:
    """One JSON line per event record (interception order), then one per
    search-plane entry — run-relative µs-precision times throughout, so
    identical scripted runs serialize identically."""
    snap = run.snapshot()
    anchor = snap["started_mono"]
    lines = []
    for entry in snap["records"]:
        doc = dict(entry["json"])
        doc["run_id"] = snap["run_id"]
        lines.append(json.dumps(doc, sort_keys=True))
    for g in snap["generations"]:
        doc = dict(g)
        for key in ("t", "t_start", "t_end"):
            if key in doc:
                doc[key] = round(doc[key] - anchor, 6)
        doc["run_id"] = snap["run_id"]
        lines.append(json.dumps(doc, sort_keys=True))
    return "\n".join(lines) + ("\n" if lines else "")


def order_lines_from_docs(docs) -> List[str]:
    """Realized dispatch order from record dicts in the NDJSON shape —
    the ONE implementation of the order identity (entity + class:hint,
    sorted by dispatch stamp; uuids and timings deliberately absent):
    both the in-process path below and the CLI's over-the-wire
    ``trace diff`` route through it, so local and remote diffs can
    never disagree on what "same interleaving" means."""
    rows = []
    for doc in docs:
        t = doc.get("t") or {}
        if doc.get("kind") or "dispatched" not in t:
            continue  # search-plane entries / never-dispatched events
        name = doc.get("event_class") or "event"
        if doc.get("hint"):
            name = f"{name}:{doc['hint']}"
        rows.append((t["dispatched"], f"{doc.get('entity', '')} {name}"))
    rows.sort(key=lambda r: r[0])
    return [line for _, line in rows]


def order_lines(run) -> List[str]:
    """The realized dispatch order of a recorded run — the schedule's
    IDENTITY, the thing a reproduced interleaving must match."""
    snap = run.snapshot()
    return order_lines_from_docs([entry["json"]
                                  for entry in snap["records"]])


def diff_order(a: List[str], b: List[str],
               label_a: str, label_b: str) -> str:
    """Unified diff of two dispatch orders ("" = same interleaving)."""
    return "\n".join(difflib.unified_diff(
        a, b, fromfile=f"run/{label_a}", tofile=f"run/{label_b}",
        lineterm=""))


def diff_runs(run_a, run_b) -> str:
    """Unified diff of two recorded runs' realized dispatch orders."""
    return diff_order(order_lines(run_a), order_lines(run_b),
                      run_a.run_id, run_b.run_id)
