"""Thread-safe metrics registry: counters, gauges, fixed-bucket histograms.

Stdlib only — no prometheus_client dependency (the container must not
grow one). The model is deliberately the Prometheus one so the text
exposition (:meth:`MetricsRegistry.render_prometheus`, served by
``GET /metrics`` on the REST endpoint) scrapes with any standard
collector:

* a *family* = (name, kind, label names), registered get-or-create and
  idempotent, so call sites never coordinate registration order;
* a *child* = one (label values) sample inside a family, with its own
  lock — concurrent increments from inspector/policy/search threads
  never lose updates (the GIL does not make ``+=`` atomic);
* histograms use fixed upper-bound buckets chosen at registration,
  rendered cumulatively with the conventional ``+Inf`` terminal.

Enable/disable is process-global (``configure``, read by the
``obs_enabled`` config key via the orchestrator): when disabled,
``get()`` hands back a :class:`NullRegistry` whose instruments are one
shared no-op singleton, and every recording helper in
``namazu_tpu/obs/spans.py`` bails on the first ``enabled()`` check — the
per-event critical path pays one global read, nothing else.
"""

from __future__ import annotations

import bisect
import json
import math
import re
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "NullRegistry",
    "MetricError", "DEFAULT_BUCKETS", "NOOP",
    "configure", "enabled", "get", "registry", "set_registry", "reset",
]

#: latency buckets (seconds) tuned to the delays this system injects:
#: sub-ms scheduling overheads up to the 100 ms-class fuzz intervals,
#: with a coarse tail for stragglers.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class MetricError(Exception):
    """Registration conflict or invalid metric usage."""


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _format_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class Counter:
    """Monotonically increasing sample."""

    KIND = "counter"

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise MetricError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _samples(self, name: str, labelstr: str) -> Iterable[str]:
        yield f"{name}{labelstr} {_format_value(self.value)}"

    def _jsonable(self) -> Any:
        return self.value


class Gauge:
    """Sample that can go both ways."""

    KIND = "gauge"

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    _samples = Counter._samples
    _jsonable = Counter._jsonable


class Histogram:
    """Fixed-bucket histogram; buckets are upper bounds in ascending
    order, rendered cumulatively with the ``+Inf`` terminal bucket."""

    KIND = "histogram"

    def __init__(self, buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        ups = tuple(sorted(float(b) for b in buckets))
        if not ups:
            raise MetricError("histogram needs at least one bucket")
        self._uppers = ups
        self._lock = threading.Lock()
        self._counts = [0] * (len(ups) + 1)  # +1 = the +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        idx = bisect.bisect_left(self._uppers, v)
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> Dict[str, Any]:
        """Cumulative bucket counts keyed by upper bound, plus sum/count."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cum, acc = [], 0
        for upper, c in zip(self._uppers, counts):
            acc += c
            cum.append((upper, acc))
        return {"buckets": cum, "sum": s, "count": total}

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def raw_state(self):
        """``(uppers, per-bucket raw counts incl. the +Inf overflow,
        sum, count)`` — the exact internal state, for the telemetry
        federation plane (obs/federation.py), whose merge must be
        bit-identical to this histogram's own snapshot."""
        with self._lock:
            return self._uppers, list(self._counts), self._sum, self._count

    def _samples(self, name: str, labelstr: str) -> Iterable[str]:
        snap = self.snapshot()
        base = labelstr[1:-1] if labelstr else ""  # strip { }
        for upper, cum in snap["buckets"]:
            sep = "," if base else ""
            yield (f'{name}_bucket{{{base}{sep}le="{_format_value(upper)}"}}'
                   f" {cum}")
        sep = "," if base else ""
        yield f'{name}_bucket{{{base}{sep}le="+Inf"}} {snap["count"]}'
        yield f"{name}_sum{labelstr} {_format_value(snap['sum'])}"
        yield f"{name}_count{labelstr} {snap['count']}"

    def _jsonable(self) -> Any:
        snap = self.snapshot()
        return {
            "buckets": [[_format_value(u), c] for u, c in snap["buckets"]],
            "sum": snap["sum"],
            "count": snap["count"],
        }


class _Family:
    """One named metric with a fixed label-name set; children are the
    per-label-value samples."""

    def __init__(self, cls, name: str, help: str,
                 labelnames: Tuple[str, ...], **child_kw):
        self.cls = cls
        self.name = name
        self.help = help
        self.labelnames = labelnames
        self._child_kw = child_kw
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], Any] = {}

    def labels(self, **kw):
        if set(kw) != set(self.labelnames):
            raise MetricError(
                f"{self.name}: labels {sorted(kw)} != declared "
                f"{sorted(self.labelnames)}")
        key = tuple(str(kw[n]) for n in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._children[key] = self.cls(**self._child_kw)
        return child

    # unlabeled convenience: family IS its single child
    def _default(self):
        if self.labelnames:
            raise MetricError(f"{self.name} declares labels "
                              f"{self.labelnames}; use .labels(...)")
        return self.labels()

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def set(self, value: float) -> None:
        self._default().set(value)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    @property
    def value(self) -> float:
        return self._default().value

    def _items(self) -> List[Tuple[Tuple[str, ...], Any]]:
        with self._lock:
            return sorted(self._children.items())

    def items(self) -> List[Tuple[Tuple[str, ...], Any]]:
        """Public (label values) -> child listing (sorted) — the read
        side the telemetry relay walks per push (obs/federation.py)."""
        return self._items()

    def render(self) -> Iterable[str]:
        if self.help:
            yield f"# HELP {self.name} {self.help}"
        else:
            yield f"# HELP {self.name}"
        yield f"# TYPE {self.name} {self.cls.KIND}"
        for key, child in self._items():
            if key:
                pairs = ",".join(
                    f'{n}="{_escape_label_value(v)}"'
                    for n, v in zip(self.labelnames, key))
                labelstr = "{" + pairs + "}"
            else:
                labelstr = ""
            yield from child._samples(self.name, labelstr)

    def jsonable(self) -> Dict[str, Any]:
        samples = []
        for key, child in self._items():
            samples.append({
                "labels": dict(zip(self.labelnames, key)),
                "value": child._jsonable(),
            })
        return {
            "name": self.name,
            "type": self.cls.KIND,
            "help": self.help,
            "samples": samples,
        }


class MetricsRegistry:
    """Name -> family table; all accessors are get-or-create and
    idempotent so concurrent first-use from any thread is safe."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _get(self, cls, name: str, help: str,
             labelnames: Iterable[str], **child_kw) -> _Family:
        names = tuple(labelnames)
        fam = self._families.get(name)
        if fam is None:
            # name/label validation only on the creation path: call
            # sites re-fetch families per event, and re-matching two
            # regexes per recording would tax exactly the hot path the
            # module header promises is cheap
            if not _NAME_RE.match(name):
                raise MetricError(f"bad metric name {name!r}")
            for n in names:
                if not _LABEL_RE.match(n):
                    raise MetricError(f"bad label name {n!r}")
            with self._lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = self._families[name] = _Family(
                        cls, name, help, names, **child_kw)
        if fam.cls is not cls or fam.labelnames != names:
            raise MetricError(
                f"{name} already registered as {fam.cls.KIND} with labels "
                f"{fam.labelnames}; got {cls.KIND} with {names}")
        return fam

    def counter(self, name: str, help: str = "",
                labelnames: Iterable[str] = ()) -> _Family:
        return self._get(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Iterable[str] = ()) -> _Family:
        return self._get(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Iterable[str] = (),
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> _Family:
        return self._get(Histogram, name, help, labelnames, buckets=buckets)

    # -- read side -------------------------------------------------------

    def families(self) -> List[_Family]:
        """Sorted live families (the telemetry relay's walk; children
        are fetched per family via :meth:`_Family.items`)."""
        with self._lock:
            return sorted(self._families.values(), key=lambda f: f.name)

    def sample(self, name: str, **labels):
        """The live child instrument for one (name, label values), or
        None when it does not exist (read-only: never creates)."""
        fam = self._families.get(name)
        if fam is None:
            return None
        key = tuple(str(labels[n]) for n in fam.labelnames
                    if n in labels)
        if len(key) != len(fam.labelnames):
            return None
        return fam._children.get(key)

    def value(self, name: str, **labels) -> Optional[float]:
        """Current value of one counter/gauge sample (histograms have no
        scalar value; use :meth:`sample` and its ``count``/``sum``)."""
        child = self.sample(name, **labels)
        return None if child is None else getattr(child, "value", None)

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        for fam in fams:
            lines.extend(fam.render())
        return "\n".join(lines) + ("\n" if lines else "")

    def to_jsonable(self) -> Dict[str, Any]:
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        return {"metrics": [f.jsonable() for f in fams]}

    def dump_json(self) -> str:
        return json.dumps(self.to_jsonable(), sort_keys=True)


class _Noop:
    """Shared do-nothing instrument: every method does nothing and
    ``labels`` returns the same singleton — the disabled path allocates
    nothing per call."""

    def labels(self, **kw):
        return self

    def inc(self, amount: float = 1.0) -> None:
        pass

    dec = inc
    set = inc
    observe = inc

    value = 0.0
    count = 0
    sum = 0.0


NOOP = _Noop()


class NullRegistry:
    """What ``get()`` returns while observability is disabled: every
    instrument accessor hands back the shared :data:`NOOP`."""

    def counter(self, *a, **kw) -> _Noop:
        return NOOP

    gauge = counter
    histogram = counter

    def families(self) -> list:
        return []

    def sample(self, name: str, **labels) -> None:
        return None

    def value(self, name: str, **labels) -> Optional[float]:
        return None

    def render_prometheus(self) -> str:
        return ""

    def to_jsonable(self) -> Dict[str, Any]:
        return {"metrics": []}

    def dump_json(self) -> str:
        return json.dumps(self.to_jsonable(), sort_keys=True)


_NULL = NullRegistry()
_enabled = True
_registry = MetricsRegistry()


def configure(on: bool) -> None:
    """Process-global switch (the ``obs_enabled`` config key lands
    here via the orchestrator). Disabling hides the registry from
    ``get()``; existing samples are kept, not cleared."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def get():
    """The default registry when enabled, the shared no-op otherwise —
    the one call every recording site routes through."""
    return _registry if _enabled else _NULL


def registry() -> MetricsRegistry:
    """The real default registry regardless of the enabled flag (the
    /metrics handler renders it even mid-toggle)."""
    return _registry


def set_registry(r: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-global registry (tests); returns the old one."""
    global _registry
    old, _registry = _registry, r
    return old


def reset() -> None:
    """Fresh empty default registry (tests)."""
    set_registry(MetricsRegistry())
