"""Render an analytics payload (obs/analytics.py) for humans and tools.

Three forms of one document:

* ``render_markdown`` — the ``nmz-tpu tools report`` default: a
  self-contained report with per-entity tables, sparkline-style text
  curves (coverage growth, novelty per window, fitness trend), and the
  top-N suspicious-branch table;
* ``render_ndjson`` — one JSON line per section, diffable and greppable
  (the ``GET /analytics?format=ndjson`` body);
* plain JSON is just ``json.dumps(payload)`` — no renderer needed.

Everything here is a pure function of the payload: no wall-clock reads,
no storage access — the golden-file test renders a fixed payload and
compares bytes.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Sequence

__all__ = ["sparkline", "render_markdown", "render_ndjson"]

_BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values: Sequence[float]) -> str:
    """Text sparkline of a numeric series (empty series -> "")."""
    vals = [float(v) for v in values]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _BLOCKS[0] * len(vals)
    span = hi - lo
    return "".join(
        _BLOCKS[min(len(_BLOCKS) - 1,
                    int((v - lo) / span * (len(_BLOCKS) - 1) + 0.5))]
        for v in vals)


def _num(v: Any) -> str:
    if v is None:
        return "-"
    if isinstance(v, bool):
        return "yes" if v else "no"
    if isinstance(v, float):
        return f"{v:g}"
    return str(v)


def _ci(ci) -> str:
    if not ci:
        return "-"
    return f"{_num(ci[0])} – {_num(ci[1])}"


def render_markdown(payload: Dict[str, Any]) -> str:
    """The full report as GitHub-flavored Markdown."""
    exp = payload.get("experiment", {})
    cov = payload.get("coverage", {})
    rep = payload.get("reproduction", {})
    conv = payload.get("convergence", {})
    entities = payload.get("entities", [])
    suspicious = payload.get("suspicious", [])

    lines: List[str] = []
    out = lines.append
    out("# Experiment analytics")
    out("")
    out("## Summary")
    out("")
    out("| runs | failures | failure rate | unique interleavings "
        "| coverage | search rounds |")
    out("|---:|---:|---:|---:|---:|---:|")
    out(f"| {_num(exp.get('runs'))} | {_num(exp.get('failures'))} "
        f"| {_num(rep.get('failure_rate'))} "
        f"| {_num(cov.get('unique_interleavings'))} "
        f"| {_num(cov.get('coverage'))} "
        f"| {_num(exp.get('search_rounds'))} |")
    out("")

    out("## Exploration coverage")
    out("")
    extra = ""
    if cov.get("digest_errors"):
        extra = f", {_num(cov['digest_errors'])} digest errors"
    if cov.get("runs_quarantined"):
        # crash-quarantined runs (doc/robustness.md) are excluded from
        # every statistic; say so whenever any exist
        extra += (f", {_num(cov['runs_quarantined'])} quarantined "
                  "(excluded)")
    out(f"- unique interleavings: {_num(cov.get('unique_interleavings'))} "
        f"/ {_num(cov.get('runs'))} runs "
        f"(coverage {_num(cov.get('coverage'))}, "
        f"{_num(cov.get('runs_without_trace'))} without a trace{extra})")
    out(f"- unique-digest growth: `{sparkline(cov.get('curve', []))}` "
        f"{cov.get('curve', [])}")
    out(f"- novelty per window (w={_num(cov.get('window'))}): "
        f"`{sparkline(cov.get('novelty_per_window', []))}` "
        f"{cov.get('novelty_per_window', [])}")
    out(f"- saturated: {_num(cov.get('saturated', False))}")
    if "relation_curve" in cov:
        # relation coverage (guidance plane, doc/search.md): the second
        # curve — ordering relations exercised, not whole interleavings
        out(f"- relation coverage: {_num(cov.get('relation_bits'))} "
            f"/ {_num(cov.get('relation_width'))} bits "
            f"({_num(cov.get('relation_coverage'))})")
        out(f"- relation-coverage growth: "
            f"`{sparkline(cov.get('relation_curve', []))}` "
            f"{cov.get('relation_curve', [])}")
        out(f"- relation novelty per window: "
            f"`{sparkline(cov.get('relation_novelty_per_window', []))}` "
            f"{cov.get('relation_novelty_per_window', [])}")
        out(f"- relation saturated: "
            f"{_num(cov.get('relation_saturated', False))} "
            f"(open frontier: "
            f"{_num(cov.get('relation_frontier_bits'))} one-sided "
            "relation bits)")
        if cov.get("digests_saturated_relations_growing"):
            out("- NOTE: digests have saturated while relations still "
                "grow — the schedule source is replaying known "
                "interleavings; relation-guided search still has a "
                "frontier (enable `guidance`)")
    out("")

    out("## Reproduction")
    out("")
    out(f"- failure rate: {_num(rep.get('failure_rate'))} "
        f"(Wilson 95% CI {_ci(rep.get('failure_rate_ci95'))})")
    out(f"- mean runs to reproduce: "
        f"{_num(rep.get('mean_runs_to_reproduce'))} "
        f"(CI {_ci(rep.get('runs_to_reproduce_ci95'))})")
    ttff = rep.get("time_to_first_failure_s")
    if ttff is None:
        out("- time to first failure: - (no failures recorded)")
    else:
        out(f"- time to first failure: {_num(ttff)} s "
            f"(run {_num(rep.get('first_failure_run'))})")
    out(f"- repros/hour: {_num(rep.get('repros_per_hour'))} "
        f"(total {_num(rep.get('total_time_s'))} s)")
    out("")

    out("## Per-entity events")
    out("")
    if entities:
        out("| entity | events | classes | runs |")
        out("|---|---:|---:|---:|")
        for row in entities:
            out(f"| {row['entity']} | {row['events']} "
                f"| {row['classes']} | {row['runs']} |")
    else:
        out("- no recorded traces")
    out("")

    out("## Search convergence")
    out("")
    if conv.get("search_rounds"):
        installs = ", ".join(f"{k}={v}" for k, v
                             in conv.get("installs", {}).items()) or "-"
        out(f"- rounds: {_num(conv.get('search_rounds'))}; "
            f"installs: {installs}")
        for name, b in conv.get("backends", {}).items():
            out(f"- `{name}`: best fitness {_num(b.get('best_fitness'))} "
                f"over {_num(b.get('rounds'))} rounds "
                f"({_num(b.get('generations'))} generations); "
                f"fitness `{sparkline(b.get('fitness_curve', []))}` "
                f"archive `{sparkline(b.get('archive_curve', []))}` "
                f"novelty `{sparkline(b.get('novelty_curve', []))}`; "
                f"stalled: {_num(b.get('stalled', False))}")
            if b.get("host_gap_share") is not None:
                # fused search loop (doc/performance.md): how much of
                # each generation's wall time the host-I/O lane covers —
                # the gap the device-side fusion exists to close
                out(f"  - host-gap share per generation: "
                    f"{b['host_gap_share'] * 100:.1f}% "
                    "(overlapped host I/O / evolve wall time)")
        out(f"- stalled: {_num(conv.get('stalled', False))}")
    else:
        out("- no search-plane records (run under a search policy with "
            "observability enabled, or pass --url for a live "
            "orchestrator)")
    out("")

    slo = payload.get("slo")
    if slo is not None:
        # present only when the config declared objectives
        # (obs/slo.py); omitted entirely otherwise so slo-less payloads
        # render byte-identically to pre-SLO reports
        out("## SLO compliance")
        out("")
        objectives = slo.get("objectives", [])
        if objectives:
            out("| slo | kind | metric | threshold | burn | breached "
                "| breaches |")
            out("|---|---|---|---:|---:|---|---:|")
            for row in objectives:
                out(f"| {row.get('name')} | {row.get('kind')} "
                    f"| {row.get('metric')} "
                    f"| {_num(row.get('threshold_s'))}s "
                    f"| {_num(row.get('burn'))} "
                    f"| {_num(row.get('breached', False))} "
                    f"| {_num(row.get('breaches'))} |")
        else:
            out("- no objectives declared")
        out("")

    progress = payload.get("progress")
    if progress is not None:
        # present only when the storage dir carries a calibration
        # artifact or a campaign checkpoint (obs/analytics.py progress
        # fold); omitted otherwise so pre-calibration payloads render
        # byte-identically
        out("## Calibration & progress")
        out("")
        band = progress.get("band") or []
        out(f"- repro rate: {_num(progress.get('repro_rate'))} "
            f"(CI {_ci(progress.get('rate_ci95'))}) over "
            f"{_num(progress.get('runs'))} runs")
        out(f"- band [{_num(band[0] if len(band) > 1 else None)}, "
            f"{_num(band[1] if len(band) > 1 else None)}] "
            f"({_num(progress.get('band_source'))}): "
            f"{_num(progress.get('band_verdict'))}"
            + (f" (decided by {progress['band_decided_by']})"
               if progress.get("band_decided_by") else ""))
        eta = progress.get("eta_next_repro_s")
        out(f"- repros/hour: {_num(progress.get('repros_per_hour'))}; "
            f"next repro ETA: "
            + (f"{_num(eta)} s" if eta is not None
               else "- (no pace yet)"))
        rtc = progress.get("runs_to_ci_width")
        if rtc:
            out(f"- runs to a {_num(rtc.get('width'))}-wide CI: "
                f"{_num(rtc.get('runs'))} "
                f"({_num(rtc.get('more_runs'))} more)")
        camp = progress.get("campaign")
        if camp:
            out(f"- campaign: {_num(camp.get('completed_slots'))} / "
                f"{_num(camp.get('requested_runs'))} slots; "
                f"completion ETA: {_num(camp.get('eta_completion_s'))} s")
        regime = progress.get("regime") or {}
        out(f"- regime: {_num(regime.get('verdict'))} — "
            f"{regime.get('reason', '-')}")
        calib = progress.get("calibration")
        if calib:
            knobs = ", ".join(f"{k}={_num(v)}" for k, v in
                              (calib.get("knobs") or {}).items()) or "-"
            out(f"- calibration ({_num(calib.get('status'))}): {knobs}; "
                f"rate {_num(calib.get('rate'))} "
                f"(CI {_ci(calib.get('rate_ci95'))}), "
                f"{_num(calib.get('runs_saved_pct'))}% runs saved vs "
                "fixed-N")
        out("")

    triage = payload.get("triage")
    if triage is not None:
        # present only when this process holds triage dossiers
        # (namazu_tpu/triage); omitted otherwise so dossier-less
        # payloads render byte-identically to pre-triage reports
        out("## Triage")
        out("")
        dossiers = triage.get("dossiers", [])
        if dossiers:
            out("| signature | run | minimal flips | candidates "
                "| probes sim/replay | validated |")
            out("|---|---:|---:|---:|---|---|")
            for row in dossiers:
                out(f"| `{row.get('signature')}` "
                    f"| {_num(row.get('run_index'))} "
                    f"| {_num(row.get('minimal_flips'))} "
                    f"| {_num(row.get('candidate_flips'))} "
                    f"| {_num(row.get('probes_simulated'))}/"
                    f"{_num(row.get('probes_replayed'))} "
                    f"| {_num(row.get('validated', False))} |")
        else:
            out("- no dossiers recorded")
        out("")

    out("## Suspicious branches")
    out("")
    if suspicious:
        out("| branch | divergence | failure hit-rate "
            "| success hit-rate |")
        out("|---|---:|---:|---:|")
        for row in suspicious:
            out(f"| {row['branch']} | {_num(row['divergence'])} "
                f"| {_num(row['fail_hit_rate'])} "
                f"| {_num(row['success_hit_rate'])} |")
    else:
        out("- no coverage data recorded (runs write coverage.json — "
            "see namazu_tpu/analyzer.py)")
    out("")
    return "\n".join(lines)


def render_ndjson(payload: Dict[str, Any]) -> str:
    """One JSON line per payload section (insertion order), each
    ``{"section": name, "data": ...}`` — greppable and diffable."""
    lines = [json.dumps({"section": k, "data": v}, sort_keys=True)
             for k, v in payload.items()]
    return "\n".join(lines) + ("\n" if lines else "")
