"""Experiment analytics: cross-run statistics over a HistoryStorage.

PR 1 (metrics) answers "how many / how fast right now"; PR 2 (flight
recorder) answers "what order did run X execute". This module is the
third tier — the *experiment* plane — answering the cross-run questions
neither instantaneous gauges nor per-run timelines can: is the search
exploring new interleavings or replaying old ones, is time-to-first-
reproduction shrinking, has the search plane gone dead, and which
branches diverge between successful and failed runs.

Four statistic families, one payload (``compute_payload``):

* **coverage** — distinct-interleaving coverage via the search plane's
  own ``trace_digest`` (models/failure_pool.py: hint/entity sequence,
  timing-invariant), the unique-digest growth curve, and the novelty
  rate per window of runs (the saturation signal: a window that adds
  no new digest means the schedule source is replaying itself);
* **reproduction** — failure rate with a Wilson 95% interval (run
  counts are small; a normal approximation would lie), mean runs to
  reproduce, time-to-first-failure, repros/hour;
* **convergence** — best-fitness and archive-occupancy trends from the
  flight recorder's generation records, plus stall detection: the
  search is stalled when fitness AND novelty both flatline over the
  last ``STALL_WINDOW`` rounds (either alone is normal — fitness
  plateaus while the archive diversifies, novelty pauses while fitness
  climbs);
* **fault localization** — the analyzer's success/failure divergence
  ranking (namazu_tpu/analyzer.py), the reference's "Suspicious:" list.

The same payload is served by ``GET /analytics`` on the REST endpoint
(the orchestrator process registers its storage dir via
``set_storage_dir``), rendered by ``nmz-tpu tools report``
(obs/report.py), and published as ``nmz_experiment_*`` gauges so a
scraper can chart cross-run trends live. The live stall detector
(``note_search_round``, fed by ``obs.search_round``) trips the
``nmz_search_stall`` gauge and a run-tagged warning as soon as a search
goes dead — before the report stage. Schema and metric names:
doc/observability.md ("Experiment analytics").
"""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

from namazu_tpu.obs import spans, stats
from namazu_tpu.obs.stats import wilson_interval  # noqa: F401 (canonical
# home moved to obs/stats.py; re-exported here for compatibility)
from namazu_tpu.utils.log import get_logger

log = get_logger("obs.analytics")

__all__ = [
    "DEFAULT_TOP", "DEFAULT_WINDOW", "STALL_WINDOW", "STALL_REL_EPS",
    "RELATION_H", "RELATION_WIDTH", "RELATION_WINDOW",
    "wilson_interval", "detect_stall", "trace_digest_of",
    "relation_bits_of",
    "coverage_stats", "reproduction_stats", "entity_stats",
    "convergence_stats", "suspicious_branches", "compute_payload",
    "progress_stats", "progress_payload",
    "payload", "set_storage_dir", "storage_dir",
    "set_knowledge_address", "knowledge_address",
    "StallDetector", "note_search_round", "reset_stall_detector",
]

#: suspicious-branch rows kept in the payload
DEFAULT_TOP = 20
#: runs per novelty window (the saturation curve's resolution)
DEFAULT_WINDOW = 8
#: search rounds both fitness and novelty must flatline over to stall
STALL_WINDOW = 8
#: relative fitness improvement below which a window counts as flat
STALL_REL_EPS = 1e-3
#: per-entity table rows kept before folding into "_other"
MAX_ENTITY_ROWS = 16

#: the analytics plane's relation-signature space (guidance plane,
#: doc/search.md): a FIXED measurement space — hint buckets, bitmap
#: width, pair window — independent of any one policy's configuration,
#: so relation-coverage curves compare across campaigns. The search
#: plane's live CoverageMap uses the policy's own H instead (actionable
#: bias needs the genome's bucket space); both run the same derivation.
RELATION_H = 256
RELATION_WIDTH = 4096
RELATION_WINDOW = 16


# -- building blocks -------------------------------------------------------

def detect_stall(fitness: List[float],
                 novelty: Optional[List[float]] = None,
                 window: int = STALL_WINDOW,
                 rel_eps: float = STALL_REL_EPS) -> bool:
    """True when the last ``window`` search rounds improved neither best
    fitness (relative improvement <= ``rel_eps``) nor novelty (the
    distinct-failure count is unchanged). ``novelty=None`` (no novelty
    series recorded) degrades to fitness-only detection."""
    if len(fitness) < window:
        return False
    recent = fitness[-window:]
    scale = max(1.0, abs(recent[0]))
    fit_flat = (max(recent) - recent[0]) <= rel_eps * scale
    if not fit_flat:
        return False
    if novelty is None or len(novelty) < window:
        return True
    return novelty[-1] <= novelty[-window]


def trace_digest_of(trace) -> str:
    """Content digest of one stored trace — the SAME digest the search
    plane dedupes failure signatures by (models/failure_pool.py), so
    "unique interleavings" here and ``failure_distinct`` in the archive
    gauges count in one currency. Imported lazily: the digest needs the
    numpy featurizer, and the analytics module itself must stay
    importable from stdlib-only control-plane processes."""
    from namazu_tpu.models.failure_pool import trace_digest
    from namazu_tpu.ops import trace_encoding as te

    return trace_digest(te.encode_trace(trace))


def relation_bits_of(trace) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """One stored run's relation-coverage signature in the analytics
    measurement space (guidance plane): ``(covered bits, reverse
    bits)`` — the reverse bits are where each exercised relation's
    FLIP would land, so the campaign-level difference reverse - covered
    measures the open ordering frontier. Lazy import for the same
    stdlib-importability reason as the digest."""
    from namazu_tpu.guidance import (
        bucket_sequence_from_trace,
        reverse_signature_bits,
        signature_bits,
    )

    seq = bucket_sequence_from_trace(trace, RELATION_H)
    fwd = signature_bits(seq, width=RELATION_WIDTH,
                         window=RELATION_WINDOW)
    rev = reverse_signature_bits(seq, width=RELATION_WIDTH,
                                 window=RELATION_WINDOW)
    return (tuple(int(b) for b in fwd), tuple(int(b) for b in rev))


# -- per-storage statistics ------------------------------------------------

#: digest memo keyed by (storage dir, run index): a completed run's
#: trace is immutable, so its digest never changes — without this every
#: /analytics scrape re-runs the numpy featurizer + sha256 over EVERY
#: stored run, a per-scrape cost that grows linearly with the experiment
_digest_cache: Dict[Tuple[str, int], str] = {}
_digest_cache_lock = threading.Lock()
_DIGEST_CACHE_MAX = 65536


def _run_digest(storage, i: int, trace) -> str:
    key_dir = getattr(storage, "dir", None)
    if key_dir is None:  # storage without a stable identity: no memo
        return trace_digest_of(trace)
    key = (key_dir, i)
    with _digest_cache_lock:
        hit = _digest_cache.get(key)
    if hit is not None:
        return hit
    digest = trace_digest_of(trace)
    with _digest_cache_lock:
        if len(_digest_cache) >= _DIGEST_CACHE_MAX:
            _digest_cache.clear()
        _digest_cache[key] = digest
    return digest


#: relation-signature memo, same rationale as the digest memo (a
#: completed run's trace is immutable); value = (covered, reverse).
#: Its OWN, much smaller cap: one entry is two bit tuples (up to a few
#: thousand ints — ~100x a digest string), so the digest cache's 65536
#: ceiling would let a long-lived /analytics server grow unbounded in
#: practice before ever clearing
_relation_cache: Dict[Tuple[str, int],
                      Tuple[Tuple[int, ...], Tuple[int, ...]]] = {}
_RELATION_CACHE_MAX = 4096


def _run_relation_bits(storage, i: int, trace
                       ) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    key_dir = getattr(storage, "dir", None)
    if key_dir is None:
        return relation_bits_of(trace)
    key = (key_dir, i)
    with _digest_cache_lock:
        hit = _relation_cache.get(key)
    if hit is not None:
        return hit
    bits = relation_bits_of(trace)
    with _digest_cache_lock:
        if len(_relation_cache) >= _RELATION_CACHE_MAX:
            _relation_cache.clear()
        _relation_cache[key] = bits
    return bits


def _quarantined_count(storage) -> int:
    """How many of the storage's allocated run dirs are crash-
    quarantined (0 for backends without quarantine support)."""
    try:
        return len(getattr(storage, "quarantined_runs")())
    except Exception:
        return 0


def coverage_stats(storage, window: int = DEFAULT_WINDOW) -> Dict[str, Any]:
    """Distinct-interleaving coverage of a storage's recorded runs —
    two curves in one section: the classic unique-``trace_digest``
    growth curve (whole interleavings) and the relation-coverage curve
    (guidance plane: which ORDERING RELATIONS the runs exercised,
    counted in the fixed analytics measurement space). The regime the
    guidance plane exists for is digests saturating while relations
    still grow: the schedule source keeps producing "new" runs whose
    orderings are all old news — flagged explicitly."""
    n = storage.nr_stored_histories()
    digests: List[str] = []
    run_bits: List[Tuple[int, ...]] = []
    missing = 0
    # counted over ALL allocated run dirs (a quarantined run past the
    # last completed one is outside nr_stored_histories' range)
    quarantined = _quarantined_count(storage)
    digest_errors = 0
    is_quarantined = getattr(storage, "is_quarantined", None)
    for i in range(n):
        if is_quarantined is not None and is_quarantined(i):
            # crash-quarantined run (storage INCOMPLETE marker): its
            # trace exists but is untrustworthy — excluded from
            # coverage (doc/robustness.md)
            continue
        try:
            trace = storage.get_stored_history(i)
        except Exception:
            missing += 1  # crashed run: no trace.json on disk
            continue
        try:
            # both derivations BEFORE either append: a failure in the
            # second must exclude the run from every count, not leave
            # it half-counted with the two curves desynced
            digest = _run_digest(storage, i, trace)
            bits = _run_relation_bits(storage, i, trace)
        except Exception:
            # an environment problem (featurizer import, numpy), NOT
            # empty data — report it as its own bucket so a broken
            # install cannot masquerade as "N runs without a trace"
            if not digest_errors:
                log.exception("trace digest failed for run %d; coverage "
                              "will undercount", i)
            digest_errors += 1
            continue
        digests.append(digest)
        run_bits.append(bits)
    seen: set = set()
    curve: List[int] = []
    for d in digests:
        seen.add(d)
        curve.append(len(seen))
    novelty: List[float] = []
    prior: set = set()
    for start in range(0, len(digests), window):
        chunk = digests[start:start + window]
        fresh = len({d for d in chunk} - prior)
        novelty.append(round(fresh / len(chunk), 3))
        prior.update(chunk)
    unique = len(seen)
    # relation-coverage curve: cumulative covered bits, and per window
    # the fraction of runs that FIRST-COVERED at least one relation —
    # the guidance plane's novelty rule (coverage.py), mirrored here.
    # Reverse bits accumulate in parallel: their uncovered remainder is
    # the campaign's open ordering frontier (relations exercised in one
    # direction whose flip was never seen).
    rel_seen: set = set()
    rev_seen: set = set()
    rel_curve: List[int] = []
    rel_added: List[bool] = []
    for fwd, rev in run_bits:
        rel_added.append(any(b not in rel_seen for b in fwd))
        rel_seen.update(fwd)
        rev_seen.update(rev)
        rel_curve.append(len(rel_seen))
    rel_novelty: List[float] = []
    for start in range(0, len(rel_added), window):
        chunk = rel_added[start:start + window]
        rel_novelty.append(round(sum(chunk) / len(chunk), 3))
    rel_saturated = len(rel_novelty) >= 2 and rel_novelty[-1] == 0.0
    frontier = len(rev_seen - rel_seen)
    saturated = len(novelty) >= 2 and novelty[-1] == 0.0
    return {
        "runs": len(digests),
        "runs_without_trace": missing,
        "runs_quarantined": quarantined,
        "digest_errors": digest_errors,
        "unique_interleavings": unique,
        "coverage": round(unique / len(digests), 4) if digests else 0.0,
        "curve": curve,
        "window": window,
        "novelty_per_window": novelty,
        "saturated": saturated,
        "relation_width": RELATION_WIDTH,
        "relation_bits": len(rel_seen),
        "relation_coverage": round(len(rel_seen) / RELATION_WIDTH, 4),
        "relation_curve": rel_curve,
        "relation_novelty_per_window": rel_novelty,
        "relation_saturated": rel_saturated,
        # relations exercised in one direction whose flip was never
        # observed — where relation coverage can still grow even after
        # every digest window reads stale
        "relation_frontier_bits": frontier,
        # the motivating regime (doc/search.md): digest novelty reads
        # saturated — the schedule source is replaying known
        # interleavings — while the ordering frontier is still open
        # (either relations grew in the last window, or one-sided
        # relations remain to flip). Exactly when digest-guided search
        # has nothing left to chase and relation-guided search does.
        "digests_saturated_relations_growing": (
            saturated and (not rel_saturated or frontier > 0)),
    }


def reproduction_stats(storage) -> Dict[str, Any]:
    """Failure (= bug reproduction) statistics across a storage's runs."""
    n = storage.nr_stored_histories()
    outcomes: List[Tuple[bool, float]] = []
    quarantined = _quarantined_count(storage)
    is_quarantined = getattr(storage, "is_quarantined", None)
    # virtual-clock runs (doc/performance.md "Virtual clock") record
    # their VIRTUAL elapsed as metadata beside the wall required_time;
    # a wall run's virtual time IS its wall time, so the virtual total
    # stays well-defined over mixed storages
    total_virtual = 0.0
    vclock_runs = 0
    for i in range(n):
        if is_quarantined is not None and is_quarantined(i):
            continue
        try:
            t = storage.get_required_time(i)
            outcomes.append((storage.is_successful(i), t))
        except Exception:
            continue
        try:
            meta = storage.get_metadata(i)
        except Exception:
            meta = {}
        virtual = meta.get("virtual_time_s")
        if virtual is not None:
            total_virtual += float(virtual)
            vclock_runs += 1
        else:
            total_virtual += t
    runs = len(outcomes)
    failures = sum(1 for ok, _ in outcomes if not ok)
    total_time = sum(t for _, t in outcomes)
    lo, hi = wilson_interval(failures, runs)
    ttff = None
    first_failure = None
    acc = 0.0
    for i, (ok, t) in enumerate(outcomes):
        acc += t
        if not ok:
            ttff, first_failure = round(acc, 3), i
            break
    rate = failures / runs if runs else 0.0
    out: Dict[str, Any] = {
        "runs": runs,
        "runs_quarantined": quarantined,
        "failures": failures,
        "failure_rate": round(rate, 4),
        "failure_rate_ci95": [round(lo, 4), round(hi, 4)],
        "mean_runs_to_reproduce": (round(runs / failures, 2)
                                   if failures else None),
        # inverse of the rate interval: the pessimistic end of "how many
        # more runs until the next repro" is what an experiment budget
        # is planned against
        "runs_to_reproduce_ci95": ([round(1.0 / hi, 2), round(1.0 / lo, 2)]
                                   if failures and lo > 0 else None),
        "time_to_first_failure_s": ttff,
        "first_failure_run": first_failure,
        "total_time_s": round(total_time, 3),
        "repros_per_hour": stats.repros_per_hour(failures,
                                                 total_time) or 0.0,
        # virtual-denominated twins, present only when at least one
        # run actually fast-forwarded: the wall fields above keep their
        # meaning (SPRT budgets and calibration artifacts are
        # wall-denominated), the virtual ones say how much scenario
        # time the campaign covered
        "vclock_runs": vclock_runs,
        "total_virtual_time_s": (round(total_virtual, 3)
                                 if vclock_runs else None),
        "repros_per_hour_virtual": (
            stats.repros_per_hour(failures, total_virtual)
            if vclock_runs else None),
    }
    return out


def _run_outcomes(storage) -> List[bool]:
    """The storage's completed-run outcome sequence in campaign order
    (True = failure = repro), quarantined runs excluded — what the
    progress surface replays through the band SPRT."""
    n = storage.nr_stored_histories()
    is_quarantined = getattr(storage, "is_quarantined", None)
    outcomes: List[bool] = []
    for i in range(n):
        if is_quarantined is not None and is_quarantined(i):
            continue
        try:
            outcomes.append(not storage.is_successful(i))
        except Exception:
            continue
    return outcomes


def progress_stats(storage, coverage: Optional[Dict[str, Any]] = None,
                   calibration: Optional[Dict[str, Any]] = None,
                   checkpoint: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
    """The live campaign-progress document (obs/stats.py machinery over
    one storage): measured rate + CI, repros/hour, ETA forecasts, the
    sequential band verdict, and the search-pays/random-suffices regime
    call. Pure function of its inputs — no wall-clock reads — so the
    REST ``/progress`` body, the ``/analytics`` fold, and ``tools
    report`` all agree byte-for-byte. Every field is ``None`` rather
    than NaN on a young campaign (0 or 1 completed runs, no failures
    yet): the document must always survive ``json.dumps(...,
    allow_nan=False)``."""
    repro = reproduction_stats(storage)
    outcomes = _run_outcomes(storage)
    runs = len(outcomes)
    failures = sum(outcomes)
    band = tuple(stats.DEFAULT_BAND)
    band_source = "default"
    if calibration and isinstance(calibration.get("band"), (list, tuple)) \
            and len(calibration["band"]) == 2:
        band = (float(calibration["band"][0]),
                float(calibration["band"][1]))
        band_source = "calibration"
    # cap out of reach: live progress reads "undecided" until the SPRT
    # genuinely concludes (the budget-capped point-estimate fallback is
    # the calibration harness's semantics, not a scrape's)
    sprt = stats.BandSPRT.replay(outcomes, lo=band[0], hi=band[1],
                                 max_runs=runs + 1)
    rate = failures / runs if runs else None
    rph = repro.get("repros_per_hour") or None
    runs_to_ci = stats.runs_for_ci_width(rate if failures else None)
    doc: Dict[str, Any] = {
        "runs": runs,
        "failures": failures,
        "runs_quarantined": repro.get("runs_quarantined", 0),
        "repro_rate": round(rate, 4) if rate is not None else None,
        "rate_ci95": repro.get("failure_rate_ci95") if runs else None,
        "repros_per_hour": rph,
        "total_time_s": repro.get("total_time_s", 0.0),
        # virtual-clock twins (None on pure wall campaigns): reported
        # as SEPARATE fields so every wall-denominated consumer (SPRT
        # budgets, calibration A/Bs) keeps reading the fields above
        "repros_per_hour_virtual": repro.get("repros_per_hour_virtual"),
        "total_virtual_time_s": repro.get("total_virtual_time_s"),
        "eta_next_repro_virtual_s": stats.eta_next_repro_s(
            repro.get("repros_per_hour_virtual")),
        # forecasters (obs/stats.py): None = nothing to extrapolate yet
        "eta_next_repro_s": stats.eta_next_repro_s(rph),
        "eta_10_repros_s": stats.eta_to_n_repros_s(rph, failures, 10),
        "runs_to_ci_width": ({
            "width": stats.DEFAULT_CI_WIDTH,
            "runs": runs_to_ci,
            "more_runs": max(0, runs_to_ci - runs),
        } if runs_to_ci is not None else None),
        # the sequential band verdict, replayed deterministically over
        # the outcome sequence (max_runs = what actually ran, so a live
        # campaign reads "undecided" until the SPRT truly concludes)
        "band": [band[0], band[1]],
        "band_source": band_source,
        "band_verdict": sprt.verdict or "undecided",
        "band_decided_by": sprt.decided_by,
        "regime": stats.regime_verdict(
            rate, runs, band=band,
            digests_saturated_relations_growing=bool(
                (coverage or {}).get(
                    "digests_saturated_relations_growing"))),
    }
    if calibration is not None:
        doc["calibration"] = {
            "schema": calibration.get("schema"),
            "status": calibration.get("status"),
            "knobs": calibration.get("knobs"),
            "rate": calibration.get("rate"),
            "rate_ci95": calibration.get("rate_ci95"),
            "runs_saved_pct": calibration.get("runs_saved_pct"),
        }
    if checkpoint is not None:
        requested = int(checkpoint.get("requested_runs", 0) or 0)
        slots = [s for s in checkpoint.get("slots", [])
                 if not s.get("in_progress")]
        remaining = max(0, requested - len(slots))
        mean_run_s = (repro["total_time_s"] / runs) if runs else None
        doc["campaign"] = {
            "requested_runs": requested,
            "completed_slots": len(slots),
            "stopped_reason": checkpoint.get("stopped_reason"),
            "eta_completion_s": (round(remaining * mean_run_s, 1)
                                 if mean_run_s is not None else None),
        }
    return doc


def _progress_inputs(dir_path: Optional[str]
                     ) -> Tuple[Optional[Dict[str, Any]],
                                Optional[Dict[str, Any]]]:
    """Best-effort read of a storage dir's calibration artifact
    (calibration.json, namazu_tpu/calibrate) and campaign checkpoint
    (campaign.json) — (None, None) when absent or unreadable, so a torn
    file degrades the fold instead of failing the payload."""
    calib = ckpt = None
    if dir_path:
        for name, slot in (("calibration.json", "calib"),
                           ("campaign.json", "ckpt")):
            path = os.path.join(dir_path, name)
            try:
                with open(path) as f:
                    doc = json.load(f)
                if isinstance(doc, dict):
                    if slot == "calib":
                        calib = doc
                    else:
                        ckpt = doc
            except (OSError, ValueError):
                continue
    return calib, ckpt


def entity_stats(storage,
                 max_rows: int = MAX_ENTITY_ROWS) -> List[Dict[str, Any]]:
    """Per-entity event totals across all recorded traces, busiest
    first; entities past ``max_rows`` fold into one ``_other`` row (same
    cardinality stance as the metric plane's entity-label cap)."""
    counts: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
    for i in range(storage.nr_stored_histories()):
        try:
            trace = storage.get_stored_history(i)
        except Exception:
            continue
        seen_here: set = set()
        for a in trace:
            row = counts.get(a.entity_id)
            if row is None:
                row = counts[a.entity_id] = {
                    "entity": a.entity_id, "events": 0,
                    "classes": set(), "runs": 0,
                }
            row["events"] += 1
            row["classes"].add(a.event_class or a.class_name())
            if a.entity_id not in seen_here:
                seen_here.add(a.entity_id)
                row["runs"] += 1
    rows = sorted(counts.values(),
                  key=lambda r: (-r["events"], r["entity"]))
    out = [{"entity": r["entity"], "events": r["events"],
            "classes": len(r["classes"]), "runs": r["runs"]}
           for r in rows[:max_rows]]
    if len(rows) > max_rows:
        rest = rows[max_rows:]
        out.append({
            "entity": "_other",
            "events": sum(r["events"] for r in rest),
            "classes": len(set().union(*(r["classes"] for r in rest))),
            "runs": max(r["runs"] for r in rest),
        })
    return out


# -- recorder-derived statistics -------------------------------------------

def convergence_stats(recorder_runs,
                      window: int = STALL_WINDOW) -> Dict[str, Any]:
    """Search-plane convergence from the flight recorder's generation
    records (obs/recorder.py ``record_generation``/``record_install``),
    concatenated across the recorded runs in ring order."""
    fitness: Dict[str, List[float]] = {}
    archive: Dict[str, List[float]] = {}
    novelty: Dict[str, List[float]] = {}
    generations: Dict[str, int] = {}
    installs: Dict[str, int] = {}
    host_io: Dict[str, float] = {}
    host_elapsed: Dict[str, float] = {}
    rounds = 0
    for run in recorder_runs or []:
        snap = run.snapshot()
        for g in snap["generations"]:
            if g.get("kind") == "generation":
                rounds += 1
                b = g.get("backend", "?")
                fitness.setdefault(b, []).append(
                    float(g.get("best_fitness", 0.0)))
                generations[b] = max(generations.get(b, 0),
                                     int(g.get("gen_end", 0)))
                if g.get("archive_entries") is not None:
                    archive.setdefault(b, []).append(
                        float(g["archive_entries"]))
                if g.get("distinct_failures") is not None:
                    novelty.setdefault(b, []).append(
                        float(g["distinct_failures"]))
                if g.get("host_io_s") is not None:
                    # fused-loop rounds: host-I/O lane wall time vs the
                    # round's whole evolve span -> per-generation
                    # host-gap share (doc/performance.md)
                    host_io[b] = host_io.get(b, 0.0) + float(g["host_io_s"])
                    host_elapsed[b] = host_elapsed.get(b, 0.0) + max(
                        0.0, float(g.get("t_end", 0.0))
                        - float(g.get("t_start", 0.0)))
            elif g.get("kind") == "install":
                src = g.get("source", "?")
                installs[src] = installs.get(src, 0) + 1
    backends: Dict[str, Any] = {}
    for b in sorted(fitness):
        fit = fitness[b]
        backends[b] = {
            "rounds": len(fit),
            "generations": generations.get(b, 0),
            "best_fitness": round(max(fit), 6),
            "fitness_curve": [round(v, 6) for v in fit[-64:]],
            "archive_curve": [int(v) for v in archive.get(b, [])[-64:]],
            "novelty_curve": [int(v) for v in novelty.get(b, [])[-64:]],
            "stalled": detect_stall(fit, novelty.get(b), window=window),
        }
        if b in host_io and host_elapsed.get(b, 0.0) > 0:
            backends[b]["host_gap_share"] = round(
                min(1.0, host_io[b] / host_elapsed[b]), 4)
    return {
        "search_rounds": rounds,
        "installs": dict(sorted(installs.items())),
        "backends": backends,
        "stalled": any(v["stalled"] for v in backends.values()),
    }


def suspicious_branches(storage, top: int = DEFAULT_TOP
                        ) -> List[Dict[str, Any]]:
    """The analyzer's divergence ranking as payload rows."""
    from namazu_tpu.analyzer import analyze_storage

    return [
        {"branch": b, "divergence": round(div, 4),
         "fail_hit_rate": round(fr, 4), "success_hit_rate": round(sr, 4)}
        for b, div, fr, sr in analyze_storage(storage, top=top)
    ]


# -- the payload -----------------------------------------------------------

def compute_payload(storage=None, recorder_runs=None,
                    top: int = DEFAULT_TOP, window: int = DEFAULT_WINDOW,
                    publish: bool = True) -> Dict[str, Any]:
    """The full analytics document: deterministic for a given storage +
    recorder state (no wall-clock stamps — two computations over the
    same inputs compare equal, which the golden-file test and the
    REST-vs-CLI parity check both lean on)."""
    if storage is not None:
        coverage = coverage_stats(storage, window=window)
        repro = reproduction_stats(storage)
        entities = entity_stats(storage)
        suspicious = suspicious_branches(storage, top=top)
    else:
        coverage = {"runs": 0, "runs_without_trace": 0,
                    "digest_errors": 0,
                    "unique_interleavings": 0, "coverage": 0.0,
                    "curve": [], "window": window,
                    "novelty_per_window": [], "saturated": False,
                    "relation_width": RELATION_WIDTH,
                    "relation_bits": 0, "relation_coverage": 0.0,
                    "relation_curve": [],
                    "relation_novelty_per_window": [],
                    "relation_saturated": False,
                    "relation_frontier_bits": 0,
                    "digests_saturated_relations_growing": False}
        repro = reproduction_stats(_EmptyStorage())
        entities = []
        suspicious = []
    convergence = convergence_stats(recorder_runs, window=STALL_WINDOW)
    doc = {
        "schema": "nmz-analytics-v1",
        "experiment": {
            "runs": repro["runs"],
            "failures": repro["failures"],
            "entities": len(entities),
            "search_rounds": convergence["search_rounds"],
        },
        "coverage": coverage,
        "reproduction": repro,
        "entities": entities,
        "convergence": convergence,
        "suspicious": suspicious,
    }
    # progress fold (obs/stats.py): the sequential-statistics surface,
    # folded in only when the storage dir carries a calibration artifact
    # or a campaign checkpoint — file-driven so the CLI report and the
    # REST route agree byte-for-byte (parity test), and golden storages
    # (neither file) render unchanged
    progress = None
    st_dir = getattr(storage, "dir", None)
    if st_dir:
        calib, ckpt = _progress_inputs(st_dir)
        if calib is not None or ckpt is not None:
            progress = progress_stats(storage, coverage=coverage,
                                      calibration=calib, checkpoint=ckpt)
            doc["progress"] = progress
    if publish:
        # the relation-coverage gauge's storage-derived face; the live
        # per-campaign face is published by the ingest path with the
        # knowledge scenario label (models/ingest.py)
        spans.relation_coverage(
            "storage", coverage.get("relation_bits", 0),
            coverage.get("relation_width", RELATION_WIDTH))
        spans.experiment_stats(
            runs=repro["runs"],
            failures=repro["failures"],
            failure_rate=repro["failure_rate"],
            unique_interleavings=coverage["unique_interleavings"],
            coverage=coverage["coverage"],
            novelty_last_window=(coverage["novelty_per_window"][-1]
                                 if coverage["novelty_per_window"]
                                 else None),
            time_to_first_failure_s=repro["time_to_first_failure_s"],
            mean_runs_to_reproduce=repro["mean_runs_to_reproduce"],
        )
        if progress is not None:
            spans.campaign_progress(
                rate=progress["repro_rate"],
                ci=progress["rate_ci95"],
                repros_per_hour=progress["repros_per_hour"],
                eta_next_repro_s=progress["eta_next_repro_s"],
                runs_to_ci=(progress["runs_to_ci_width"] or {}).get(
                    "more_runs"),
                in_band=(1 if progress["band_verdict"] == "in_band"
                         else 0 if progress["band_verdict"] in
                         ("below", "above") else None),
            )
    return doc


class _EmptyStorage:
    """Zero-run stand-in so the no-storage payload shares one code path."""

    def nr_stored_histories(self) -> int:
        return 0


# -- process-global wiring (the REST /analytics source) --------------------

_storage_dir: Optional[str] = None


def set_storage_dir(dir_path: Optional[str]) -> None:
    """Register the experiment storage the live ``/analytics`` route
    aggregates over (``nmz-tpu run`` registers its storage dir; embedded
    orchestrators and tests may register any initialized storage)."""
    global _storage_dir
    _storage_dir = dir_path or None


def storage_dir() -> Optional[str]:
    return _storage_dir


_knowledge_addr: Optional[str] = None


def set_knowledge_address(addr: Optional[str]) -> None:
    """Register the knowledge-service address whose pool/tenant stats
    the live payload folds in (``nmz-tpu run --knowledge`` registers
    it; None unregisters). Purely additive: no address, no section."""
    global _knowledge_addr
    _knowledge_addr = addr or None


def knowledge_address() -> Optional[str]:
    return _knowledge_addr


def _knowledge_section() -> Optional[Dict[str, Any]]:
    """Pool/tenant stats from the registered knowledge service — the
    fleet-level counterpart of the per-storage sections. Best-effort
    like the storage join: an outage yields ``available: false``, never
    a failed payload (a scrape must not 500 on a dead sidecar)."""
    addr = _knowledge_addr
    if not addr:
        return None
    from namazu_tpu.knowledge import shared_client

    stats = shared_client(addr, tenant="analytics").stats()
    if stats is None:
        return {"address": addr, "available": False}
    return {
        "address": addr,
        "available": True,
        "pool_size": stats.get("pool_size", 0),
        "tenant_count": stats.get("tenant_count", 0),
        "scenario_count": stats.get("scenario_count", 0),
        "pushes": stats.get("pushes", 0),
        "pulls": stats.get("pulls", 0),
        "dedupe_hits": stats.get("dedupe_hits", 0),
        "surrogate": stats.get("surrogate", {}),
    }


def payload(top: int = DEFAULT_TOP,
            window: int = DEFAULT_WINDOW) -> Dict[str, Any]:
    """The live analytics document: the registered storage (when one is
    registered and loadable) joined with this process's flight-recorder
    runs. Storage trouble degrades to a recorder-only payload rather
    than failing the route — a mid-experiment scrape must not 500
    because a run dir is being written."""
    st = None
    d = _storage_dir
    if d:
        try:
            from namazu_tpu.storage import load_storage

            st = load_storage(d)
        except Exception:
            log.warning("analytics storage %s unreadable; serving "
                        "recorder-only payload", d, exc_info=True)
    from namazu_tpu.obs import recorder as _recorder

    try:
        doc = compute_payload(storage=st,
                              recorder_runs=_recorder.recorder().runs(),
                              top=top, window=window)
    finally:
        if st is not None:
            st.close()
    know = _knowledge_section()
    if know is not None:
        doc["knowledge"] = know
    # SLO compliance (obs/slo.py): folded in only when objectives were
    # DECLARED in config — like the knowledge section, purely additive,
    # so the compute_payload parity (REST vs CLI on an slo-less fleet)
    # is untouched
    try:
        from namazu_tpu.obs import federation

        slo_doc = federation.slo_summary()
        if slo_doc is not None:
            doc["slo"] = slo_doc
    except Exception:
        log.warning("slo summary failed; payload served without it",
                    exc_info=True)
    # causality fold (obs/causality.py): per-run critical-path latency
    # attribution over this process's recorded runs — additive like the
    # knowledge/slo sections (no recorded runs, no section), so the
    # compute_payload parity stays untouched
    try:
        runs = _recorder.recorder().runs()
        if runs:
            from namazu_tpu.obs import causality

            rows = []
            for run in runs[-4:]:  # newest runs; a bounded fold
                records, gens, run_id = causality.docs_of_run(run)
                if not records:
                    continue
                graph = causality.build_graph(records, gens, run_id)
                row = causality.critical_path(records, run_id)
                row["acyclic"] = graph.is_acyclic()
                row["stamp_inversions"] = len(graph.stamp_inversions())
                rows.append(row)
            if rows:
                doc["causality"] = {"runs": rows}
    except Exception:
        log.warning("causality fold failed; payload served without it",
                    exc_info=True)
    # triage fold (namazu_tpu/triage): per-signature dossier summaries —
    # additive like the knowledge/slo/causality sections (no dossiers,
    # no section), preserving the compute_payload parity
    try:
        from namazu_tpu.triage import store as _triage_store

        rows = _triage_store.summaries()
        if rows:
            doc["triage"] = {"dossiers": rows}
    except Exception:
        log.warning("triage fold failed; payload served without it",
                    exc_info=True)
    return doc


def progress_payload() -> Dict[str, Any]:
    """The live ``GET /progress`` body: progress_stats over the
    registered storage, always served (default band, all-None
    forecasts) even before the first run lands — a young campaign
    scrape returns zeros, never a 404 or NaN."""
    st = None
    d = _storage_dir
    if d:
        try:
            from namazu_tpu.storage import load_storage

            st = load_storage(d)
        except Exception:
            log.warning("progress storage %s unreadable; serving "
                        "zero-run payload", d, exc_info=True)
    calib, ckpt = _progress_inputs(d)
    try:
        doc = progress_stats(st if st is not None else _EmptyStorage(),
                             calibration=calib, checkpoint=ckpt)
    finally:
        if st is not None:
            st.close()
    doc["schema"] = "nmz-progress-v1"
    doc["storage"] = d
    return doc


# -- live stall detection --------------------------------------------------

class StallDetector:
    """Per-backend sliding window over (best_fitness, distinct_failures)
    search rounds; trips when both flatline (``detect_stall``). Fed by
    ``obs.search_round`` on every round, so a dead search surfaces as
    the ``nmz_search_stall`` gauge and one run-tagged warning while the
    experiment is still running — not in the post-hoc report."""

    def __init__(self, window: int = STALL_WINDOW,
                 rel_eps: float = STALL_REL_EPS) -> None:
        self.window = window
        self.rel_eps = rel_eps
        self._lock = threading.Lock()
        self._fitness: Dict[str, deque] = {}
        self._novelty: Dict[str, deque] = {}
        self._stalled: Dict[str, bool] = {}

    def update(self, backend: str, best_fitness: float,
               distinct_failures: float) -> Tuple[bool, bool]:
        """Feed one round; returns (stalled, changed-since-last-round)."""
        with self._lock:
            fit = self._fitness.setdefault(
                backend, deque(maxlen=self.window))
            nov = self._novelty.setdefault(
                backend, deque(maxlen=self.window))
            fit.append(float(best_fitness))
            nov.append(float(distinct_failures))
            stalled = detect_stall(list(fit), list(nov),
                                   window=self.window,
                                   rel_eps=self.rel_eps)
            changed = stalled != self._stalled.get(backend, False)
            self._stalled[backend] = stalled
            return stalled, changed


_stall_detector = StallDetector()


def reset_stall_detector(window: int = STALL_WINDOW,
                         rel_eps: float = STALL_REL_EPS) -> StallDetector:
    """Fresh detector (tests); returns it."""
    global _stall_detector
    _stall_detector = StallDetector(window, rel_eps)
    return _stall_detector


def note_search_round(backend: str, best_fitness: float,
                      distinct_failures: float) -> bool:
    """Live stall hook (called by ``obs.search_round``): updates the
    detector, mirrors the verdict into ``nmz_search_stall{backend}``,
    and logs the stall/recovery transitions (run-tagged via the log
    plane's ``[run_id]`` filter)."""
    stalled, changed = _stall_detector.update(
        backend, best_fitness, distinct_failures)
    spans.search_stall(backend, stalled)
    if changed and stalled:
        log.warning(
            "search plane stalled: backend=%s best_fitness and "
            "distinct-failure novelty both flat over the last %d rounds "
            "(best=%.6g, distinct_failures=%d) — the schedule source is "
            "replaying itself", backend, _stall_detector.window,
            best_fitness, int(distinct_failures))
    elif changed:
        log.info("search plane resumed progress (backend=%s)", backend)
    return stalled
