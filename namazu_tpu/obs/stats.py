"""Sequential binomial statistics for repro-rate campaigns.

The repo's headline metric is a reproduction PROBABILITY (PAPER.md), and
every consumer of it — the calibration harness (namazu_tpu/calibrate),
the live ``GET /progress`` surface, the A/B gates — faces the same two
questions: *how sure are we about the rate so far* and *how much longer
until we know enough*. This module is the one pure, seed-deterministic
answer shared by all of them:

* :func:`wilson_interval` — the small-n confidence interval (canonical
  home; ``obs.analytics.wilson_interval`` re-exports it);
* :class:`BandSPRT` — a sequential band test over a stream of run
  outcomes: early-accept "rate is inside [lo, hi]", early-reject "rate
  is below/above the band", with a hard run cap that falls back to the
  point estimate (``decided_by: "cap"``);
* forecasters — expected runs to a target CI width, ETA to the next
  reproduction and to N reproductions from repros/hour;
* :func:`regime_verdict` — search-pays vs random-suffices, combining
  the measured baseline rate with the coverage plane's
  ``digests_saturated_relations_growing`` flag (RESULTS.md: search pays
  ~15x where random repro is rare and loses where random trivially
  repros).

Everything here is stdlib-only and wall-clock free: two computations
over the same outcome sequence compare equal, which the calibration
journal and the /progress parity lean on. Degenerate inputs (no runs,
no failures, zero elapsed time) yield ``None``, never NaN or a
ZeroDivisionError — a young campaign's progress document must always
be JSON-serializable with ``allow_nan=False``.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "DEFAULT_BAND", "DEFAULT_ALPHA", "DEFAULT_BETA",
    "DEFAULT_CI_WIDTH",
    "wilson_interval", "BandSPRT",
    "runs_for_ci_width", "eta_next_repro_s", "eta_to_n_repros_s",
    "regime_verdict",
]

#: the target baseline-rate band (ROADMAP item 1): rare enough that
#: search pays ~15x, common enough that a bounded campaign measures it
DEFAULT_BAND: Tuple[float, float] = (0.02, 0.10)
#: SPRT error rates: P(reject band | rate at a band edge) and
#: P(accept band | rate at the indifference midpoint) targets
DEFAULT_ALPHA = 0.05
DEFAULT_BETA = 0.05
#: default CI-width target the runs-to-width forecaster answers for
DEFAULT_CI_WIDTH = 0.10


def wilson_interval(k: int, n: int, z: float = 1.96) -> Tuple[float, float]:
    """Wilson score interval for a proportion of ``k`` hits in ``n``
    trials. Correct at the tiny n this system lives at (10-run
    experiments), where the normal approximation collapses to [p, p]."""
    if n <= 0:
        return (0.0, 0.0)
    p = k / n
    denom = 1.0 + z * z / n
    center = (p + z * z / (2 * n)) / denom
    half = (z / denom) * math.sqrt(p * (1 - p) / n + z * z / (4 * n * n))
    return (max(0.0, center - half), min(1.0, center + half))


def _llr_terms(p0: float, p1: float) -> Tuple[float, float]:
    """Per-observation log-likelihood-ratio increments for H1: p=p1 vs
    H0: p=p0 — (on a failure, on a success)."""
    return (math.log(p1 / p0), math.log((1.0 - p1) / (1.0 - p0)))


class BandSPRT:
    """Sequential test of "repro rate is inside [lo, hi]" over a stream
    of per-run outcomes (``update(failed)``; a failure IS a repro).

    Two one-sided Wald SPRTs around the band's geometric midpoint
    ``mid = sqrt(lo * hi)``:

    * the LOW test distinguishes p = lo from p = mid; concluding for
      mid ("the rate clears the band floor") is half of in-band,
      concluding for lo is read as **below the band**;
    * the HIGH test distinguishes p = mid from p = hi; concluding for
      mid ("the rate stays under the band ceiling") is the other half,
      concluding for hi is **above the band**.

    Each sub-test freezes once concluded (its verdict never flips on
    later data). ``verdict`` is ``None`` while undecided, then one of
    ``"in_band"`` / ``"below"`` / ``"above"`` with ``decided_by:
    "sprt"``. The semantics are deliberately mid-seeking: a true rate
    sitting exactly on a band edge may be rejected either way — the
    calibration sweep WANTS probes pushed toward mid-band, not parked
    on an edge.

    Distinguishing a near-zero rate from the band floor (or floor from
    midpoint) is inherently sample-hungry, so a ``max_runs`` cap bounds
    every probe: at the cap the verdict falls back to classifying the
    point estimate against the band, marked ``decided_by: "cap"`` —
    honest provenance for a budget-bounded answer.
    """

    def __init__(self, lo: float = DEFAULT_BAND[0],
                 hi: float = DEFAULT_BAND[1],
                 alpha: float = DEFAULT_ALPHA, beta: float = DEFAULT_BETA,
                 max_runs: int = 40):
        if not (0.0 < lo < hi < 1.0):
            raise ValueError(f"need 0 < lo < hi < 1, got [{lo}, {hi}]")
        if not (0.0 < alpha < 1.0 and 0.0 < beta < 1.0):
            raise ValueError("alpha and beta must be in (0, 1)")
        if max_runs < 1:
            raise ValueError(f"max_runs must be >= 1, got {max_runs}")
        self.lo = lo
        self.hi = hi
        self.mid = math.sqrt(lo * hi)
        self.alpha = alpha
        self.beta = beta
        self.max_runs = max_runs
        self.accept_llr = math.log((1.0 - beta) / alpha)
        self.reject_llr = math.log(beta / (1.0 - alpha))
        self._low_fail, self._low_pass = _llr_terms(lo, self.mid)
        self._high_fail, self._high_pass = _llr_terms(self.mid, hi)
        self.llr_low = 0.0
        self.llr_high = 0.0
        #: frozen sub-verdicts: None undecided, True = the rate cleared
        #: this sub-test toward the band, False = it left the band here
        self._above_floor: Optional[bool] = None
        self._under_ceiling: Optional[bool] = None
        self.runs = 0
        self.failures = 0
        self.verdict: Optional[str] = None
        self.decided_by: Optional[str] = None

    # -- feeding ---------------------------------------------------------

    def update(self, failed: bool) -> Optional[str]:
        """Feed one run outcome (in campaign order); returns the
        verdict, still ``None`` while undecided. Outcomes past a
        decision are counted (runs/failures/rate stay truthful) but no
        longer move the frozen verdict."""
        self.runs += 1
        self.failures += int(failed)
        if self.verdict is not None:
            return self.verdict
        if self._above_floor is None:
            self.llr_low += self._low_fail if failed else self._low_pass
            if self.llr_low >= self.accept_llr:
                self._above_floor = True
            elif self.llr_low <= self.reject_llr:
                self._above_floor = False
        if self._under_ceiling is None:
            self.llr_high += self._high_fail if failed else self._high_pass
            if self.llr_high >= self.accept_llr:
                self._under_ceiling = False
            elif self.llr_high <= self.reject_llr:
                self._under_ceiling = True
        if self._above_floor is False:
            self.verdict, self.decided_by = "below", "sprt"
        elif self._under_ceiling is False:
            self.verdict, self.decided_by = "above", "sprt"
        elif self._above_floor and self._under_ceiling:
            self.verdict, self.decided_by = "in_band", "sprt"
        elif self.runs >= self.max_runs:
            rate = self.failures / self.runs
            self.verdict = ("below" if rate < self.lo
                            else "above" if rate > self.hi else "in_band")
            self.decided_by = "cap"
        return self.verdict

    # -- reading ---------------------------------------------------------

    @property
    def rate(self) -> Optional[float]:
        return self.failures / self.runs if self.runs else None

    @property
    def ci95(self) -> Optional[Tuple[float, float]]:
        if not self.runs:
            return None
        return wilson_interval(self.failures, self.runs)

    def to_jsonable(self) -> Dict[str, Any]:
        ci = self.ci95
        return {
            "band": [self.lo, self.hi],
            "runs": self.runs,
            "failures": self.failures,
            "rate": (round(self.failures / self.runs, 4)
                     if self.runs else None),
            "rate_ci95": ([round(ci[0], 4), round(ci[1], 4)]
                          if ci else None),
            "verdict": self.verdict,
            "decided_by": self.decided_by,
            "llr_low": round(self.llr_low, 4),
            "llr_high": round(self.llr_high, 4),
            "max_runs": self.max_runs,
        }

    @classmethod
    def replay(cls, outcomes: List[bool], **kwargs) -> "BandSPRT":
        """A BandSPRT fed an outcome sequence (True = repro) — how the
        progress surface re-derives the live band verdict from a
        storage's completed runs, deterministically."""
        t = cls(**kwargs)
        for failed in outcomes:
            t.update(bool(failed))
        return t


# -- forecasters -----------------------------------------------------------

def runs_for_ci_width(rate: Optional[float],
                      width: float = DEFAULT_CI_WIDTH,
                      z: float = 1.96) -> Optional[int]:
    """Expected total runs for the rate's 95% CI to shrink to
    ``width``, from the normal-width inversion n = (2z/w)^2 p(1-p).
    ``None`` when the estimate is degenerate (no rate yet, rate 0 or 1
    — Wilson still shrinks there, but a variance-based forecast has
    nothing to stand on) or the target width is not positive."""
    if rate is None or width <= 0.0:
        return None
    var = rate * (1.0 - rate)
    if var <= 0.0:
        return None
    return max(1, math.ceil((2.0 * z / width) ** 2 * var))


def repros_per_hour(failures: int, total_seconds: Optional[float]
                    ) -> Optional[float]:
    """Throughput in repros/hour over ``total_seconds`` of run time;
    ``None`` without a measured denominator. The SAME helper computes
    the wall-denominated rate and its virtual-clock twin — the two
    rates differ ONLY by which elapsed total is passed in, never by
    formula (doc/performance.md "Virtual clock")."""
    if not total_seconds or total_seconds <= 0:
        return None
    return round(failures / (total_seconds / 3600.0), 1)


def eta_next_repro_s(repros_per_hour: Optional[float]) -> Optional[float]:
    """Expected seconds to the next reproduction at the measured pace;
    ``None`` before any repro (no pace to extrapolate)."""
    if not repros_per_hour or repros_per_hour <= 0.0:
        return None
    return round(3600.0 / repros_per_hour, 1)


def eta_to_n_repros_s(repros_per_hour: Optional[float], current: int,
                      target: int) -> Optional[float]:
    """Expected seconds until the campaign holds ``target`` repros
    (0.0 when already there; ``None`` with no measured pace)."""
    if target <= current:
        return 0.0
    if not repros_per_hour or repros_per_hour <= 0.0:
        return None
    return round((target - current) * 3600.0 / repros_per_hour, 1)


# -- the regime verdict ----------------------------------------------------

#: completed runs below which no regime call is made: with fewer, the
#: Wilson interval spans most of [0, 1] and any verdict is noise
MIN_REGIME_RUNS = 8


def regime_verdict(rate: Optional[float], runs: int,
                   band: Tuple[float, float] = DEFAULT_BAND,
                   digests_saturated_relations_growing: bool = False,
                   min_runs: int = MIN_REGIME_RUNS) -> Dict[str, Any]:
    """Does search pay on this workload, or does random suffice?

    RESULTS.md's cross-scenario finding: searched schedules pay ~15x
    where the random baseline's repro rate is rare (the band) and LOSE
    where random trivially repros (the search spends its budget
    re-finding what random stumbles into). The verdict combines the
    measured baseline rate with the coverage plane's
    ``digests_saturated_relations_growing`` flag — random replaying
    known interleavings while the ordering frontier is open is the
    strongest "search still has something to chase" signal there is.
    """
    lo, hi = band
    if rate is None or runs < min_runs:
        return {
            "verdict": "insufficient_data",
            "reason": (f"{runs} completed run(s) < {min_runs}; the rate "
                       "interval is too wide to call a regime"),
        }
    if rate > hi:
        reason = (f"baseline repro rate {rate:.3f} is above the "
                  f"[{lo:g}, {hi:g}] band: random already reproduces "
                  "the bug cheaply, a searched schedule has little to "
                  "add")
        if digests_saturated_relations_growing:
            reason += (" (relation frontier is still open, but repros "
                       "are not the bottleneck)")
        return {"verdict": "random_suffices", "reason": reason}
    reason = (f"baseline repro rate {rate:.3f} is "
              + ("inside" if rate >= lo else "below")
              + f" the [{lo:g}, {hi:g}] band: repros are rare under "
              "random, the regime where searched schedules pay")
    if digests_saturated_relations_growing:
        reason += ("; digests have saturated while relations still "
                   "grow — guided search has an open frontier")
    return {"verdict": "search_pays", "reason": reason}
