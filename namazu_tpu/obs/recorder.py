"""Flight recorder: bounded per-run event-timeline capture.

PR 1's metrics registry answers "how many / how fast"; this module
answers "what order did run X actually execute, and which policy
decision caused it". Every event that crosses a choke point the metrics
plane already instruments (hub interception, orchestrator enqueue/
decide, policy release, action dispatch, REST ack) also lands one
structured :class:`EventRecord` in the current run's trace, keyed by the
event's uuid so all lifecycle stamps join on one record. The search
plane contributes its own track: one :class:`RunTrace` entry per
``search.run()`` round plus one per schedule install, and every policy
decision is tagged with the schedule-generation id active when it was
made — the causal link from "this event was delayed 80 ms" back to "by
the table evolved in generations 64..128".

Memory is bounded twice: a ring of ``max_runs`` runs (oldest evicted
whole) and ``max_records`` event records per run (later events count in
``dropped_records`` instead of allocating). Everything is thread-safe —
hub/orchestrator/policy/REST threads stamp concurrently while an
exporter snapshots.

The hot path honors ``obs_enabled = false`` exactly like the metrics
helpers: every recording function bails on the first
``metrics.enabled()`` check, and with no run begun (e.g. a bare
MockOrchestrator hub) recording is a no-op too — no run, no allocation.

Correlation key: the ``run_id``. :func:`begin_run` pushes it into
``namazu_tpu/utils/log.py`` so every log line carries the same id the
trace records and ``GET /traces/<run_id>`` serve; logs, metrics, and
traces join on one key. Exporters live in ``namazu_tpu/obs/export.py``;
the record schema is documented in doc/observability.md.
"""

from __future__ import annotations

import threading
import time
import uuid as _uuid
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from namazu_tpu.obs import metrics
from namazu_tpu.utils import log as _log

__all__ = [
    "EventRecord", "RunTrace", "FlightRecorder",
    "recorder", "set_recorder", "reset",
    "begin_run", "end_run", "current_run_id", "current_generation_id",
    "record_intercepted", "record_enqueued", "record_decided",
    "record_decision", "record_released", "record_dispatched",
    "record_acked", "record_edge", "record_generation", "record_install",
    "record_annotation",
]

#: lifecycle stamp names, in causal order (export sorts tracks by the
#: first present stamp; the acceptance invariant "monotonic per-track
#: timestamps" holds because each stage stamps with time.monotonic()).
#: ``reconciled`` exists only on edge-decided records: the moment their
#: async backhaul folded back into the orchestrator — the anchor of the
#: causality plane's ``backhaul`` latency segment (obs/causality.py)
STAGES = ("intercepted", "enqueued", "decided", "released",
          "dispatched", "acked", "reconciled")


class EventRecord:
    """One event's full lifecycle through the control plane."""

    __slots__ = ("event_id", "entity", "endpoint", "event_class", "hint",
                 "policy", "decision", "action_class", "action_kind", "t",
                 "ctx")

    def __init__(self, event_id: str, entity: str = "",
                 endpoint: str = "", event_class: str = "",
                 hint: str = "") -> None:
        self.event_id = event_id
        self.entity = entity
        self.endpoint = endpoint
        self.event_class = event_class
        self.hint = hint
        self.policy = ""
        #: what the policy chose: delay/priority, table source
        #: ("hash" | "table"), schedule generation id, fault flag, ...
        self.decision: Dict[str, Any] = {}
        self.action_class = ""
        self.action_kind = ""
        #: stage -> monotonic stamp (subset of STAGES)
        self.t: Dict[str, float] = {}
        #: the event's span context in wire form (obs/context.py):
        #: causal parent, Lamport clock at mint, origin process — None
        #: for events from pre-context clients
        self.ctx: Optional[Dict[str, Any]] = None

    def copy(self) -> "EventRecord":
        """Deep-enough copy for lock-free export: writers keep mutating
        the live ``t``/``decision`` dicts after a snapshot, and
        iterating those concurrently would race."""
        dup = EventRecord(self.event_id, self.entity, self.endpoint,
                          self.event_class, self.hint)
        dup.policy = self.policy
        dup.decision = dict(self.decision)
        dup.action_class = self.action_class
        dup.action_kind = self.action_kind
        dup.t = dict(self.t)
        dup.ctx = dict(self.ctx) if self.ctx else None
        return dup

    def first_stamp(self) -> Optional[float]:
        for name in STAGES:
            if name in self.t:
                return self.t[name]
        return None

    def to_jsonable(self, anchor: float = 0.0) -> Dict[str, Any]:
        """Record as a plain dict; timestamps become offsets (seconds,
        µs precision) from ``anchor`` so two runs' dumps diff cleanly."""
        doc = {
            "event": self.event_id,
            "entity": self.entity,
            "endpoint": self.endpoint,
            "event_class": self.event_class,
            "hint": self.hint,
            "policy": self.policy,
            "decision": dict(self.decision),
            "action_class": self.action_class,
            "action_kind": self.action_kind,
            "t": {name: round(self.t[name] - anchor, 6)
                  for name in STAGES if name in self.t},
        }
        # additive: context-less records (old clients, obs-off mints)
        # serialize exactly as before, so existing dumps stay diffable
        if self.ctx:
            doc["ctx"] = dict(self.ctx)
        return doc


class RunTrace:
    """One run's bounded record table plus the search-plane round log."""

    def __init__(self, run_id: str, max_records: int = 4096,
                 now: Optional[float] = None,
                 wall: Optional[float] = None) -> None:
        self.run_id = run_id
        self.max_records = max_records
        self.started_mono = time.monotonic() if now is None else now
        self.started_wall = time.time() if wall is None else wall
        self.ended_mono: Optional[float] = None
        #: record-creation attempts refused by the ``max_records`` cap.
        #: Counts attempts, not distinct events (a dropped event's later
        #: lifecycle stamps each count one more): > 0 means the trace is
        #: incomplete, and the magnitude tracks how much traffic arrived
        #: after the cap — without a per-uuid dropped set to maintain.
        self.dropped_records = 0
        #: search-plane entries: {"kind": "generation"|"install", ...}
        self.generations: List[Dict[str, Any]] = []
        self._records: "OrderedDict[str, EventRecord]" = OrderedDict()
        self._lock = threading.Lock()

    def record_for(self, event_id: str, create: bool = True,
                   decision: Optional[Dict[str, Any]] = None,
                   **fields: Any) -> Optional[EventRecord]:
        """The record keyed by ``event_id``, created on first use (unless
        the per-run cap is hit, which counts in ``dropped_records``).
        ``fields`` fill still-empty identity attributes; ``decision``
        entries merge into the record's decision dict — under the run
        lock, so a concurrent :meth:`snapshot` can never copy a
        half-written decision (plain ``dict.update`` outside the lock
        inserts key by key)."""
        with self._lock:
            rec = self._records.get(event_id)
            if rec is None:
                if not create:
                    return None
                if len(self._records) >= self.max_records:
                    self.dropped_records += 1
                    return None
                rec = self._records[event_id] = EventRecord(event_id)
            for name, value in fields.items():
                if value and not getattr(rec, name):
                    setattr(rec, name, value)
            if decision:
                rec.decision.update(decision)
            return rec

    def stamp(self, event_id: str, stage: str,
              now: Optional[float] = None, **fields: Any) -> None:
        rec = self.record_for(event_id, **fields)
        if rec is not None:
            rec.t[stage] = time.monotonic() if now is None else now

    def add_generation(self, entry: Dict[str, Any],
                       cap: int = 1024) -> None:
        with self._lock:
            if len(self.generations) < cap:
                self.generations.append(entry)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def snapshot(self) -> Dict[str, Any]:
        """Consistent COPY for the exporters (records in interception
        order, the generation log, the run envelope): writers keep
        mutating live records after this returns, so everything handed
        out is copied under the lock."""
        with self._lock:
            copies = [rec.copy() for rec in self._records.values()]
            generations = [dict(g) for g in self.generations]
            dropped = self.dropped_records
        records = [{"rec": rec, "json": rec.to_jsonable(self.started_mono)}
                   for rec in copies]
        return {
            "run_id": self.run_id,
            "started_wall": self.started_wall,
            "started_mono": self.started_mono,
            "ended_mono": self.ended_mono,
            "records": records,
            "generations": generations,
            "dropped_records": dropped,
        }

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            n, dropped = len(self._records), self.dropped_records
            n_gen = len(self.generations)
        return {
            "run_id": self.run_id,
            "started": self.started_wall,
            "records": n,
            "dropped_records": dropped,
            "search_rounds": n_gen,
            "ended": self.ended_mono is not None,
        }


class FlightRecorder:
    """Ring of the last ``max_runs`` :class:`RunTrace` instances plus
    the process-wide schedule-generation counter."""

    def __init__(self, max_runs: int = 8, max_records: int = 4096) -> None:
        self.max_runs = max_runs
        self.max_records = max_records
        self._lock = threading.Lock()
        self._runs: "OrderedDict[str, RunTrace]" = OrderedDict()
        self._current: Optional[RunTrace] = None
        # tenancy plane (doc/tenancy.md): namespace tag -> concurrently
        # OPEN RunTrace. Pinned runs record in parallel with (and
        # independent of) the `_current` run; signals tagged with a
        # namespace resolve here, untagged ones keep resolving to
        # `_current` — so N tenants' records never interleave
        self._pinned: Dict[str, RunTrace] = {}
        # cumulative GA generations (or MCTS simulations) evolved in this
        # process; decisions snapshot it so a replayed delay points back
        # at the search round that produced its table
        self._gen_seq = 0

    # -- run lifecycle ----------------------------------------------------

    def begin_run(self, run_id: Optional[str] = None,
                  now: Optional[float] = None,
                  wall: Optional[float] = None) -> str:
        """Open (and make current) a new run trace; evicts the oldest
        run beyond ``max_runs``. Always tags the log plane with the run
        id; allocates a trace only while observability is enabled."""
        rid = run_id or _uuid.uuid4().hex[:12]
        _log.set_run_id(rid)
        if not metrics.enabled():
            with self._lock:
                self._current = None
            return rid
        run = RunTrace(rid, self.max_records, now=now, wall=wall)
        with self._lock:
            self._runs[rid] = run
            self._runs.move_to_end(rid)
            self._evict_runs()
            self._current = run
        return rid

    def _evict_runs(self) -> None:
        """Ring eviction; caller holds the lock. Still-OPEN pinned runs
        are never evicted (a tenant's live trace must not vanish under
        it because seven siblings started later) — the ring can
        temporarily exceed ``max_runs`` by the number of live pins,
        which the lease table bounds."""
        if len(self._runs) <= self.max_runs:
            return
        protected = {run.run_id for run in self._pinned.values()}
        for rid in list(self._runs):
            if len(self._runs) <= self.max_runs:
                return
            if rid in protected or self._runs[rid] is self._current:
                continue
            del self._runs[rid]

    # -- pinned (tenancy) runs --------------------------------------------

    def begin_pinned(self, tag: str, run_id: Optional[str] = None,
                     now: Optional[float] = None,
                     wall: Optional[float] = None) -> str:
        """Open a run trace for namespace ``tag`` WITHOUT making it the
        process-current run (tenancy plane: N runs record concurrently).
        Returns the run id; with observability disabled no trace is
        allocated and namespaced recording stays a no-op."""
        rid = run_id or _uuid.uuid4().hex[:12]
        if not metrics.enabled():
            with self._lock:
                self._pinned.pop(tag, None)
            return rid
        run = RunTrace(rid, self.max_records, now=now, wall=wall)
        with self._lock:
            self._runs[rid] = run
            self._runs.move_to_end(rid)
            self._pinned[tag] = run
            self._evict_runs()
        return rid

    def end_pinned(self, tag: str, now: Optional[float] = None) -> None:
        with self._lock:
            run = self._pinned.pop(tag, None)
            if run is not None:
                run.ended_mono = time.monotonic() if now is None else now

    def pinned(self, tag: str) -> Optional[RunTrace]:
        return self._pinned.get(tag)

    def pinned_run_id(self, tag: str) -> Optional[str]:
        run = self._pinned.get(tag)
        return None if run is None else run.run_id

    def end_run(self, run_id: Optional[str] = None,
                now: Optional[float] = None) -> None:
        closed = False
        with self._lock:
            run = self._current
            if run is not None and (run_id is None or run.run_id == run_id):
                run.ended_mono = time.monotonic() if now is None else now
                self._current = None
                closed = True
        # clear the log tag only when this call actually ended the run
        # it names: with two orchestrators in one process, A's shutdown
        # must not strip the [run-id] tag off B's still-active run. The
        # log-tag comparison covers the obs-disabled case, where a run
        # id exists for correlation but no trace was allocated.
        if closed or run_id is None or _log.get_run_id() == run_id:
            _log.set_run_id(None)

    def current(self) -> Optional[RunTrace]:
        return self._current

    def run(self, run_id: str) -> Optional[RunTrace]:
        with self._lock:
            if run_id == "latest":
                return next(reversed(self._runs.values()), None)
            return self._runs.get(run_id)

    def runs(self) -> List[RunTrace]:
        with self._lock:
            return list(self._runs.values())

    def summaries(self) -> List[Dict[str, Any]]:
        return [r.summary() for r in self.runs()]

    # -- search-plane counter ---------------------------------------------

    def advance_generations(self, n: int) -> int:
        with self._lock:
            self._gen_seq += int(n)
            return self._gen_seq

    def generation_id(self) -> int:
        return self._gen_seq


_recorder = FlightRecorder()


def recorder() -> FlightRecorder:
    return _recorder


def set_recorder(r: FlightRecorder) -> FlightRecorder:
    """Swap the process-global recorder (tests); returns the old one."""
    global _recorder
    old, _recorder = _recorder, r
    return old


def reset(max_runs: int = 8, max_records: int = 4096) -> FlightRecorder:
    """Fresh empty recorder (tests)."""
    set_recorder(FlightRecorder(max_runs, max_records))
    return _recorder


def _trace_for(sig) -> Optional[RunTrace]:
    """The run trace a signal's records belong to: signals tagged with
    a tenancy namespace (``sig._ns``, set at the ingress edge and
    propagated event -> action) resolve to that namespace's PINNED run;
    untagged signals keep resolving to the process-current run. A
    namespaced signal with no pinned run records NOWHERE — leaking a
    tenant's records into the default run would break the isolation
    the tenancy plane promises (doc/tenancy.md)."""
    tag = getattr(sig, "_ns", "")
    if tag:
        return _recorder.pinned(tag)
    return _recorder.current()


def begin_run(run_id: Optional[str] = None) -> str:
    # a new run means a new search: clear the stall detector's
    # fitness/novelty windows so run A's final plateau (or its absolute
    # fitness scale) cannot read as run B's stall during B's healthy
    # early rounds — the ab harness runs many experiments per process
    from namazu_tpu.obs import analytics

    analytics.reset_stall_detector()
    return _recorder.begin_run(run_id)


def end_run(run_id: Optional[str] = None) -> None:
    _recorder.end_run(run_id)


def current_run_id() -> Optional[str]:
    """The active run's id. Falls back to the log-plane tag so the id
    survives ``obs_enabled = false`` (no trace is allocated then, but
    /healthz and log correlation still name the run — liveness is not
    telemetry)."""
    run = _recorder.current()
    if run is not None:
        return run.run_id
    rid = _log.get_run_id()
    return None if rid == "-" else rid


def current_generation_id() -> int:
    return _recorder.generation_id()


# -- recording hooks (control plane) --------------------------------------
#
# Each takes the signal at its choke point; all are no-ops when
# observability is disabled or no run is open.

def record_intercepted(event, endpoint: str,
                       now: Optional[float] = None) -> None:
    if not metrics.enabled():
        return
    run = _trace_for(event)
    if run is None:
        return
    run.stamp(event.uuid, "intercepted", now=now,
              entity=event.entity_id, endpoint=endpoint,
              event_class=event.class_name(), hint=event.replay_hint(),
              ctx=getattr(event, "_obs_ctx", None))


def record_enqueued(event, policy: str,
                    now: Optional[float] = None) -> None:
    if not metrics.enabled():
        return
    run = _trace_for(event)
    if run is None:
        return
    run.stamp(event.uuid, "enqueued", now=now,
              entity=event.entity_id, policy=policy)


def record_decided(event, policy: str,
                   now: Optional[float] = None) -> None:
    if not metrics.enabled():
        return
    run = _trace_for(event)
    if run is None:
        return
    run.stamp(event.uuid, "decided", now=now,
              entity=event.entity_id, policy=policy)


def record_decision(event, policy: str, **detail: Any) -> None:
    """Attach the policy's choice (delay/priority, table source,
    schedule-generation id, fault flag, ...) to the event's record."""
    if not metrics.enabled():
        return
    run = _trace_for(event)
    if run is None:
        return
    run.record_for(event.uuid, entity=event.entity_id, policy=policy,
                   decision=detail)


def record_released(event, policy: str,
                    now: Optional[float] = None) -> None:
    """The policy's delay queue released the event (dwell is over)."""
    if not metrics.enabled():
        return
    run = _trace_for(event)
    if run is None:
        return
    run.stamp(event.uuid, "released", now=now,
              entity=event.entity_id, policy=policy)


def record_edge(event, endpoint: str, policy: str, action,
                decision: Dict[str, Any]) -> None:
    """One edge-decided event's COMPLETE record in a single pass
    (zero-RTT backhaul reconciliation, doc/performance.md): identity,
    decision detail (``decision_source="edge"``, ``table_version``,
    delay), the synthesized action, and every lifecycle stamp from the
    edge's own clocks — one run-lock acquisition instead of the six a
    stage-by-stage replay would cost per event."""
    if not metrics.enabled():
        return
    run = _trace_for(event)
    if run is None:
        return
    detail = {name: decision[name] for name in
              ("delay", "source", "decision_source", "table_version",
               "lc", "o")
              if name in decision}
    rec = run.record_for(
        event.uuid, entity=event.entity_id, endpoint=endpoint,
        event_class=event.class_name(), hint=event.replay_hint(),
        policy=policy, decision=detail,
        action_class=action.class_name(), action_kind="edge",
        ctx=getattr(event, "_obs_ctx", None))
    if rec is None:
        return
    now = time.monotonic()
    t0 = decision.get("t_intercepted")
    t1 = decision.get("t_dispatched")
    t0 = now if t0 is None else float(t0)
    t1 = now if t1 is None else float(t1)
    # dict assignment is GIL-atomic and snapshot copies under the run
    # lock, so stamping outside record_for's lock is race-free enough
    # (the same contract stamp() relies on). ``reconciled`` = THIS
    # moment — the backhaul-lag anchor the causality plane attributes
    # the async window to.
    rec.t.update(intercepted=t0, enqueued=t0, decided=t0,
                 released=t1, dispatched=t1, reconciled=now)


def record_dispatched(action, kind: str,
                      now: Optional[float] = None) -> None:
    """The answering action left the orchestrator action loop. Keyed by
    the cause event's uuid when the action has one (so all stamps land
    on one record), else by the action's own (shell/nop injections)."""
    if not metrics.enabled():
        return
    run = _trace_for(action)
    if run is None:
        return
    key = action.event_uuid or action.uuid
    run.stamp(key, "dispatched", now=now,
              entity=action.entity_id,
              event_class=action.event_class, hint=action.event_hint,
              action_class=action.class_name(), action_kind=kind)


def record_acked(action, now: Optional[float] = None) -> None:
    """The inspector acknowledged the action over REST."""
    if not metrics.enabled():
        return
    run = _trace_for(action)
    if run is None:
        return
    run.stamp(action.event_uuid or action.uuid, "acked", now=now,
              entity=action.entity_id)


# -- recording hooks (search plane) ---------------------------------------

def record_generation(backend: str, generations: int, elapsed: float,
                      best_fitness: float,
                      now: Optional[float] = None,
                      archive_entries: Optional[int] = None,
                      failure_entries: Optional[int] = None,
                      distinct_failures: Optional[int] = None,
                      host_io_s: Optional[float] = None,
                      fit_curve: Optional[list] = None) -> None:
    """One ``search.run()`` round: advances the process generation
    counter and logs the round on the run's search track. The optional
    archive occupancies feed the experiment plane's convergence/stall
    analysis (obs/analytics.py convergence_stats) — recorded only when
    the caller supplies them, so pre-existing traces and exporters see
    the same entries as before."""
    if not metrics.enabled():
        return
    gen_end = _recorder.advance_generations(generations)
    run = _recorder.current()
    if run is None:
        return
    end = time.monotonic() if now is None else now
    entry = {
        "kind": "generation",
        "backend": backend,
        "gen_start": gen_end - generations,
        "gen_end": gen_end,
        "t_start": end - elapsed,
        "t_end": end,
        "best_fitness": best_fitness,
    }
    if archive_entries is not None:
        entry["archive_entries"] = int(archive_entries)
    if failure_entries is not None:
        entry["failure_entries"] = int(failure_entries)
    if distinct_failures is not None:
        entry["distinct_failures"] = int(distinct_failures)
    if host_io_s is not None:
        # fused-loop rounds: wall time spent in the overlapped host-I/O
        # lane — the experiment plane derives the per-generation
        # host-gap share from it (obs/analytics.py convergence_stats)
        entry["host_io_s"] = round(float(host_io_s), 6)
    if fit_curve:
        # fused-loop rounds: the PER-GENERATION global-best history the
        # host lane drained (one point per generation, not per round) —
        # intra-round convergence at a resolution the round-level
        # fitness_curve cannot see. Tail-capped like the other curves.
        entry["fit_curve"] = [round(float(v), 6) for v in fit_curve[-64:]]
    run.add_generation(entry)


def record_install(source: str, generation: Optional[int] = None,
                   now: Optional[float] = None) -> None:
    """A delay/fault table was installed on the policy hot path."""
    if not metrics.enabled():
        return
    run = _recorder.current()
    if run is None:
        return
    run.add_generation({
        "kind": "install",
        "source": source,
        "generation": (_recorder.generation_id()
                       if generation is None else generation),
        "t": time.monotonic() if now is None else now,
    })


def record_annotation(kind: str, now: Optional[float] = None,
                      **fields: Any) -> None:
    """Stamp an out-of-band annotation onto the current run's search
    track (e.g. an SLO breach transition, obs/slo.py). Annotations ride
    the same bounded ``generations`` list the exporters already carry;
    consumers dispatch on ``kind`` and ignore unknown kinds, so new
    annotation kinds never break existing traces."""
    if not metrics.enabled():
        return
    run = _recorder.current()
    if run is None:
        return
    entry = {"kind": str(kind),
             "t": time.monotonic() if now is None else now}
    entry.update(fields)
    run.add_generation(entry)
