"""Differential profiling (doc/observability.md "Profiling").

Aligns two profiles — files (nmz-profile-v1 JSON, speedscope JSON, or
collapsed folded text) or live processes (``http://`` / ``uds://`` /
``tcp://`` obs endpoints, fetched via the framed/REST ``profile`` op) —
and ranks frames by **self-time share delta**: each frame's leaf-sample
count normalized by its profile's total, B minus A. Shares (not raw
counts) are compared so a 10-second capture diffs cleanly against a
60-second one; raw counts ride along for scale.

Surfaces: ``nmz-tpu tools profdiff <a> <b>`` and the ``bench.py
--gate`` failure path, which emits this diff against the baseline's
stored profile so a gate trip ships with the hot-stack explanation.
"""

from __future__ import annotations

import json
from typing import Dict, Optional

from namazu_tpu.obs import profiling

SCHEMA = "nmz-profdiff-v1"


def load_profile(source: str) -> dict:
    """Load a ``nmz-profile-v1`` payload from a live obs endpoint url
    or a file in any of the three export formats."""
    if source.startswith(("http://", "https://", "uds://", "tcp://",
                          "shm://")):
        from namazu_tpu.obs import federation
        # fetch() appends the /profile route itself, but the natural
        # thing to paste is the route URL straight from the browser —
        # accept both
        if source.startswith(("http://", "https://")):
            base, _, query = source.partition("?")
            if base.rstrip("/").endswith("/profile"):
                source = base.rstrip("/")[:-len("/profile")]
        doc = federation.fetch(source, "profile")
        if not isinstance(doc, dict) or "stacks" not in doc:
            raise ValueError(f"{source}: no profile payload (is the "
                             "profiler enabled there?)")
        return doc
    with open(source, "r", encoding="utf-8", errors="replace") as f:
        text = f.read()
    stripped = text.lstrip()
    if stripped.startswith("{"):
        doc = json.loads(text)
        if doc.get("schema") == profiling.SCHEMA:
            return doc
        if "profiles" in doc and "shared" in doc:   # speedscope
            return profiling.payload_from_speedscope(doc)
        raise ValueError(f"{source}: unrecognized JSON profile format")
    return profiling.payload_from_collapsed(text)


def diff(a: dict, b: dict, *, min_share: float = 0.0) -> dict:
    """Frame-aligned self-time diff of two payloads: positive
    ``delta_share`` = frame got hotter in ``b``. Frames below
    ``min_share`` in both profiles are elided."""
    self_a = profiling.self_times(a)
    self_b = profiling.self_times(b)
    total_a = sum(self_a.values()) or 1
    total_b = sum(self_b.values()) or 1
    planes = frame_planes_merged(a, b)
    frames = []
    for frame in set(self_a) | set(self_b):
        ca, cb = self_a.get(frame, 0), self_b.get(frame, 0)
        sa, sb = ca / total_a, cb / total_b
        if sa < min_share and sb < min_share:
            continue
        frames.append({"frame": frame,
                       "plane": planes.get(frame, "other"),
                       "self_a": ca, "self_b": cb,
                       "share_a": sa, "share_b": sb,
                       "delta_share": sb - sa})
    frames.sort(key=lambda f: -f["delta_share"])
    return {"schema": SCHEMA,
            "a": {"job": a.get("job", ""), "samples": total_a},
            "b": {"job": b.get("job", ""), "samples": total_b},
            "frames": frames}


def frame_planes_merged(a: dict, b: dict) -> Dict[str, str]:
    planes = profiling.frame_planes(a)
    planes.update(profiling.frame_planes(b))
    return planes


def top_regression(d: dict) -> Optional[dict]:
    """The #1 frame by self-time share delta (None if nothing grew)."""
    frames = d.get("frames") or []
    if frames and frames[0]["delta_share"] > 0:
        return frames[0]
    return None


def render_text(d: dict, limit: int = 15) -> str:
    """Human table, regressions first; improvements (negative deltas)
    footnoted so the output reads top-down as "what got slower"."""
    frames = d.get("frames") or []
    lines = [f"profdiff: A={d['a']['samples']} samples "
             f"({d['a'].get('job') or '?'})  "
             f"B={d['b']['samples']} samples "
             f"({d['b'].get('job') or '?'})",
             f"{'DELTA':>8} {'A':>7} {'B':>7} {'PLANE':<8} FRAME"]
    shown = 0
    for f in frames:
        if shown >= limit:
            break
        lines.append(f"{f['delta_share']*100:+7.2f}% "
                     f"{f['share_a']*100:6.2f}% {f['share_b']*100:6.2f}% "
                     f"{f['plane']:<8} {f['frame']}")
        shown += 1
    hidden = len(frames) - shown
    if hidden > 0:
        lines.append(f"... {hidden} more frames (use --limit)")
    return "\n".join(lines) + "\n"


def render_md(d: dict, limit: int = 15) -> str:
    frames = (d.get("frames") or [])[:limit]
    lines = ["# profdiff",
             "",
             f"A: `{d['a'].get('job') or '?'}` "
             f"({d['a']['samples']} samples) → "
             f"B: `{d['b'].get('job') or '?'}` "
             f"({d['b']['samples']} samples)",
             "",
             "| Δ self | A | B | plane | frame |",
             "|---:|---:|---:|---|---|"]
    for f in frames:
        lines.append(f"| {f['delta_share']*100:+.2f}% "
                     f"| {f['share_a']*100:.2f}% "
                     f"| {f['share_b']*100:.2f}% "
                     f"| {f['plane']} | `{f['frame']}` |")
    return "\n".join(lines) + "\n"
