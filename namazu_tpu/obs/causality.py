"""Causality plane: happens-before graphs, critical-path latency
attribution, and divergence explanation over recorded runs.

Namazu's product is an event *ordering*; until this module the
observability stack could only compare orderings as opaque digests or
diff them as flat sequences (obs/export.py). Three analyses close that
gap (doc/observability.md "Causality"):

* :func:`build_graph` — the per-run **happens-before DAG**. Nodes are
  ``(event, lifecycle-stage)`` points (plus one node per schedule
  install); edges are the four relation families the system actually
  enforces:

  - ``chain``    — an event's own stage progression (intercepted ->
    ... -> acked/reconciled);
  - ``program``  — per-entity interception order (the order the testee
    emitted events);
  - ``dispatch`` — the policy-imposed release order, the total order
    Namazu exists to control (its edge list IS the flight recorder's
    release sequence);
  - ``install``  — a schedule install precedes every decision tagged
    with its generation (the search plane's causal reach into the
    event plane).

  Stage-level nodes make the graph acyclic **by construction** even
  when the policy reorders events against program order (the entire
  point of a fuzzer): a reordering shows up as ``program`` and
  ``dispatch`` edges crossing between stage columns, never as a cycle.
  A vector-clock pass assigns per-process clocks, and
  :meth:`HBGraph.stamp_inversions` flags edges whose monotonic stamps
  run *backwards* across process boundaries — the forensic check for
  clock skew or a hub that reordered what it claims it didn't.

* :func:`critical_path` — decompose each event's intercepted->acked
  span into the named segments ``queue`` (hub queue), ``decision``
  (policy), ``parking`` (the injected delay), ``dispatch`` (action
  loop), ``wire`` (dispatch -> inspector ack); edge-decided events
  contribute ``edge_parking`` and ``backhaul`` instead. The central
  segments telescope — they sum to the end-to-end span exactly — so
  per-stage p50/p99 and "which stage dominates" are queries, not bench
  runs. The same segments feed ``nmz_event_stage_seconds{stage}``
  live (obs/spans.event_stage).

* :func:`relation_flips` — given two runs (a failing and a passing
  one), the **minimal set of ordering-relation flips** between their
  dispatch orders: pairs ``(x, y)`` dispatched ``x`` before ``y`` in
  one run and ``y`` before ``x`` in the other, reduced to the pairs
  not implied by other flips (transitive reduction of the inversion
  set), ranked by positional displacement plus the analyzer's
  fault-localization scores when available. This extends the PR 2
  differ from "the sequences differ" to "these relations flipped" —
  the answer to RESULTS.md's "why does B's schedule reproduce and A's
  near-identical one doesn't".

All three work off the NDJSON record shape (``EventRecord.to_jsonable``)
so they run identically over a live :class:`RunTrace`, a
``GET /traces/<id>?format=ndjson`` body, or a dump file on disk —
``GET /causality/...`` and ``nmz-tpu tools why`` are thin wrappers.
"""

from __future__ import annotations

import json
from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Tuple

from namazu_tpu.obs import export
from namazu_tpu.obs.recorder import STAGES

__all__ = [
    "SCHEMA_GRAPH", "SCHEMA_WHY", "CENTRAL_SEGMENTS", "EDGE_SEGMENTS",
    "HBGraph", "build_graph", "docs_of_run", "split_ndjson",
    "segments_of", "observe_stage_segments", "critical_path",
    "run_payload", "relation_flips", "why_payload", "render_why_md",
]

SCHEMA_GRAPH = "nmz-causality-v1"
SCHEMA_WHY = "nmz-why-v1"

#: (segment name, from-stage, to-stage) for centrally-decided events.
#: Telescoping by construction: consecutive segments share a stamp, so
#: their sum equals the intercepted->acked span whenever all stamps are
#: present (the <=5%% attribution acceptance is an identity, not a fit).
CENTRAL_SEGMENTS = (
    ("queue", "intercepted", "enqueued"),
    ("decision", "enqueued", "decided"),
    ("parking", "decided", "released"),
    ("dispatch", "released", "dispatched"),
    ("wire", "dispatched", "acked"),
)

#: edge-decided events (``decision_source == "edge"``): the local
#: decide collapses intercepted/enqueued/decided onto one stamp and the
#: record never sees a REST ack; what matters is how long the event sat
#: in the edge's parked heap and how far the async backhaul ran behind.
EDGE_SEGMENTS = (
    ("edge_parking", "decided", "released"),
    ("backhaul", "dispatched", "reconciled"),
)

#: monotonic-stamp slack before an edge counts as inverted: same-host
#: CLOCK_MONOTONIC is shared, so anything past scheduler noise is a
#: real inversion (cross-host stamps, a reordering hub, a torn merge)
INVERSION_EPS_S = 1e-6


def _is_edge(doc: Dict[str, Any]) -> bool:
    return (doc.get("decision") or {}).get("decision_source") == "edge"


# -- input shaping ---------------------------------------------------------

def docs_of_run(run) -> Tuple[List[dict], List[dict], str]:
    """``(record_docs, generation_docs, run_id)`` of a live RunTrace."""
    snap = run.snapshot()
    return ([entry["json"] for entry in snap["records"]],
            snap["generations"], snap["run_id"])


def split_ndjson(text: str) -> Tuple[List[dict], List[dict], str]:
    """Parse an NDJSON trace dump (obs/export.to_ndjson) into record
    docs + search-plane docs; malformed lines are skipped (a torn tail
    must not kill an offline analysis)."""
    records: List[dict] = []
    gens: List[dict] = []
    run_id = ""
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if not isinstance(doc, dict):
            continue
        run_id = run_id or str(doc.get("run_id") or "")
        if doc.get("kind"):
            gens.append(doc)
        elif doc.get("event"):
            records.append(doc)
    return records, gens, run_id


# -- happens-before graph --------------------------------------------------

class HBGraph:
    """The per-run happens-before DAG (see the module header)."""

    def __init__(self, run_id: str = "") -> None:
        self.run_id = run_id
        #: node key -> {"t": stamp|None, "proc": clock domain,
        #:              "event": uuid|None, "stage": stage|None}
        self.nodes: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        #: (src key, dst key, kind)
        self.edges: List[Tuple[str, str, str]] = []
        #: event uuids in policy release order (== the dispatch-order
        #: edge chain; the acceptance join against the flight recorder)
        self.dispatch_order: List[str] = []
        #: uuids of every record that reached a released/dispatched
        #: stamp (coverage: each must appear in the graph)
        self.dispatched_events: List[str] = []

    # -- construction ------------------------------------------------------

    def _add_node(self, key: str, t: Optional[float], proc: str,
                  event: Optional[str] = None,
                  stage: Optional[str] = None) -> str:
        if key not in self.nodes:
            self.nodes[key] = {"t": t, "proc": proc,
                               "event": event, "stage": stage}
        return key

    def _add_edge(self, src: str, dst: str, kind: str) -> None:
        self.edges.append((src, dst, kind))

    # -- analysis ----------------------------------------------------------

    def topo_order(self) -> Optional[List[str]]:
        """Kahn topological order, or None when the graph has a cycle
        (which build_graph's edge families cannot produce — a None here
        means corrupted input and the payload says so)."""
        indeg = {k: 0 for k in self.nodes}
        succ: Dict[str, List[str]] = {k: [] for k in self.nodes}
        for src, dst, _ in self.edges:
            succ[src].append(dst)
            indeg[dst] += 1
        ready = [k for k in self.nodes if indeg[k] == 0]
        order: List[str] = []
        while ready:
            node = ready.pop()
            order.append(node)
            for nxt in succ[node]:
                indeg[nxt] -= 1
                if indeg[nxt] == 0:
                    ready.append(nxt)
        return order if len(order) == len(self.nodes) else None

    def is_acyclic(self) -> bool:
        return self.topo_order() is not None

    def vector_clocks(self) -> Optional[Dict[str, Dict[str, int]]]:
        """Per-node vector clocks over the graph's clock domains
        (orchestrator / each edge process / the search plane), derived
        from the DAG itself — the order witness that needs no clock
        trust. None on a cyclic (corrupt) graph."""
        order = self.topo_order()
        if order is None:
            return None
        pred: Dict[str, List[str]] = {k: [] for k in self.nodes}
        for src, dst, _ in self.edges:
            pred[dst].append(src)
        clocks: Dict[str, Dict[str, int]] = {}
        for key in order:
            vc: Dict[str, int] = {}
            for p in pred[key]:
                for proc, val in clocks[p].items():
                    if val > vc.get(proc, 0):
                        vc[proc] = val
            proc = self.nodes[key]["proc"]
            vc[proc] = vc.get(proc, 0) + 1
            clocks[key] = vc
        return clocks

    def stamp_inversions(self,
                         eps: float = INVERSION_EPS_S) -> List[dict]:
        """Edges whose monotonic stamps contradict the happens-before
        direction: the DAG says src precedes dst, the clocks say dst's
        stamp is EARLIER. On one host (shared CLOCK_MONOTONIC) this is
        the forensic smoking gun — a reordering merge point, a torn
        record, or genuinely skewed cross-host stamps."""
        out = []
        for src, dst, kind in self.edges:
            ts = self.nodes[src]["t"]
            td = self.nodes[dst]["t"]
            if ts is None or td is None:
                continue
            if ts - td > eps:
                out.append({
                    "src": src, "dst": dst, "kind": kind,
                    "skew_s": round(ts - td, 6),
                    "cross_process": (self.nodes[src]["proc"]
                                      != self.nodes[dst]["proc"]),
                })
        return out

    def edge_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for _, _, kind in self.edges:
            counts[kind] = counts.get(kind, 0) + 1
        return counts

    def to_jsonable(self, max_edges: int = 4096) -> Dict[str, Any]:
        inversions = self.stamp_inversions()
        doc: Dict[str, Any] = {
            "run_id": self.run_id,
            "nodes": len(self.nodes),
            "events": len(self.dispatched_events),
            "acyclic": self.is_acyclic(),
            "edge_counts": self.edge_counts(),
            "dispatch_order": list(self.dispatch_order),
            "inversions": inversions,
        }
        if len(self.edges) <= max_edges:
            doc["edges"] = [{"src": s, "dst": d, "kind": k}
                            for s, d, k in self.edges]
        else:
            # no silent caps: say what was dropped instead of shipping
            # a graph that reads complete but isn't
            doc["edges_truncated"] = len(self.edges)
        return doc


def _stage_proc(doc: Dict[str, Any], stage: str) -> str:
    """The clock domain a stage's stamp came from: edge-decided events
    stamp intercepted..dispatched in the edge process, everything else
    (and the reconcile itself) stamps in the orchestrator."""
    if _is_edge(doc) and stage != "reconciled":
        return f"edge:{doc.get('entity', '')}"
    return "orc"


def build_graph(record_docs: Iterable[dict],
                generation_docs: Iterable[dict] = (),
                run_id: str = "") -> HBGraph:
    """Construct the happens-before DAG from NDJSON-shaped records."""
    g = HBGraph(run_id)
    docs = [d for d in record_docs if isinstance(d.get("t"), dict)
            and d.get("event")]

    # chain edges: each event's own stage progression
    for doc in docs:
        t = doc["t"]
        uuid = doc["event"]
        prev = None
        for stage in STAGES:
            if stage not in t:
                continue
            key = g._add_node(f"{uuid}:{stage}", t[stage],
                              _stage_proc(doc, stage),
                              event=uuid, stage=stage)
            if prev is not None:
                g._add_edge(prev, key, "chain")
            prev = key

    # program edges: per-entity interception order (stable: ties keep
    # record insertion order, which IS interception order)
    by_entity: Dict[str, List[dict]] = {}
    for doc in docs:
        if "intercepted" in doc["t"]:
            by_entity.setdefault(str(doc.get("entity") or ""),
                                 []).append(doc)
    for entity, rows in by_entity.items():
        rows.sort(key=lambda d: d["t"]["intercepted"])
        for a, b in zip(rows, rows[1:]):
            g._add_edge(f"{a['event']}:intercepted",
                        f"{b['event']}:intercepted", "program")

    # dispatch edges: the policy's realized release order. ``released``
    # is the policy's own stamp; records lacking it (edge bursts stamp
    # released == dispatched, orchestrator-side actions) fall back to
    # the dispatch stamp — the same sequence export.order_lines sorts.
    released = [d for d in docs
                if "released" in d["t"] or "dispatched" in d["t"]]
    released.sort(key=lambda d: d["t"].get("released",
                                           d["t"].get("dispatched")))
    g.dispatched_events = [d["event"] for d in docs
                           if "dispatched" in d["t"]]
    g.dispatch_order = [d["event"] for d in released]

    def _rel_node(doc: dict) -> str:
        stage = "released" if "released" in doc["t"] else "dispatched"
        return f"{doc['event']}:{stage}"

    for a, b in zip(released, released[1:]):
        g._add_edge(_rel_node(a), _rel_node(b), "dispatch")

    # parent edges: explicit causal descent (obs/context.child_of —
    # an inspector emitted this event BECAUSE of the action answering
    # its parent, so the parent's dispatch precedes the child's
    # emission). A lying parent claim can surface as a cycle or a
    # stamp inversion — either IS the finding, not a crash.
    by_uuid = {d["event"]: d for d in docs}
    for doc in docs:
        parent = (doc.get("ctx") or {}).get("p")
        if not parent or parent not in by_uuid \
                or "intercepted" not in doc["t"]:
            continue
        pt = by_uuid[parent]["t"]
        for stage in ("dispatched", "released", "decided",
                      "intercepted"):
            if stage in pt:
                g._add_edge(f"{parent}:{stage}",
                            f"{doc['event']}:intercepted", "parent")
                break

    # install edges: a schedule install happens-before every decision
    # tagged with its generation id
    installs: Dict[int, str] = {}
    for i, entry in enumerate(generation_docs):
        if entry.get("kind") != "install":
            continue
        gen = entry.get("generation")
        if not isinstance(gen, (int, float)):
            continue
        key = g._add_node(f"install:{int(gen)}:{i}", entry.get("t"),
                          "search")
        installs[int(gen)] = key
    if installs:
        for doc in docs:
            gen = (doc.get("decision") or {}).get("generation")
            if isinstance(gen, (int, float)) \
                    and int(gen) in installs and "decided" in doc["t"]:
                g._add_edge(installs[int(gen)],
                            f"{doc['event']}:decided", "install")
    return g


# -- critical-path latency attribution -------------------------------------

def segments_of(doc: Dict[str, Any]) -> Dict[str, float]:
    """One record's named latency segments (missing stamps = missing
    segments, never zeros)."""
    t = doc.get("t") or {}
    segments = EDGE_SEGMENTS if _is_edge(doc) else CENTRAL_SEGMENTS
    out: Dict[str, float] = {}
    for name, since, until in segments:
        t0, t1 = t.get(since), t.get(until)
        if t0 is not None and t1 is not None:
            out[name] = max(0.0, t1 - t0)
    return out


def observe_stage_segments(sig) -> None:
    """Publish a centrally-dispatched signal's completed segments into
    ``nmz_event_stage_seconds`` from its span dict (called at the ack
    choke point, where every central stamp is in hand)."""
    from namazu_tpu.obs import metrics, spans

    if not metrics.enabled():
        return
    for name, since, until in CENTRAL_SEGMENTS:
        spans.event_stage(name, spans.span_delta(sig, since, until))


def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def critical_path(record_docs: Iterable[dict],
                  run_id: str = "") -> Dict[str, Any]:
    """Per-run latency attribution: where each event's span went, which
    stage dominates, and how much of the measured span the segments
    explain (``attribution_coverage`` ~1.0 = the decomposition is an
    identity, not an estimate)."""
    per_stage: Dict[str, List[float]] = {}
    spans_s: List[float] = []
    explained = 0.0
    span_total = 0.0
    events = 0
    in_flight = 0
    for doc in record_docs:
        t = doc.get("t") or {}
        if "intercepted" not in t:
            continue
        end = t.get("acked", t.get("dispatched"))
        if end is None:
            # still in flight (a live /analytics read mid-run): its
            # partial segments must not fold into the per-stage stats
            # while its span cannot reach span_total — the shares
            # would sum past 1 and misname the critical stage exactly
            # when an operator is watching live
            in_flight += 1
            continue
        segs = segments_of(doc)
        for name, value in segs.items():
            per_stage.setdefault(name, []).append(value)
        events += 1
        span = max(0.0, end - t["intercepted"])
        spans_s.append(span)
        span_total += span
        # backhaul runs PAST the event's own end-to-end span (it is the
        # reconcile lag, not delivery latency): exclude it from the
        # "does the decomposition sum to the span" coverage figure
        explained += sum(v for n, v in segs.items() if n != "backhaul")
    spans_s.sort()
    stages: Dict[str, Any] = {}
    for name, vals in sorted(per_stage.items()):
        vals.sort()
        total = sum(vals)
        stages[name] = {
            "count": len(vals),
            "total_s": round(total, 6),
            "mean_s": round(total / len(vals), 6),
            "p50_s": round(_quantile(vals, 0.50), 6),
            "p99_s": round(_quantile(vals, 0.99), 6),
            "share": (round(total / span_total, 4)
                      if span_total > 0 else None),
        }
    critical = max(
        (name for name in stages if name != "backhaul"),
        key=lambda name: stages[name]["total_s"], default=None)
    return {
        "run_id": run_id,
        "events": events,
        "in_flight": in_flight,
        "span_p50_s": round(_quantile(spans_s, 0.50), 6),
        "span_p99_s": round(_quantile(spans_s, 0.99), 6),
        "span_total_s": round(span_total, 6),
        "attribution_coverage": (round(explained / span_total, 4)
                                 if span_total > 0 else None),
        "critical_stage": critical,
        "stages": stages,
    }


def run_payload(run) -> Dict[str, Any]:
    """The ``GET /causality/<run_id>`` body: one run's happens-before
    graph + critical-path attribution."""
    records, gens, run_id = docs_of_run(run)
    graph = build_graph(records, gens, run_id)
    return {
        "schema": SCHEMA_GRAPH,
        "run_id": run_id,
        "graph": graph.to_jsonable(),
        "critical_path": critical_path(records, run_id),
    }


# -- divergence explanation ------------------------------------------------

#: shared-identity cap for the O(n^2) inversion scan; past it the
#: payload carries ``truncated`` with the dropped count
FLIP_SCAN_CAP = 512
#: inverted-pair budget for the FULL transitive-reduction pass. A
#: near-reversed 512-event pair holds ~131k inversions, and reducing
#: every one (O(interval) probes each) would pin a live REST handler
#: for seconds — past this budget only the top-scored pairs are
#: reduced and the payload says so (``minimality_bounded``).
MINIMALITY_BUDGET = 2048


def _occurrence_keys(record_docs: Iterable[dict]) -> List[str]:
    """Dispatch-ordered identity keys: the PR 2 order-line identity
    (entity + class:hint) made unique by occurrence index, so repeated
    hints — the normal case — pair up positionally across runs."""
    seen: Dict[str, int] = {}
    keys = []
    for line in export.order_lines_from_docs(record_docs):
        n = seen.get(line, 0)
        seen[line] = n + 1
        keys.append(f"{line}#{n}")
    return keys


def relation_flips(docs_a: Iterable[dict], docs_b: Iterable[dict],
                   top: int = 20,
                   suspicious: Optional[List] = None
                   ) -> Dict[str, Any]:
    """The ordering-relation diff between two runs' dispatch orders
    (see the module header). ``suspicious`` is the analyzer's
    fault-localization ranking (``[(branch, divergence, ...), ...]``);
    flips touching a suspicious branch's identity rank first."""
    keys_a = _occurrence_keys(docs_a)
    keys_b = _occurrence_keys(docs_b)
    set_a, set_b = set(keys_a), set(keys_b)
    shared_order = [k for k in keys_a if k in set_b]
    truncated = 0
    if len(shared_order) > FLIP_SCAN_CAP:
        truncated = len(shared_order) - FLIP_SCAN_CAP
        shared_order = shared_order[:FLIP_SCAN_CAP]
    shared = set(shared_order)
    # positions live in SHARED coordinates on both sides: indexing the
    # full per-run sequences would skew the minimality scan (and the
    # displacement score) whenever a run holds only-in-one events
    # before the flip region
    b_shared = [k for k in keys_b if k in shared]
    pos_a = {k: i for i, k in enumerate(shared_order)}
    pos_b = {k: i for i, k in enumerate(b_shared)}

    inverted = set()
    n = len(shared_order)
    for i in range(n):
        x = shared_order[i]
        for j in range(i + 1, n):
            y = shared_order[j]
            if pos_b[y] < pos_b[x]:
                inverted.add((x, y))

    def _minimal(x: str, y: str) -> bool:
        # a flip implied by two smaller flips through an intermediate z
        # is not part of the minimal explanation
        for z in shared_order[pos_a[x] + 1:pos_a[y]]:
            if (x, z) in inverted and (z, y) in inverted:
                return False
        return True

    boosts: List[Tuple[str, float]] = []
    for row in suspicious or []:
        try:
            branch, divergence = str(row[0]), float(row[1])
        except (IndexError, TypeError, ValueError):
            continue
        if branch and divergence > 0:
            boosts.append((branch, divergence))

    def _score(x: str, y: str) -> float:
        disp = abs(pos_a[x] - pos_b[x]) + abs(pos_a[y] - pos_b[y])
        boost = 0.0
        for branch, divergence in boosts:
            if branch in x or branch in y:
                boost = max(boost, divergence)
        return disp + 100.0 * boost

    # bound the reduction work: the full pass costs O(pairs x interval)
    # set probes, fine for real divergences (a handful to a few
    # thousand inversions) but quadratic-cubed for a near-reversed
    # pair — there, reduce only the pairs worth reporting
    bounded = len(inverted) > MINIMALITY_BUDGET
    candidates = sorted(inverted, key=lambda p: (-_score(*p), p))
    if bounded:
        candidates = candidates[:4 * max(1, top)]
    flips = [{
        "first": x, "then": y,
        "a_pos": [pos_a[x], pos_a[y]],
        "b_pos": [pos_b[x], pos_b[y]],
        "score": round(_score(x, y), 3),
    } for x, y in candidates if _minimal(x, y)]
    flips.sort(key=lambda f: (-f["score"], f["first"], f["then"]))

    return {
        "shared_events": len(shared),
        "truncated": truncated,
        "inverted_pairs": len(inverted),
        # bounded = a lower bound over the top-scored pairs only (the
        # payload must never read as exhaustive when it is not)
        "flips_minimal": len(flips),
        "minimality_bounded": bounded,
        "flips": flips[:max(1, top)],
        "only_in_a": sorted(set_a - set_b),
        "only_in_b": sorted(set_b - set_a),
        "identical_order": not inverted and set_a == set_b,
    }


def why_payload(records_a: List[dict], records_b: List[dict],
                run_a: str, run_b: str, top: int = 20,
                suspicious: Optional[List] = None) -> Dict[str, Any]:
    """The ``GET /causality/<a>/<b>`` body: the relation diff plus each
    run's graph summary and critical path, one self-contained document
    (``nmz-tpu tools why`` renders it)."""
    graph_a = build_graph(records_a, run_id=run_a)
    graph_b = build_graph(records_b, run_id=run_b)
    # per-run summaries keyed by SIDE, not run id: two storages'
    # traces legitimately share sequence-numbered ids (00000002 vs
    # 00000002), and id-keyed entries would silently collapse to one
    return {
        "schema": SCHEMA_WHY,
        "run_a": run_a,
        "run_b": run_b,
        "diff": relation_flips(records_a, records_b, top=top,
                               suspicious=suspicious),
        "runs": {
            side: {
                "run_id": run_label,
                "events": len(graph.dispatched_events),
                "acyclic": graph.is_acyclic(),
                "inversions": len(graph.stamp_inversions()),
                "critical_path": critical_path(records, run_label),
            }
            for side, run_label, graph, records in (
                ("a", run_a, graph_a, records_a),
                ("b", run_b, graph_b, records_b))
        },
    }


def render_why_md(doc: Dict[str, Any], perfetto: bool = True) -> str:
    """Markdown face of a why payload (``tools why --format md``).

    ``perfetto=False`` drops the closing "export and load in
    ui.perfetto.dev" pointer — callers rendering a payload whose runs
    have no local recorder dump (``--url``-fetched payloads, triage
    dossiers replayed elsewhere) must not print an export command that
    would only say "no recorded run"."""
    diff = doc.get("diff") or {}
    run_a, run_b = doc.get("run_a", "a"), doc.get("run_b", "b")
    lines = [
        f"# Why do runs `{run_a}` and `{run_b}` diverge?",
        "",
        f"- shared dispatched events: {diff.get('shared_events', 0)}",
        f"- ordering relations flipped: {diff.get('inverted_pairs', 0)}"
        f" (minimal explanation: {diff.get('flips_minimal', 0)} flips)",
        f"- only in {run_a}: {len(diff.get('only_in_a') or [])};"
        f" only in {run_b}: {len(diff.get('only_in_b') or [])}",
    ]
    if diff.get("truncated"):
        lines.append(f"- NOTE: flip scan truncated past "
                     f"{FLIP_SCAN_CAP} shared events "
                     f"({diff['truncated']} dropped)")
    if diff.get("minimality_bounded"):
        lines.append("- NOTE: the runs are heavily divergent; the "
                     "minimal-flip count covers only the top-scored "
                     "inverted pairs, not an exhaustive reduction")
    if diff.get("identical_order"):
        lines += ["", "The realized dispatch orders are identical — "
                      "any behavioral divergence is not an ordering "
                      "effect visible to the recorder."]
    flips = diff.get("flips") or []
    if flips:
        lines += ["", "## Minimal ordering flips (most suspicious "
                      "first)", "",
                  f"| score | first in `{run_a}` | then in `{run_a}` "
                  f"| positions a | positions b |",
                  "|---|---|---|---|---|"]
        for f in flips:
            lines.append(
                f"| {f['score']} | `{f['first']}` | `{f['then']}` "
                f"| {f['a_pos']} | {f['b_pos']} |")
    runs = doc.get("runs") or {}
    if runs:
        lines += ["", "## Per-run causality summary", "",
                  "| run | events | acyclic | stamp inversions "
                  "| critical stage | span p99 |",
                  "|---|---|---|---|---|---|"]
        for side in sorted(runs):
            row = runs[side]
            cp = row.get("critical_path") or {}
            lines.append(
                f"| `{row.get('run_id', side)}` | {row.get('events')} "
                f"| {row.get('acyclic')} | {row.get('inversions')} "
                f"| {cp.get('critical_stage')} "
                f"| {cp.get('span_p99_s')}s |")
    if perfetto:
        lines += ["",
                  "Inspect either side visually: `nmz-tpu tools trace "
                  "export <run_id> --out trace.json` and load it in "
                  "ui.perfetto.dev (tracks per entity/policy; the "
                  "decision args carry the delay and table "
                  "provenance).", ""]
    else:
        lines.append("")
    return "\n".join(lines)
