"""Cross-process trace context: span contexts + a Lamport clock.

The causality plane's wire layer (doc/observability.md "Causality").
Every event minted by a transceiver (and every event first seen at an
endpoint hub, for clients that predate this module) carries a compact
**span context** — run id, event uuid, causal parent, a Lamport logical
clock value, and the origin process — serialized as a ``ctx`` field on
the signal's wire dict. Because the journal, the batch REST routes, the
uds frames, and the edge backhaul all serialize signals through
``Signal.to_jsonable``, the context survives every hop we own (replay
after a reconnect, requeue after a failed backhaul flush, crash
recovery from the WAL) without per-wire plumbing.

The **logical clock** is the piece wall clocks cannot give us: each
process ticks it on every mint and merges (``observe``) the remote
value on every receive, so for any two context-stamped points connected
by a message chain, ``lc`` ordering agrees with causality regardless of
clock skew between processes. The happens-before analyzer
(obs/causality.py) uses the monotonic stamps for *latency* and the
logical clocks + graph structure for *order* — stamp inversions across
process boundaries are detected, never trusted.

Representation: a context IS its wire dict —
``{"lc": int, "o": "pid@host"[, "r": run id][, "p": parent uuid]}`` —
attached to signals as ``sig._obs_ctx``. Encode and decode are
therefore attribute moves, not conversions, and the dict is minimal by
design: the event's uuid is NOT repeated inside it (the signal carries
it), and the run id is filled at hub interception rather than minted
client-side. Both choices are load-bearing — the event plane serves
six figures of events per second through
``to_jsonable``/``signal_from_jsonable``, and an earlier per-event
object round-trip plus a fatter dict measurably taxed the zero-RTT
path.

Op-level frames that carry no signal (knowledge push/pull, telemetry
pushes, the framed fleet ops) attach a bare ``{"lc", "o"}`` stamp via
:func:`wire_stamp`; the shared framed server (endpoint/framed.py) and
the aggregator merge it with :func:`observe_wire`, so the clock stays
coherent across every wire, not just the event plane.

Cost contract: mirrors ``obs_enabled`` — with observability disabled
every helper here is one global read and a return; nothing is minted,
attached, or serialized.
"""

from __future__ import annotations

import os
import socket as _socket
import threading
from typing import Any, Dict, List, Optional

from namazu_tpu.obs import metrics

__all__ = [
    "CTX_ATTR", "CTX_KEY", "LamportClock",
    "clock", "origin", "mint", "mint_many", "ensure",
    "attach", "context_of", "child_of", "observe_wire",
    "observe", "lc_of", "wire_stamp", "reset",
]

#: attribute name on signals (same convention as spans.SPANS_ATTR)
CTX_ATTR = "_obs_ctx"
#: wire field on signal dicts and framed-op frames
CTX_KEY = "ctx"


class LamportClock:
    """A process-wide Lamport clock: ``tick`` on local send/mint,
    ``observe`` on receive (merge to ``max(local, remote) + 1``)."""

    __slots__ = ("_value", "_lock")

    def __init__(self) -> None:
        self._value = 0
        self._lock = threading.Lock()

    def tick(self, n: int = 1) -> int:
        with self._lock:
            self._value += n
            return self._value

    def observe(self, remote: int) -> int:
        with self._lock:
            self._value = max(self._value, int(remote)) + 1
            return self._value

    def value(self) -> int:
        return self._value


_clock = LamportClock()


def clock() -> LamportClock:
    return _clock


_origin: Optional[str] = None


def origin() -> str:
    """``pid@host`` — the process identity carried in contexts (and the
    forensic key for "which process stamped this"). Re-derived after a
    fork so children do not impersonate their parent."""
    global _origin
    o = _origin
    if o is None:
        o = _origin = f"{os.getpid()}@{_socket.gethostname()}"
    return o


def _forget_origin() -> None:
    global _origin
    _origin = None


if hasattr(os, "register_at_fork"):  # pragma: no branch
    os.register_at_fork(after_in_child=_forget_origin)


def mint(parent: str = "") -> Dict[str, Any]:
    """A fresh context: ticks the process clock once. The run id is
    deliberately absent — the hub fills it at interception, where the
    authoritative run is known (a remote mint can only guess)."""
    ctx: Dict[str, Any] = {"lc": _clock.tick(), "o": origin()}
    if parent:
        ctx["p"] = parent
    return ctx


def mint_many(events: List[Any], parent: str = "") -> None:
    """Batch mint for a burst (``Transceiver.send_events``): ONE clock
    tick for the whole burst — the intra-burst order is already carried
    by entity program order, and a per-event tick under the clock lock
    would tax the zero-RTT path for nothing.

    The burst shares ONE context dict (not per-event copies of equal
    value): every field the receive side ever writes into it is
    burst-invariant — the hub fills the same run id, clock merges read
    it — so aliasing is unobservable in meaning, it saves a dict mint
    per event on the million-events/s path, and it is what lets the
    binary batch codec carry the context ONCE per frame
    (signal/binary.py tag 0x11)."""
    if not metrics.enabled() or not events:
        return
    ctx: Dict[str, Any] = {"lc": _clock.tick(), "o": origin()}
    if parent:
        ctx["p"] = parent
    for ev in events:
        if ev.__dict__.get(CTX_ATTR) is None:
            ev.__dict__[CTX_ATTR] = ctx


def attach(sig: Any, ctx: Optional[Dict[str, Any]]) -> None:
    if ctx is not None:
        setattr(sig, CTX_ATTR, ctx)


def context_of(sig: Any) -> Optional[Dict[str, Any]]:
    return getattr(sig, CTX_ATTR, None)


def ensure(sig: Any, parent: str = "") -> Optional[Dict[str, Any]]:
    """The signal's context, minted on first use. None (and zero
    allocation) while observability is disabled."""
    if not metrics.enabled():
        return None
    ctx = getattr(sig, CTX_ATTR, None)
    if ctx is None:
        ctx = mint(parent=parent)
        setattr(sig, CTX_ATTR, ctx)
    return ctx


def child_of(parent_sig: Any) -> Optional[Dict[str, Any]]:
    """A context causally descending from ``parent_sig`` — for
    follow-on events an inspector emits *because of* an action it
    received (the explicit causal-parent edge in the DAG)."""
    if not metrics.enabled():
        return None
    return mint(parent=getattr(parent_sig, "uuid", ""))


def lc_of(ctx: Optional[Dict[str, Any]]) -> int:
    if not ctx:
        return 0
    lc = ctx.get("lc")
    return lc if isinstance(lc, int) else 0


def observe(ctx: Optional[Dict[str, Any]]) -> None:
    """Merge a context's clock into ours (the receive-side choke
    points: endpoint hub, framed server, fleet aggregator)."""
    lc = lc_of(ctx)
    if lc > 0:
        _clock.observe(lc)


def observe_wire(d: Any) -> Optional[Dict[str, Any]]:
    """Receive-side merge for a raw wire field (a signal's ctx, or a
    bare op stamp): folds the clock, returns the context dict (None
    for malformed input)."""
    if not isinstance(d, dict):
        return None
    lc = d.get("lc")
    if isinstance(lc, int) and lc > 0:
        _clock.observe(lc)
    return d


def wire_stamp() -> Dict[str, Any]:
    """A bare ``{"lc", "o"}`` stamp for op-level frames that carry no
    signal (knowledge ops, telemetry pushes, framed fleet reads)."""
    return {"lc": _clock.tick(), "o": origin()}


def reset() -> None:
    """Fresh clock (tests)."""
    global _clock
    _clock = LamportClock()
