"""Event-lifecycle spans + the domain metric vocabulary.

One home for every metric name the system emits (the schema
``doc/observability.md`` documents), and the span-stamping helpers that
thread an event's lifecycle through the stack:

====================  =====================================================
span                  stamped by
====================  =====================================================
``intercepted``       EndpointHub.post_event — the moment an inspector's
                      event enters the orchestrator process
``enqueued``          Orchestrator._event_loop — handed to the active
                      policy (queue-dwell starts here)
``decided``           Orchestrator._event_loop — queue_event returned,
                      i.e. the policy chose this event's delay/priority
``dispatched``        Orchestrator._action_loop — the answering action
                      left for its endpoint (or ran orchestrator-side)
``acked``             RestEndpoint DELETE — the inspector acknowledged
                      the action over the wire
====================  =====================================================

Spans are monotonic-clock floats stored in a per-signal dict
(``sig._obs_spans``); :func:`carry` copies them from the cause event onto
its answering action (signal/action.py ``Action.for_event``) so latencies
survive the event->action hand-off. Every helper here starts with the
``metrics.enabled()`` check — the disabled per-event cost is one global
read and a function call, nothing else (the micro-assert in
tests/test_obs.py pins this down).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional

from namazu_tpu.obs import metrics
from namazu_tpu.utils import timesource

SPANS_ATTR = "_obs_spans"

# -- metric name schema (see doc/observability.md) ----------------------

EVENTS_INTERCEPTED = "nmz_events_intercepted_total"
QUEUE_DWELL = "nmz_event_queue_dwell_seconds"
POLICY_DECISIONS = "nmz_policy_decisions_total"
DECISION_LATENCY = "nmz_policy_decision_latency_seconds"
ACTIONS_DISPATCHED = "nmz_actions_dispatched_total"
EVENT_E2E = "nmz_event_e2e_seconds"
REST_REQUESTS = "nmz_rest_requests_total"
REST_ACKS = "nmz_rest_acks_total"
REST_ACK_LATENCY = "nmz_rest_ack_latency_seconds"
SCHED_QUEUE_DEPTH = "nmz_sched_queue_depth"
SCHED_QUEUE_WAIT = "nmz_sched_queue_wait_seconds"
SEARCH_GENERATIONS = "nmz_search_generations_total"
SEARCH_GEN_RATE = "nmz_search_generations_per_sec"
SEARCH_BEST_FITNESS = "nmz_search_best_fitness"
SEARCH_ARCHIVE = "nmz_search_archive_entries"
SEARCH_INSTALLS = "nmz_search_installs_total"
SCORER_THROUGHPUT = "nmz_scorer_schedules_per_sec"
SEARCH_PHASE = "nmz_search_phase_seconds"
SEARCH_HOST_GAP = "nmz_search_host_gap_share"
SEARCH_DEVICE_TRACES = "nmz_search_device_traces_total"
SEARCH_STALL = "nmz_search_stall"
SIDECAR_REQUESTS = "nmz_sidecar_requests_total"
ENTITY_LABEL_OVERFLOW = "nmz_entity_label_overflow_total"

# event-plane fast path (doc/performance.md): how full the batches
# actually run, and what each client-side HTTP round trip costs
EVENT_BATCH = "nmz_event_batch_size"
TRANSPORT_RTT = "nmz_transport_rtt_seconds"

# the negotiated wire codec (doc/performance.md "Binary wire + sharded
# edge"): payload bytes by codec + op — the JSON-vs-binary byte savings
# made visible on /fleet — and how many connections negotiated what
WIRE_BYTES = "nmz_wire_bytes_total"
CODEC_NEGOTIATIONS = "nmz_codec_negotiations_total"
SHM_RING_FULL = "nmz_shm_ring_full_total"

#: power-of-two batch-occupancy buckets — the interesting question is
#: "are batches amortizing anything" (1 vs 2-8 vs full), not sub-unit
#: latency resolution
BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                 512.0, 1024.0)

#: event-stage latency buckets: the decision/dispatch segments run in
#: the tens of microseconds at edge rates, so the default 500µs floor
#: made HOTSTAGE and stage-p99 bucket-floor artifacts — sub-millisecond
#: bounds restore resolution where the serving plane actually lives.
#: The federation merge segregates (warns, never blends) pushes from
#: producers still on the old layout (obs/federation.py).
STAGE_BUCKETS = (0.000025, 0.00005, 0.0001, 0.00025, 0.0005, 0.001,
                 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                 1.0, 2.5)

# resilience plane (doc/robustness.md): unroutable-action drops and
# liveness-watchdog stall declarations, by entity
ACTIONS_UNROUTABLE = "nmz_actions_unroutable_total"
ENTITY_STALLED = "nmz_entity_stalled_total"

# zero-RTT edge dispatch (doc/performance.md "Zero-RTT dispatch"):
# events decided at the edge against a published delay table (counted
# when their backhaul reconciles into the orchestrator), and the
# monotonic version of the currently published table
EDGE_DECISIONS = "nmz_edge_decisions_total"
TABLE_VERSION = "nmz_table_version"

# edge observability (doc/observability.md "Fleet telemetry"): how far
# behind the async backhaul runs (edge decision stamp -> orchestrator
# reconcile stamp, same-host CLOCK_MONOTONIC), how stale the edge's
# held table is vs its last server confirmation, the edge's parked-heap
# depth, and the table version the edge currently decides with
EDGE_BACKHAUL_LAG = "nmz_edge_backhaul_lag_seconds"
EDGE_TABLE_STALENESS = "nmz_edge_table_staleness_seconds"
EDGE_PARKED = "nmz_edge_parked_events"
EDGE_TABLE_VERSION_HELD = "nmz_edge_table_version"
# search-install -> edge-decision propagation (ROADMAP item 3): the
# TablePublisher stamps each published table with its install time and
# every edge sync that adopts the table observes the gap (same-host
# CLOCK_MONOTONIC) — the first-class histogram behind `tools top`'s
# SKEW column, which shows versions-behind but not seconds-behind
TABLE_PROPAGATION = "nmz_table_propagation_seconds"

# fleet telemetry federation (doc/observability.md "Fleet telemetry"):
# relay push outcomes (producer side), fleet occupancy (aggregator
# side), SLO burn rates + breach transitions, and campaign slot
# outcomes (the supervisor's own producer metrics)
TELEMETRY_PUSHES = "nmz_telemetry_pushes_total"
TELEMETRY_FORWARD_DROPPED = "nmz_telemetry_forward_dropped_total"
FLEET_INSTANCES = "nmz_fleet_instances"
FLEET_STALE_INSTANCES = "nmz_fleet_stale_instances"
SLO_BURN = "nmz_slo_burn"
SLO_BREACHES = "nmz_slo_breaches_total"
CAMPAIGN_SLOTS = "nmz_campaign_slots_total"
# tenancy plane (doc/tenancy.md): per-namespace serving telemetry —
# the `run` label is the namespace name, the /fleet RUN dimension
TENANCY_EVENTS = "nmz_tenancy_events_total"
TENANCY_PARKED = "nmz_tenancy_parked"
TENANCY_RUNS = "nmz_tenancy_runs"
TENANCY_RECLAIMS = "nmz_tenancy_reclaims_total"
REST_CONN_THREADS = "nmz_rest_conn_threads"
REST_CONNS_QUEUED = "nmz_rest_conns_queued"
# fleet placement plane (doc/tenancy.md "Fleet of fleets"): pool-level
# lease migrations by reason (drain = operator-requested, death = TTL /
# staleness declared the host dead), admission-control refusals, and the
# placement service's live occupancy (hosts by liveness, pool leases,
# placements still waiting for an eligible host)
FLEET_MIGRATIONS = "nmz_fleet_migrations_total"
FLEET_ADMISSION_REJECTIONS = "nmz_fleet_admission_rejections_total"
FLEET_POOL_HOSTS = "nmz_fleet_pool_hosts"
FLEET_POOL_LEASES = "nmz_fleet_pool_leases"
FLEET_POOL_PENDING = "nmz_fleet_pool_pending_placements"

# chaos + survivability plane (doc/robustness.md "Chaos plane"):
# injected faults by point, ingress backpressure rejections, the
# server-requested Retry-After delays the transceiver honored, and the
# crash-recovery journal's traffic
CHAOS_FAULTS = "nmz_chaos_faults_injected_total"
INGRESS_REJECTIONS = "nmz_ingress_rejections_total"
TRANSPORT_RETRY_AFTER = "nmz_transport_retry_after_seconds"
JOURNAL_EVENTS = "nmz_journal_events_total"
JOURNAL_RECOVERED = "nmz_journal_recovered_events_total"

# global failure-knowledge plane (doc/knowledge.md): cross-campaign
# pool traffic, warm-start installs, the shared surrogate's training
# cadence, and the service's tenant/pool occupancy
KNOWLEDGE_PUSHES = "nmz_knowledge_pushes_total"
KNOWLEDGE_PULLS = "nmz_knowledge_pulls_total"
KNOWLEDGE_DEDUPE = "nmz_knowledge_dedupe_hits_total"
KNOWLEDGE_WARMSTART = "nmz_knowledge_warmstart_installs_total"
KNOWLEDGE_SURROGATE_ROUNDS = "nmz_knowledge_surrogate_train_rounds_total"
KNOWLEDGE_TENANTS = "nmz_knowledge_tenants"
KNOWLEDGE_POOL = "nmz_knowledge_pool_entries"
KNOWLEDGE_OUTAGES = "nmz_knowledge_outages_total"
# knowledge fan-in (M orchestrator hosts pushing concurrently): requests
# currently inside the service handler, and how long each waited for the
# shared-state lock — the serialize-behind-one-lock regression detector
KNOWLEDGE_FANIN_INFLIGHT = "nmz_knowledge_fanin_inflight"
KNOWLEDGE_FANIN_LOCK_WAIT = "nmz_knowledge_fanin_lock_wait_seconds"

# triage plane (doc/observability.md "Triage"): minimization probe
# traffic split by mode (simulated = free predicted_gain scoring,
# replayed = real campaign-runner executions), the last minimization's
# size ratio (minimal flips / candidate flips), dossier pulls against
# the knowledge wire, and how many failure signatures this process
# holds dossiers for (the /fleet SIGS column)
TRIAGE_PROBES = "nmz_triage_probes_total"
TRIAGE_MINIMIZATION_RATIO = "nmz_triage_minimization_ratio"
TRIAGE_DOSSIER_PULLS = "nmz_triage_dossier_pulls_total"
TRIAGE_SIGNATURES = "nmz_triage_signatures"

# causality plane (doc/observability.md "Causality"): each event's
# intercepted->acked span decomposed into named segments — queue (hub
# queue dwell), decision (policy), parking (the injected delay),
# dispatch (action loop), wire (dispatch -> inspector ack); edge events
# contribute edge_parking (local decide -> local release) and backhaul
# (edge dispatch -> orchestrator reconcile). The central segments
# telescope: their sum IS the intercepted->acked span, so "where does
# the millisecond go" is a histogram query, not a bench run.
EVENT_STAGE = "nmz_event_stage_seconds"

# guidance plane (doc/search.md): relation-coverage occupancy of the
# campaign's CoverageMap (covered bits / bitmap width) and the size of
# its one-sided frontier — the live face of the relation-coverage curve
# /analytics serves post-hoc
RELATION_COVERAGE = "nmz_relation_coverage"
RELATION_ONE_SIDED = "nmz_relation_one_sided"

# experiment plane (cross-run aggregates, set by obs/analytics.py when a
# payload is computed — GET /analytics, nmz-tpu tools report)
EXPERIMENT_RUNS = "nmz_experiment_runs"
EXPERIMENT_FAILURES = "nmz_experiment_failures"
EXPERIMENT_FAILURE_RATE = "nmz_experiment_failure_rate"
EXPERIMENT_UNIQUE = "nmz_experiment_unique_interleavings"
EXPERIMENT_COVERAGE = "nmz_experiment_interleaving_coverage"
EXPERIMENT_NOVELTY = "nmz_experiment_novelty_last_window"
EXPERIMENT_TTFF = "nmz_experiment_time_to_first_failure_seconds"
EXPERIMENT_RUNS_TO_REPRO = "nmz_experiment_mean_runs_to_reproduce"

# campaign progress plane (obs/stats.py sequential statistics, published
# live by the campaign supervisor after every slot and by the analytics
# fold — doc/observability.md "Calibration & progress"): the measured
# repro rate with its Wilson bounds, throughput in repros/hour, the
# next-repro ETA forecast, how many more runs a target-width CI needs,
# and the band SPRT's in/out-of-band verdict (1 in band, 0 out, unset
# while undecided). Federated through /fleet as the RATE and ETA columns
CAMPAIGN_RATE = "nmz_campaign_repro_rate"
CAMPAIGN_RATE_CI_LOW = "nmz_campaign_repro_rate_ci_low"
CAMPAIGN_RATE_CI_HIGH = "nmz_campaign_repro_rate_ci_high"
CAMPAIGN_REPROS_PER_HOUR = "nmz_campaign_repros_per_hour"
CAMPAIGN_ETA_NEXT = "nmz_campaign_eta_next_repro_seconds"
CAMPAIGN_RUNS_TO_CI = "nmz_campaign_runs_to_ci_width"
CAMPAIGN_IN_BAND = "nmz_campaign_in_band"
CAMPAIGN_REPROS_PER_HOUR_VIRTUAL = "nmz_campaign_repros_per_hour_virtual"

# virtual-clock plane (doc/performance.md "Virtual clock"): how much
# wall time the discrete-event fast-forward saved (virtual elapsed /
# wall elapsed) and how long the pinning rule held the clock at wall
# rate (real I/O, running entities, busy queues). Wall-denominated
# surfaces (SPRT budgets, calibration artifacts) NEVER read these
VCLOCK_SPEEDUP = "nmz_vclock_speedup_ratio"
VCLOCK_PINNED = "nmz_vclock_pinned_seconds_total"


#: distinct ``entity`` label values admitted per registry before new
#: entities fold into "_other" — inspectors can mint an entity per
#: observed process/connection, and unbounded label cardinality would
#: grow the registry (and every /metrics scrape) without limit over a
#: long experiment
MAX_ENTITY_LABELS = 64

_entity_lock = threading.Lock()


def _entity_label(reg, entity: str) -> str:
    # locked: hub/orchestrator/policy/REST threads all admit entities
    # concurrently, and a racy lazy-init or check-then-add would split
    # one entity's samples across its own series and "_other"
    with _entity_lock:
        seen = getattr(reg, "_obs_entity_labels", None)
        if seen is None:
            seen = reg._obs_entity_labels = set()
        if entity in seen:
            return entity
        if len(seen) >= MAX_ENTITY_LABELS:
            # the fold is itself observable: a dashboard showing flat
            # per-entity series while this counter climbs is sampling a
            # collapsed label space, not a quiet system
            reg.counter(
                ENTITY_LABEL_OVERFLOW,
                "entity label admissions folded into _other "
                "(MAX_ENTITY_LABELS cap hit)",
            ).inc()
            return "_other"
        seen.add(entity)
        return entity


# -- span stamping ------------------------------------------------------

def mark(sig, name: str, now: Optional[float] = None) -> None:
    """Stamp ``sig`` with the monotonic time of lifecycle point ``name``.

    Stamps read the process TimeSource — ``time.monotonic()`` under the
    default wall source, the jumpable virtual clock under
    ``run --virtual-clock`` — so every span delta (and the queue-dwell a
    shutdown drain attributes to still-resident events) is denominated
    in the same domain the delays themselves were scheduled in
    (doc/performance.md "Virtual clock")."""
    if not metrics.enabled():
        return
    spans = getattr(sig, SPANS_ATTR, None)
    if spans is None:
        spans = {}
        setattr(sig, SPANS_ATTR, spans)
    spans[name] = timesource.get().now() if now is None else now


def span(sig, name: str) -> Optional[float]:
    spans = getattr(sig, SPANS_ATTR, None)
    return spans.get(name) if spans else None


def latency(sig, since: str, now: Optional[float] = None) -> Optional[float]:
    """Seconds elapsed since span ``since`` was stamped, or None."""
    t0 = span(sig, since)
    if t0 is None:
        return None
    return (timesource.get().now() if now is None else now) - t0


def span_delta(sig, since: str, until: str) -> Optional[float]:
    """Seconds between two already-stamped spans, or None when either
    is missing — the per-segment read the stage attribution uses."""
    spans = getattr(sig, SPANS_ATTR, None)
    if not spans:
        return None
    t0 = spans.get(since)
    t1 = spans.get(until)
    if t0 is None or t1 is None:
        return None
    return t1 - t0


def carry(dst, src) -> None:
    """Attach the cause event's span dict to its answering action.

    The dict is SHARED, not copied: the orchestrator's event loop may
    still be stamping ``decided`` while a zero-delay dequeue is already
    constructing the action on another thread — sharing makes every
    stamp visible on both signals regardless of that race (dict access
    is GIL-atomic)."""
    if not metrics.enabled():
        return
    spans = getattr(src, SPANS_ATTR, None)
    if spans is not None:
        setattr(dst, SPANS_ATTR, spans)


# -- recording helpers (control plane) ----------------------------------

def event_intercepted(endpoint: str, entity: str, n: int = 1) -> None:
    if not metrics.enabled():
        return
    reg = metrics.get()
    reg.counter(
        EVENTS_INTERCEPTED,
        "events entering the orchestrator, by transport endpoint",
        ("endpoint", "entity"),
    ).labels(endpoint=endpoint, entity=_entity_label(reg, entity)).inc(n)


def policy_decision(policy: str, entity: str,
                    decision_latency: Optional[float]) -> None:
    """One policy decision (delay/priority chosen for an event)."""
    if not metrics.enabled():
        return
    reg = metrics.get()
    reg.counter(
        POLICY_DECISIONS,
        "events a policy decided a schedule for",
        ("policy", "entity"),
    ).labels(policy=policy, entity=_entity_label(reg, entity)).inc()
    if decision_latency is not None:
        reg.histogram(
            DECISION_LATENCY,
            "interception -> policy decision (hub queue + queue_event)",
            ("policy",),
        ).labels(policy=policy).observe(decision_latency)


def queue_dwell(policy: str, entity: str,
                seconds: Optional[float]) -> None:
    """How long an event sat in the policy's delay queue (the injected
    fuzz delay plus scheduling overhead)."""
    if seconds is None or not metrics.enabled():
        return
    reg = metrics.get()
    reg.histogram(
        QUEUE_DWELL,
        "policy enqueue -> release (injected delay + overhead)",
        ("policy", "entity"),
    ).labels(policy=policy,
             entity=_entity_label(reg, entity)).observe(seconds)


def action_dispatched(kind: str, e2e: Optional[float],
                      n: int = 1) -> None:
    if not metrics.enabled():
        return
    reg = metrics.get()
    reg.counter(
        ACTIONS_DISPATCHED,
        "actions leaving the orchestrator action loop",
        ("kind",),
    ).labels(kind=kind).inc(n)
    if e2e is not None:
        reg.histogram(
            EVENT_E2E,
            "interception -> action dispatch, end to end",
        ).observe(e2e)


def action_unroutable(entity: str) -> None:
    """An action dropped because no endpoint ever carried an event for
    its entity (EndpointHub.send_action) — the counter that replaces
    silent log-and-drop during long experiments."""
    if not metrics.enabled():
        return
    reg = metrics.get()
    reg.counter(
        ACTIONS_UNROUTABLE,
        "actions dropped for lack of an entity -> endpoint route",
        ("entity",),
    ).labels(entity=_entity_label(reg, entity)).inc()


def entity_stalled(entity: str) -> None:
    """The liveness watchdog declared an entity dead (no event within
    the configured timeout while events sat parked on its behalf)."""
    if not metrics.enabled():
        return
    reg = metrics.get()
    reg.counter(
        ENTITY_STALLED,
        "liveness-watchdog stall declarations (parked events force-"
        "released)",
        ("entity",),
    ).labels(entity=_entity_label(reg, entity)).inc()


def edge_decision(entity: str, n: int = 1) -> None:
    """``n`` edge-decided events reconciled into the orchestrator via
    asynchronous backhaul (the zero-RTT dispatch path) — every one was
    dispatched at the edge without a central round trip."""
    if n <= 0 or not metrics.enabled():
        return
    reg = metrics.get()
    reg.counter(
        EDGE_DECISIONS,
        "events decided and dispatched at the edge against a "
        "published delay table",
        ("entity",),
    ).labels(entity=_entity_label(reg, entity)).inc(n)


def table_version(version: int) -> None:
    """The monotonic version of the currently published delay table
    (bumped on every search-plane install, withdrawal, or
    suspend/resume — policy/edge_table.py)."""
    if not metrics.enabled():
        return
    metrics.get().gauge(
        TABLE_VERSION,
        "monotonic version of the published hash->delay table",
    ).set(version)


def edge_backhaul_lag(entity: str, seconds: float) -> None:
    """One edge-decided event's decision->reconcile lag, observed at
    ``Orchestrator._ingest_edge_batch`` (the edge stamps and the
    orchestrator clock share CLOCK_MONOTONIC on one host)."""
    if not metrics.enabled():
        return
    reg = metrics.get()
    reg.histogram(
        EDGE_BACKHAUL_LAG,
        "edge decision stamp -> orchestrator backhaul reconcile",
        ("entity",),
    ).labels(entity=_entity_label(reg, entity)).observe(max(0.0, seconds))


def edge_table_staleness(entity: str, seconds: float) -> None:
    """Seconds since this edge last confirmed its held table version
    against the server (0 while on the central wire — central dispatch
    cannot be stale)."""
    if not metrics.enabled():
        return
    reg = metrics.get()
    reg.gauge(
        EDGE_TABLE_STALENESS,
        "seconds since the edge's held table was last confirmed "
        "against the server",
        ("entity",),
    ).labels(entity=_entity_label(reg, entity)).set(max(0.0, seconds))


def edge_parked(entity: str, depth: int) -> None:
    """Events parked in the edge dispatcher's delayed-release heap."""
    if not metrics.enabled():
        return
    reg = metrics.get()
    reg.gauge(
        EDGE_PARKED,
        "events parked in the edge dispatcher's delayed-release heap",
        ("entity",),
    ).labels(entity=_entity_label(reg, entity)).set(depth)


def edge_table_version_held(entity: str, version: int) -> None:
    """The table version this edge currently decides with (0 = central
    fallback); the fleet view diffs it against ``nmz_table_version`` to
    surface table-version skew."""
    if not metrics.enabled():
        return
    reg = metrics.get()
    reg.gauge(
        EDGE_TABLE_VERSION_HELD,
        "table version the edge currently decides with (0 = central)",
        ("entity",),
    ).labels(entity=_entity_label(reg, entity)).set(version)


# -- fleet telemetry federation (doc/observability.md) --------------------

def telemetry_push(ok: bool) -> None:
    """One relay push cycle's outcome (producer side)."""
    if not metrics.enabled():
        return
    metrics.get().counter(
        TELEMETRY_PUSHES, "telemetry relay push cycles", ("ok",),
    ).labels(ok=str(bool(ok)).lower()).inc()


def telemetry_forward_dropped(n: int = 1) -> None:
    """Foreign telemetry docs dropped from a full forward buffer (the
    federation hop stayed bounded through an upstream outage)."""
    if n <= 0 or not metrics.enabled():
        return
    metrics.get().counter(
        TELEMETRY_FORWARD_DROPPED,
        "forwarded telemetry docs dropped by the bounded buffer",
    ).inc(n)


def fleet_occupancy(instances: int, stale: int) -> None:
    """Aggregator-side view: producers currently merged, and how many
    have gone silent past their staleness window."""
    if not metrics.enabled():
        return
    reg = metrics.get()
    reg.gauge(FLEET_INSTANCES,
              "producer instances in the fleet aggregator").set(instances)
    reg.gauge(FLEET_STALE_INSTANCES,
              "fleet producers silent past their staleness window",
              ).set(stale)


def slo_burn(name: str, burn: float) -> None:
    """Current burn rate of one declared SLO (>= 1 = the objective is
    being violated over its window; obs/slo.py)."""
    if not metrics.enabled():
        return
    metrics.get().gauge(
        SLO_BURN,
        "SLO burn rate (>= 1 = objective violated over its window)",
        ("slo",),
    ).labels(slo=name).set(burn)


def slo_breach(name: str) -> None:
    """One breach TRANSITION (burn crossed 1.0 upward) of an SLO."""
    if not metrics.enabled():
        return
    metrics.get().counter(
        SLO_BREACHES, "SLO breach transitions", ("slo",),
    ).labels(slo=name).inc()


def campaign_slot(cls: str) -> None:
    """One finished campaign run slot, by outcome class (the supervisor
    process's own producer metrics for the fleet view)."""
    if not metrics.enabled():
        return
    metrics.get().counter(
        CAMPAIGN_SLOTS, "campaign run slots finished, by class",
        ("slot_class",),
    ).labels(slot_class=cls).inc()


def tenancy_events(run: str, n: int = 1) -> None:
    """Events ingested for one tenant namespace (the per-run events/s
    numerator of the /fleet RUN table)."""
    if not metrics.enabled():
        return
    metrics.get().counter(
        TENANCY_EVENTS, "events ingested per tenant run namespace",
        ("run",),
    ).labels(run=run).inc(n)


def tenancy_parked(run: str, depth: int) -> None:
    """One namespace's parked-event depth (its policy's ScheduledQueue
    residency) — refreshed on ingest and on the host's reaper tick."""
    if not metrics.enabled():
        return
    metrics.get().gauge(
        TENANCY_PARKED, "parked events per tenant run namespace",
        ("run",),
    ).labels(run=run).set(depth)


def tenancy_runs(n: int) -> None:
    """How many run namespaces this orchestrator currently leases."""
    if not metrics.enabled():
        return
    metrics.get().gauge(
        TENANCY_RUNS, "active leased run namespaces").set(n)


def tenancy_reclaim(run: str) -> None:
    """A lease expired and its namespace was reclaimed (the crashed-
    tenant transition; parked events stay journaled for the re-lease)."""
    if not metrics.enabled():
        return
    metrics.get().counter(
        TENANCY_RECLAIMS,
        "tenant namespaces reclaimed after lease expiry", ("run",),
    ).labels(run=run).inc()


def fleet_migration(reason: str, n: int = 1) -> None:
    """``n`` pool leases re-placed onto a replacement host, by reason
    (``drain`` = operator-requested graceful evacuation, ``death`` =
    the monitor declared the host dead)."""
    if n <= 0 or not metrics.enabled():
        return
    metrics.get().counter(
        FLEET_MIGRATIONS,
        "pool leases migrated to a replacement host, by reason",
        ("reason",),
    ).labels(reason=reason).inc(n)


def fleet_admission_rejected(reason: str) -> None:
    """The placement service refused a pool lease (``slo_burn`` = the
    pool's SLO burn gate tripped, ``capacity`` = no eligible host had a
    free slot, ``chaos`` = the fleet.admission.refuse seam fired)."""
    if not metrics.enabled():
        return
    metrics.get().counter(
        FLEET_ADMISSION_REJECTIONS,
        "pool lease requests refused by admission control", ("reason",),
    ).labels(reason=reason).inc()


def fleet_pool_stats(hosts: int, dead: int, leases: int,
                     pending: int) -> None:
    """The placement service's occupancy gauges, refreshed on every
    monitor tick: pool hosts by liveness, granted pool leases, and
    placements still waiting for an eligible host."""
    if not metrics.enabled():
        return
    reg = metrics.get()
    g = reg.gauge(FLEET_POOL_HOSTS,
                  "orchestrator hosts in the placement pool, by state",
                  ("state",))
    g.labels(state="live").set(max(0, hosts - dead))
    g.labels(state="dead").set(dead)
    reg.gauge(FLEET_POOL_LEASES,
              "pool leases the placement service has granted",
              ).set(leases)
    reg.gauge(FLEET_POOL_PENDING,
              "pool leases waiting for an eligible host").set(pending)


def rest_conn_pool(active: int, queued: int) -> None:
    """The REST endpoint's bounded ingress pool: handler threads alive
    vs connections queued waiting for one (doc/tenancy.md — 8 campaigns'
    clients must not mean unbounded thread growth)."""
    if not metrics.enabled():
        return
    reg = metrics.get()
    reg.gauge(REST_CONN_THREADS,
              "REST connection handler threads alive").set(active)
    reg.gauge(REST_CONNS_QUEUED,
              "REST connections queued for a handler thread").set(queued)


def chaos_fault_injected(point: str) -> None:
    """A chaos fault point fired (namazu_tpu/chaos): the injected-fault
    ledger a scenario report joins against its invariants."""
    if not metrics.enabled():
        return
    metrics.get().counter(
        CHAOS_FAULTS,
        "chaos-plane faults injected, by fault point",
        ("point",),
    ).labels(point=point).inc()


def ingress_rejected(endpoint: str, reason: str) -> None:
    """The REST endpoint refused an event POST — backpressure (the
    bounded ingress queue is full) or an injected chaos refusal."""
    if not metrics.enabled():
        return
    metrics.get().counter(
        INGRESS_REJECTIONS,
        "event POSTs refused with 429/503 (backpressure or chaos)",
        ("endpoint", "reason"),
    ).labels(endpoint=endpoint, reason=reason).inc()


def transport_retry_after(seconds: float) -> None:
    """The transceiver honored a server-sent Retry-After before its
    next POST attempt (capped + jittered; doc/robustness.md)."""
    if not metrics.enabled():
        return
    metrics.get().histogram(
        TRANSPORT_RETRY_AFTER,
        "server-requested Retry-After delays honored by the transceiver",
    ).observe(seconds)


def journal_events(n: int) -> None:
    if n <= 0 or not metrics.enabled():
        return
    metrics.get().counter(
        JOURNAL_EVENTS,
        "inbound events appended to the crash-recovery journal",
    ).inc(n)


def journal_recovered(n: int) -> None:
    if n <= 0 or not metrics.enabled():
        return
    metrics.get().counter(
        JOURNAL_RECOVERED,
        "parked events recovered from the journal after a restart",
    ).inc(n)


def event_batch(stage: str, size: int) -> None:
    """One batch moved through an event-plane stage (``ingress`` = REST
    batch POST -> hub, ``dispatch`` = orchestrator action fan-out,
    ``actions_poll`` = batch GET response, ``flush`` = transceiver
    client-side coalescing flush)."""
    if not metrics.enabled():
        return
    metrics.get().histogram(
        EVENT_BATCH,
        "events per batch through the event-plane fast path",
        ("stage",),
        buckets=BATCH_BUCKETS,
    ).labels(stage=stage).observe(size)


_EVENT_STAGE_HELP = ("per-event latency by lifecycle segment (queue/"
                     "decision/parking/dispatch/wire; edge_parking/"
                     "backhaul on the edge path)")


def event_stage(stage: str, seconds: Optional[float]) -> None:
    """One event's time through one lifecycle segment (the critical-
    path attribution's histogram face; None = the bounding stamps were
    absent, e.g. wire-less local transports — observe nothing rather
    than a fake 0)."""
    if seconds is None or not metrics.enabled():
        return
    metrics.get().histogram(
        EVENT_STAGE, _EVENT_STAGE_HELP, ("stage",),
        buckets=STAGE_BUCKETS,
    ).labels(stage=stage).observe(max(0.0, seconds))


def event_stage_many(stage: str, values) -> None:
    """Batch face of :func:`event_stage`: ONE registry/label
    resolution for a whole burst's samples — the edge-backhaul
    reconcile runs at zero-RTT rates, where a per-event family lookup
    would tax the serving plane it measures."""
    if not values or not metrics.enabled():
        return
    child = metrics.get().histogram(
        EVENT_STAGE, _EVENT_STAGE_HELP, ("stage",),
        buckets=STAGE_BUCKETS,
    ).labels(stage=stage)
    for v in values:
        child.observe(max(0.0, v))


def wire_bytes(codec: str, op: str, n: int) -> None:
    """``n`` payload bytes moved over a signal-carrying wire under
    ``codec`` ("json"/"nmzb1") for ``op`` (post_batch/poll/ack/
    backhaul/table/frame). Counted once per message at the side that
    built/parsed it — the byte-savings ledger of the negotiated
    binary codec."""
    if not metrics.enabled() or n <= 0:
        return
    metrics.get().counter(
        WIRE_BYTES,
        "wire payload bytes by codec and operation",
        ("codec", "op"),
    ).labels(codec=codec, op=op).inc(n)


def shm_ring_full(entity: str) -> None:
    """One burst that could not fit the shm ring and fell back to the
    acked uds op wire — the ring-sizing backpressure signal."""
    if not metrics.enabled():
        return
    reg = metrics.get()
    reg.counter(
        SHM_RING_FULL,
        "shm-ring-full fallbacks onto the acked op wire",
        ("entity",),
    ).labels(entity=_entity_label(reg, entity)).inc()


def codec_negotiated(codec: str) -> None:
    """One per-connection codec negotiation settled on ``codec``."""
    if not metrics.enabled():
        return
    metrics.get().counter(
        CODEC_NEGOTIATIONS,
        "per-connection codec negotiations by outcome",
        ("codec",),
    ).labels(codec=codec).inc()


def transport_rtt(op: str, seconds: float) -> None:
    """Client-side wall time of one transceiver HTTP round trip
    (``post`` / ``post_batch`` / ``poll`` / ``ack``)."""
    if not metrics.enabled():
        return
    metrics.get().histogram(
        TRANSPORT_RTT,
        "transceiver-side HTTP round-trip time",
        ("op",),
    ).labels(op=op).observe(seconds)


def rest_request(method: str, code: int) -> None:
    if not metrics.enabled():
        return
    metrics.get().counter(
        REST_REQUESTS, "REST endpoint requests", ("method", "code"),
    ).labels(method=method, code=str(code)).inc()


def rest_ack(entity: str, ack_latency: Optional[float]) -> None:
    if not metrics.enabled():
        return
    reg = metrics.get()
    reg.counter(
        REST_ACKS, "actions acknowledged over REST", ("entity",),
    ).labels(entity=_entity_label(reg, entity)).inc()
    if ack_latency is not None:
        reg.histogram(
            REST_ACK_LATENCY,
            "action dispatch -> REST DELETE acknowledgment",
        ).observe(ack_latency)


def sched_queue_depth(queue: str, depth: int) -> None:
    if not metrics.enabled():
        return
    metrics.get().gauge(
        SCHED_QUEUE_DEPTH, "items pending in a ScheduledQueue", ("queue",),
    ).labels(queue=queue).set(depth)


def sched_queue_wait(queue: str, seconds: float) -> None:
    if not metrics.enabled():
        return
    metrics.get().histogram(
        SCHED_QUEUE_WAIT,
        "realized put -> get delay inside a ScheduledQueue",
        ("queue",),
    ).labels(queue=queue).observe(seconds)


# -- recording helpers (search plane) -----------------------------------

def search_round(backend: str, generations: int, elapsed: float,
                 schedules: float, best_fitness: float,
                 archive_entries: int, failure_entries: int,
                 distinct_failures: int,
                 host_io_s: Optional[float] = None) -> None:
    """One search.run() call's worth of progress. ``host_io_s`` is the
    wall time the round spent in the fused loop's overlapped host-I/O
    lane (doc/performance.md "Fused search loop"): published as the
    ``nmz_search_host_gap_share{backend}`` gauge (host seconds per
    evolve second — the number the fusion exists to drive toward 0)."""
    if not metrics.enabled():
        return
    reg = metrics.get()
    if host_io_s is not None and elapsed > 0:
        reg.gauge(
            SEARCH_HOST_GAP, "host-I/O share of the last fused search "
            "round (host_io seconds / evolve seconds)", ("backend",),
        ).labels(backend=backend).set(host_io_s / elapsed)
    reg.counter(
        SEARCH_GENERATIONS, "GA generations (or MCTS simulations) run",
        ("backend",),
    ).labels(backend=backend).inc(generations)
    if elapsed > 0:
        reg.gauge(
            SEARCH_GEN_RATE, "generations/sec of the last search round",
            ("backend",),
        ).labels(backend=backend).set(generations / elapsed)
        reg.gauge(
            SCORER_THROUGHPUT,
            "schedules scored per second by the jitted scorer",
            ("source",),
        ).labels(source=backend).set(schedules / elapsed)
    reg.gauge(
        SEARCH_BEST_FITNESS, "best fitness seen so far", ("backend",),
    ).labels(backend=backend).set(best_fitness)
    arch = reg.gauge(
        SEARCH_ARCHIVE, "archive ring occupancy", ("backend", "archive"),
    )
    arch.labels(backend=backend, archive="novelty").set(archive_entries)
    arch.labels(backend=backend, archive="failure").set(failure_entries)
    arch.labels(backend=backend,
                archive="failure_distinct").set(distinct_failures)
    # live stall detection (obs/analytics.py): fitness + novelty sliding
    # window per backend; trips nmz_search_stall and a run-tagged
    # warning while the experiment is still running. Lazy import — the
    # analytics module imports this one for the metric vocabulary.
    from namazu_tpu.obs import analytics

    analytics.note_search_round(backend, best_fitness, distinct_failures)


def search_progress(backend: str, best_fitness: float) -> None:
    """Live best-fitness update from the fused loop's host lane — the
    cheap per-chunk publication that keeps the gauge moving while one
    ``run()`` is still evolving (search_round refreshes it at the end
    of the round as before)."""
    if not metrics.enabled():
        return
    metrics.get().gauge(
        SEARCH_BEST_FITNESS, "best fitness seen so far", ("backend",),
    ).labels(backend=backend).set(best_fitness)


def search_stall(backend: str, stalled: bool) -> None:
    """Mirror the live stall detector's verdict (obs/analytics.py) into
    ``nmz_search_stall{backend}`` (1 = novelty and fitness both flat
    over the detector window, 0 = progressing)."""
    if not metrics.enabled():
        return
    metrics.get().gauge(
        SEARCH_STALL,
        "search-plane stall detector (1 = fitness and novelty both "
        "flatlined over the detector window)",
        ("backend",),
    ).labels(backend=backend).set(1.0 if stalled else 0.0)


def experiment_stats(runs: int, failures: int, failure_rate: float,
                     unique_interleavings: int, coverage: float,
                     novelty_last_window: Optional[float],
                     time_to_first_failure_s: Optional[float],
                     mean_runs_to_reproduce: Optional[float]) -> None:
    """Publish one analytics payload's cross-run aggregates as gauges
    (None values leave their gauge untouched rather than faking a 0)."""
    if not metrics.enabled():
        return
    reg = metrics.get()
    reg.gauge(EXPERIMENT_RUNS,
              "completed runs in the analyzed storage").set(runs)
    reg.gauge(EXPERIMENT_FAILURES,
              "failed (= bug-reproducing) runs in the analyzed storage",
              ).set(failures)
    reg.gauge(EXPERIMENT_FAILURE_RATE,
              "failure rate over the analyzed storage").set(failure_rate)
    reg.gauge(EXPERIMENT_UNIQUE,
              "distinct interleavings (trace_digest) recorded",
              ).set(unique_interleavings)
    reg.gauge(EXPERIMENT_COVERAGE,
              "unique interleavings / runs").set(coverage)
    if novelty_last_window is not None:
        reg.gauge(EXPERIMENT_NOVELTY,
                  "new-interleaving rate of the last analytics window",
                  ).set(novelty_last_window)
    if time_to_first_failure_s is not None:
        reg.gauge(EXPERIMENT_TTFF,
                  "cumulative run time until the first failure",
                  ).set(time_to_first_failure_s)
    if mean_runs_to_reproduce is not None:
        reg.gauge(EXPERIMENT_RUNS_TO_REPRO,
                  "runs per reproduction (inverse failure rate)",
                  ).set(mean_runs_to_reproduce)


def campaign_progress(rate: Optional[float],
                      ci: Optional[Any] = None,
                      repros_per_hour: Optional[float] = None,
                      eta_next_repro_s: Optional[float] = None,
                      runs_to_ci: Optional[float] = None,
                      in_band: Optional[int] = None,
                      repros_per_hour_virtual: Optional[float] = None,
                      ) -> None:
    """Publish one campaign-progress document's live face (obs/stats.py
    via obs/analytics.progress_stats) as ``nmz_campaign_*`` gauges. A
    None value leaves its gauge untouched rather than faking a 0 — a
    young campaign has no rate yet, not a zero rate; an undecided SPRT
    has no in/out-of-band verdict."""
    if not metrics.enabled():
        return
    reg = metrics.get()
    if rate is not None:
        reg.gauge(CAMPAIGN_RATE,
                  "measured repro (failure) rate of the campaign's "
                  "storage").set(rate)
    if ci is not None and len(ci) == 2:
        reg.gauge(CAMPAIGN_RATE_CI_LOW,
                  "Wilson 95% lower bound of the repro rate").set(ci[0])
        reg.gauge(CAMPAIGN_RATE_CI_HIGH,
                  "Wilson 95% upper bound of the repro rate").set(ci[1])
    if repros_per_hour is not None:
        reg.gauge(CAMPAIGN_REPROS_PER_HOUR,
                  "reproductions per hour of run time").set(
                      repros_per_hour)
    if eta_next_repro_s is not None:
        reg.gauge(CAMPAIGN_ETA_NEXT,
                  "forecast seconds of run time to the next repro",
                  ).set(eta_next_repro_s)
    if runs_to_ci is not None:
        reg.gauge(CAMPAIGN_RUNS_TO_CI,
                  "additional runs forecast to reach the target CI "
                  "width").set(runs_to_ci)
    if in_band is not None:
        reg.gauge(CAMPAIGN_IN_BAND,
                  "band SPRT verdict (1 = measured rate in the target "
                  "band, 0 = out of band)").set(in_band)
    if repros_per_hour_virtual is not None:
        reg.gauge(CAMPAIGN_REPROS_PER_HOUR_VIRTUAL,
                  "reproductions per hour of VIRTUAL run time "
                  "(fast-forwarded campaigns; wall-denominated "
                  "surfaces keep nmz_campaign_repros_per_hour)").set(
                      repros_per_hour_virtual)


def vclock_speedup(ratio: float) -> None:
    """One run's virtual/wall elapsed ratio (virtual-clock plane)."""
    if not metrics.enabled():
        return
    metrics.get().gauge(
        VCLOCK_SPEEDUP,
        "virtual elapsed / wall elapsed of the last virtual-clock run",
    ).set(ratio)


def vclock_pinned(seconds: float) -> None:
    """Wall seconds the pinning rule held the virtual clock at wall
    rate during the last run (accumulates across runs)."""
    if not metrics.enabled():
        return
    metrics.get().counter(
        VCLOCK_PINNED,
        "wall seconds the virtual clock spent pinned to wall rate "
        "(busy queues, running entities, real I/O)",
    ).inc(seconds)


def relation_coverage(scenario: str, covered: int, width: int,
                      one_sided: Optional[int] = None) -> None:
    """Publish one campaign's relation-coverage frontier (guidance
    plane, doc/search.md): bitmap occupancy in [0, 1] plus the count of
    one-sided relations still waiting for their flip (None = the
    caller's derivation doesn't track pair identities — leave that
    gauge untouched rather than faking a 0)."""
    if not metrics.enabled():
        return
    reg = metrics.get()
    reg.gauge(
        RELATION_COVERAGE,
        "relation-coverage bitmap occupancy of the campaign's "
        "guidance CoverageMap",
        ("scenario",),
    ).labels(scenario=scenario).set(
        covered / float(width) if width > 0 else 0.0)
    if one_sided is not None:
        reg.gauge(
            RELATION_ONE_SIDED,
            "directed ordering relations observed in one direction "
            "only (the guided search's mutation frontier)",
            ("scenario",),
        ).labels(scenario=scenario).set(one_sided)


def schedule_install(source: str) -> None:
    """A delay/fault table was installed on the policy hot path."""
    if not metrics.enabled():
        return
    metrics.get().counter(
        SEARCH_INSTALLS, "delay-table installs on the policy", ("source",),
    ).labels(source=source).inc()


def scorer_throughput(source: str, rate: float) -> None:
    """Jitted-scorer throughput sample (bench.py and the search plane
    publish through the same gauge so they can never disagree)."""
    if not metrics.enabled():
        return
    metrics.get().gauge(
        SCORER_THROUGHPUT,
        "schedules scored per second by the jitted scorer",
        ("source",),
    ).labels(source=source).set(rate)


def scorer_throughput_value(source: str) -> Optional[float]:
    return metrics.registry().value(SCORER_THROUGHPUT, source=source)


#: cached jax.profiler.TraceAnnotation class, resolved lazily so the
#: control plane never imports jax (policy/base.py's contract); False =
#: probed and unavailable (no-op fallback, e.g. CPU-only builds)
_trace_annotation_cls = None


def _trace_annotation(name: str):
    global _trace_annotation_cls
    cls = _trace_annotation_cls
    if cls is None:
        try:
            from jax.profiler import TraceAnnotation as cls
        except Exception:  # pragma: no cover - jax-less deployments
            cls = False
        _trace_annotation_cls = cls
    if cls is False:
        return contextlib.nullcontext()
    return cls(name)


@contextlib.contextmanager
def search_phase(phase: str):
    """Time one search-plane phase (ingest / evolve / extract / install
    / surrogate / host_io — the last is the fused loop's overlapped
    host-I/O lane, doc/performance.md) into
    ``nmz_search_phase_seconds{phase=...}`` and, when
    jax's profiler is importable, annotate the region into any active
    device profile via ``jax.profiler.TraceAnnotation`` (no-op without a
    profiler session, no-op fallback when jax is absent). Finer-grained
    in-step phases (mutate/score/select/migrate) are annotated with
    ``jax.named_scope`` inside the jitted island step
    (parallel/islands.py), where host-side timers cannot reach."""
    if not metrics.enabled():
        yield
        return
    t0 = time.perf_counter()
    try:
        with _trace_annotation(f"nmz:{phase}"):
            yield
    finally:
        metrics.get().histogram(
            SEARCH_PHASE,
            "wall time per search-plane phase",
            ("phase",),
        ).labels(phase=phase).observe(time.perf_counter() - t0)


def search_device_trace(path: str) -> None:
    """One completed ``jax.profiler`` device-trace capture dumped into
    ``path`` (the ``device_trace_dir`` knob, models/search.py): counted
    and stamped into the flight recorder so the trace directory
    correlates with the run that produced it."""
    if not metrics.enabled():
        return
    metrics.get().counter(
        SEARCH_DEVICE_TRACES,
        "completed jax.profiler device-trace captures").inc()
    from namazu_tpu.obs import recorder

    recorder.record_annotation("device_trace", path=str(path))


def sidecar_request(op: str, ok: bool) -> None:
    if not metrics.enabled():
        return
    metrics.get().counter(
        SIDECAR_REQUESTS, "search sidecar requests", ("op", "ok"),
    ).labels(op=op, ok=str(bool(ok)).lower()).inc()


# -- global failure-knowledge plane (doc/knowledge.md) -------------------

def knowledge_push(ok: bool, accepted: int = 0, duplicates: int = 0) -> None:
    """One pool_push round trip: entries the service newly stored vs
    content-keyed dedupe hits (the same signature already pooled)."""
    if not metrics.enabled():
        return
    reg = metrics.get()
    reg.counter(
        KNOWLEDGE_PUSHES, "knowledge-service pool_push requests", ("ok",),
    ).labels(ok=str(bool(ok)).lower()).inc()
    if duplicates > 0:
        reg.counter(
            KNOWLEDGE_DEDUPE,
            "pushed signatures the pool already held (content-keyed "
            "dedupe)",
        ).inc(duplicates)


def knowledge_pull(ok: bool) -> None:
    # pulled-entry VOLUME is deliberately not counted here: the entries
    # that matter (new to the pulling search) land in
    # nmz_knowledge_warmstart_installs_total{kind="archive"}
    if not metrics.enabled():
        return
    metrics.get().counter(
        KNOWLEDGE_PULLS, "knowledge-service pool_pull requests", ("ok",),
    ).labels(ok=str(bool(ok)).lower()).inc()


def knowledge_warmstart(kind: str, n: int = 1) -> None:
    """A cold run installed fleet knowledge: ``kind`` = what landed
    (``archive`` = pooled signatures folded into the failure archive,
    ``table`` = a scenario's best delay table installed on the hot
    path)."""
    if not metrics.enabled() or n <= 0:
        return
    metrics.get().counter(
        KNOWLEDGE_WARMSTART,
        "warm-start installs from the knowledge service", ("kind",),
    ).labels(kind=kind).inc(n)


def knowledge_surrogate_round() -> None:
    if not metrics.enabled():
        return
    metrics.get().counter(
        KNOWLEDGE_SURROGATE_ROUNDS,
        "shared-surrogate training rounds on the knowledge service",
    ).inc()


def knowledge_service_stats(tenants: int, pool_entries: int) -> None:
    """Service-side occupancy gauges (published on every handled op)."""
    if not metrics.enabled():
        return
    reg = metrics.get()
    reg.gauge(
        KNOWLEDGE_TENANTS,
        "distinct tenants the knowledge service has seen",
    ).set(tenants)
    reg.gauge(
        KNOWLEDGE_POOL,
        "failure signatures in the global knowledge pool",
    ).set(pool_entries)


def knowledge_fanin(inflight: int,
                    lock_wait_s: Optional[float] = None) -> None:
    """One request entering/leaving the knowledge service handler:
    ``inflight`` concurrent requests right now, plus (entry only) how
    long this one waited for the shared-state lock. A 3-host pool
    pushing concurrently should show lock waits in the microseconds —
    milliseconds here mean the fan-in is serializing again."""
    if not metrics.enabled():
        return
    reg = metrics.get()
    reg.gauge(
        KNOWLEDGE_FANIN_INFLIGHT,
        "requests currently inside the knowledge service handler",
    ).set(max(0, inflight))
    if lock_wait_s is not None:
        reg.histogram(
            KNOWLEDGE_FANIN_LOCK_WAIT,
            "knowledge-service shared-state lock acquisition wait",
        ).observe(max(0.0, lock_wait_s))


def knowledge_outage() -> None:
    """The knowledge service was unreachable/stale; the caller degraded
    to local-only search (an outage must never fail a campaign)."""
    if not metrics.enabled():
        return
    metrics.get().counter(
        KNOWLEDGE_OUTAGES,
        "knowledge-service outages degraded to local-only search",
    ).inc()


# -- triage plane (doc/observability.md "Triage") ------------------------

def table_propagation(seconds: Optional[float]) -> None:
    """One published table's search-install -> edge-adoption gap
    (publisher install stamp -> edge sync, same-host CLOCK_MONOTONIC;
    None/negative = the doc predates the stamp or crossed hosts —
    observe nothing rather than a fake 0)."""
    if seconds is None or seconds < 0.0 or not metrics.enabled():
        return
    metrics.get().histogram(
        TABLE_PROPAGATION,
        "delay-table search-install -> edge-decision propagation",
    ).observe(seconds)


def triage_probe(mode: str, n: int = 1) -> None:
    """Minimization probes by cost class: ``simulated`` = scored free
    through the guidance plane's predicted_gain, ``replayed`` = a real
    campaign-runner execution."""
    if not metrics.enabled() or n <= 0:
        return
    metrics.get().counter(
        TRIAGE_PROBES, "delta-debugging minimization probes", ("mode",),
    ).labels(mode=mode).inc(n)


def triage_minimized(ratio: float) -> None:
    """Size of the latest minimized reproducer relative to its
    candidate flip set (0 = everything shed, 1 = nothing shed)."""
    if not metrics.enabled():
        return
    metrics.get().gauge(
        TRIAGE_MINIMIZATION_RATIO,
        "latest minimization's minimal-flips / candidate-flips ratio",
    ).set(max(0.0, min(1.0, float(ratio))))


def triage_dossier_pull(ok: bool) -> None:
    """One dossier fetch against the knowledge wire (v3 triage_pull);
    ok = a dossier came back (miss and outage both count false)."""
    if not metrics.enabled():
        return
    metrics.get().counter(
        TRIAGE_DOSSIER_PULLS,
        "triage dossier pulls against the knowledge service", ("ok",),
    ).labels(ok=str(bool(ok)).lower()).inc()


def triage_signatures(n: int) -> None:
    """Distinct failure signatures this process holds dossiers for
    (the /fleet SIGS column's source gauge)."""
    if not metrics.enabled():
        return
    metrics.get().gauge(
        TRIAGE_SIGNATURES,
        "failure signatures with a local triage dossier",
    ).set(n)
