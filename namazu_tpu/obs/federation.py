"""Fleet telemetry federation: cross-process metrics push + one view.

PRs 4-8 deliberately broke the one-process observability assumption:
campaigns supervise N ``run`` children, edge dispatchers decide events
locally and reconcile via async backhaul, a uds endpoint serves
same-host inspectors, and the knowledge sidecar runs as its own
process. Each of those processes has its own PR 1 metrics registry —
and until this module, no single surface could answer "is the fleet
healthy, how stale are the edges, where is the latency going".

Three pieces (doc/observability.md "Fleet telemetry"):

* :class:`TelemetryRelay` — producer side. A background thread that
  walks the process registry every ``interval_s`` and pushes one
  ``nmz-telemetry-v1`` doc containing the samples that **changed since
  the last acknowledged push** (counters/histograms as absolute
  cumulatives — the aggregator derives monotonic deltas itself, so a
  replayed push whose ack was lost can never double-count; gauges as
  last-write). A failed push degrades to local-only metrics with ONE
  warning; the unsent samples simply remain changed-vs-acked and ride
  the next push — bounded by the series count, no queue to overflow.
  Pushes travel over the existing wires: ``POST /api/v3/telemetry`` on
  the REST endpoint, the ``telemetry`` op on the uds endpoint / the
  campaign supervisor's collector (the sidecar's framed-JSON codec).

* :class:`FleetAggregator` — consumer side, hosted by the orchestrator
  and/or the campaign supervisor. Merges pushes under ``(job,
  instance)`` keys with a per-instance ``seq`` watermark (replays and
  out-of-order duplicates are acked but not merged), evicts silent
  instances, caps post-merge label cardinality, feeds the SLO layer
  (obs/slo.py) with histogram bucket deltas, and serves the whole
  fleet as one document: ``GET /fleet`` (JSON, or ``?format=prom`` for
  a single Prometheus scrape covering every process) and ``nmz-tpu
  tools top``.

* **Federation hop** — a relay with an upstream target also forwards
  the foreign docs its local aggregator received (campaign ``run``
  children forward their inspectors' pushes to the supervisor), each
  doc keeping its own ``(job, instance, seq)`` identity so upstream
  dedupe still holds. The forward buffer is bounded; drops are counted
  (docs carry absolutes, so a dropped hop costs freshness, never
  correctness).

Cost contract: mirroring ``obs_enabled``, a disabled plane
(:func:`configure`, config key ``telemetry_enabled``) is one global
read — ``TelemetryRelay.start`` refuses to spawn its thread and no
seam touches the event hot path at all (the relay is the only moving
part, and it runs off-path at push cadence).
"""

from __future__ import annotations

import atexit
import json
import os
import socket as _socket
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from namazu_tpu import chaos
from namazu_tpu.obs import metrics, slo, spans
from namazu_tpu.utils.log import get_logger

log = get_logger("obs.federation")

__all__ = [
    "SCHEMA", "FLEET_SCHEMA", "TelemetryRelay", "FleetAggregator",
    "TelemetryServer", "default_instance", "pusher_for", "fetch",
    "handle_obs_op", "register_collector", "unregister_collector",
    "run_collectors",
    "configure", "enabled", "configure_from_config", "aggregator",
    "set_aggregator", "ensure_self_relay", "self_relay", "slo_summary",
    "reset",
]

SCHEMA = "nmz-telemetry-v1"
FLEET_SCHEMA = "nmz-fleet-v1"


def default_instance(prefix: str = "") -> str:
    """``[prefix.]pid@host`` — unique per producer process (a restart
    is a NEW instance, which is what makes absolute-cumulative merge
    semantics safe)."""
    base = f"{os.getpid()}@{_socket.gethostname()}"
    return f"{prefix}.{base}" if prefix else base


# -- producer side ---------------------------------------------------------

#: sampled-at-push-time gauges (edge table staleness age, parked-heap
#: depth): producers register a refresh callable instead of racing a
#: timer of their own — the relay runs them right before each encode,
#: so the pushed values are as fresh as the push itself
_collectors: List[Callable[[], None]] = []
_collectors_lock = threading.Lock()


def register_collector(fn: Callable[[], None]) -> None:
    with _collectors_lock:
        if fn not in _collectors:
            _collectors.append(fn)


def unregister_collector(fn: Callable[[], None]) -> None:
    with _collectors_lock:
        try:
            _collectors.remove(fn)
        except ValueError:
            pass


def run_collectors() -> None:
    """Refresh every registered sampled gauge (the relay's pre-encode
    hook; also callable directly before a local registry read)."""
    with _collectors_lock:
        fns = list(_collectors)
    for fn in fns:
        try:
            fn()
        except Exception:  # a gauge refresh must never kill a push
            log.debug("telemetry collector failed", exc_info=True)


class DeltaEncoder:
    """Change-tracking encoder over a metrics registry.

    Each :meth:`encode` returns the families whose samples changed
    since the last :meth:`mark_acked` — the "delta snapshot" on the
    wire. Sample VALUES are absolute cumulatives (bit-identical to the
    local registry); only the *selection* is differential, so an
    unacked sample is automatically re-sent with fresh values on the
    next cycle and a replay merges idempotently."""

    def __init__(self, registry=None) -> None:
        self._registry = registry
        self._acked: Dict[Tuple[str, Tuple[str, ...]], Any] = {}

    def _reg(self):
        return self._registry if self._registry is not None \
            else metrics.registry()

    def encode(self):
        """``(families, fingerprints)``: wire-form families holding the
        changed samples, and the fingerprint dict to pass to
        :meth:`mark_acked` once the push is acknowledged."""
        families: List[Dict[str, Any]] = []
        fps: Dict[Tuple[str, Tuple[str, ...]], Any] = {}
        for fam in self._reg().families():
            samples = []
            uppers: Optional[List[float]] = None
            for key, child in fam.items():
                skey = (fam.name, key)
                if isinstance(child, metrics.Histogram):
                    u, counts, s, n = child.raw_state()
                    uppers = list(u)
                    fp: Any = (n, s)
                    if self._acked.get(skey) == fp:
                        continue
                    samples.append({
                        "labels": dict(zip(fam.labelnames, key)),
                        "counts": counts, "sum": s, "count": n})
                else:
                    v = child.value
                    fp = v
                    if self._acked.get(skey) == fp:
                        continue
                    samples.append({
                        "labels": dict(zip(fam.labelnames, key)),
                        "value": v})
                fps[skey] = fp
            if samples:
                fdoc = {"name": fam.name, "type": fam.cls.KIND,
                        "help": fam.help,
                        "labelnames": list(fam.labelnames),
                        "samples": samples}
                if uppers is not None:
                    fdoc["uppers"] = uppers
                families.append(fdoc)
        return families, fps

    def mark_acked(self, fps: Dict) -> None:
        self._acked.update(fps)

    def reset(self) -> None:
        """Forget every ack: the next encode re-sends full state
        (absolutes merge idempotently, so a full resend is always
        safe)."""
        self._acked.clear()


class TelemetryRelay:
    """One producer's push loop; see the module header for semantics.

    ``push`` is any callable ``doc -> ack_dict`` that raises on failure
    (a transceiver's ``push_telemetry``, :func:`pusher_for`'s client);
    ``local`` is a :class:`FleetAggregator` merged synchronously (the
    orchestrator's self-relay feeds its own ``/fleet`` this way);
    ``forward_source`` enables the federation hop."""

    def __init__(self, job: str, instance: Optional[str] = None,
                 push: Optional[Callable[[dict], Any]] = None,
                 local: Optional["FleetAggregator"] = None,
                 interval_s: float = 2.0, registry=None,
                 forward_source: Optional["FleetAggregator"] = None,
                 target_desc: str = "") -> None:
        self.job = str(job)
        self.instance = instance or default_instance()
        self.interval_s = max(0.05, float(interval_s))
        self.local = local
        self._push = push
        self._target_desc = target_desc or "upstream"
        self.forward_source = forward_source
        if forward_source is not None and push is not None:
            forward_source.enable_forwarding()
        self._encoder = DeltaEncoder(registry)
        # profile delta (obs/profiling.py): lazily bound so a process
        # without a profiler pays one None check per cycle
        self._prof_delta = None
        self._seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._cycle_lock = threading.Lock()
        self._warned = False

    def set_upstream(self, push: Callable[[dict], Any],
                     forward_source: Optional["FleetAggregator"] = None,
                     target_desc: str = "") -> None:
        """Late-bind an upstream target (the single process-global
        self-relay may learn its push url after creation)."""
        # under the cycle lock: an in-flight push-less cycle must not
        # mark_acked into the freshly-reset encoder (that would record
        # series as delivered that the new upstream never saw)
        with self._cycle_lock:
            self._push = push
            # every sample acked during the push-less era was acked
            # LOCALLY only — the new upstream has never seen any of
            # it, so the next cycle must re-send full state (quiescent
            # series would otherwise stay invisible upstream forever)
            self._encoder.reset()
            if self._prof_delta is not None:
                self._prof_delta.reset()
            if target_desc:
                self._target_desc = target_desc
            if forward_source is not None:
                self.forward_source = forward_source
                forward_source.enable_forwarding()

    def start(self) -> "TelemetryRelay":
        if not enabled():
            return self  # disabled plane: no thread, no cost
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name=f"telemetry-{self.job}",
                daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        # push IMMEDIATELY on start: short-lived producers (a 2-second
        # `run` child) must appear in the fleet view at all
        while True:
            self.flush()
            if self._stop.wait(self.interval_s):
                return

    def flush(self) -> None:
        """One push cycle NOW; never raises (a telemetry failure must
        never reach inspector/policy/campaign code — the knowledge-
        client cooldown contract, doc/knowledge.md)."""
        try:
            with self._cycle_lock:
                self._cycle()
        except Exception:  # pragma: no cover - defensive
            log.debug("telemetry cycle failed", exc_info=True)

    def _profile_delta(self):
        """Encode the profiler's changed stacks (obs/profiling.py) —
        same differential-selection contract as the metric encoder:
        absolutes on the wire, fingerprints advance only on ack."""
        from namazu_tpu.obs import profiling

        prof = profiling.profiler()
        if prof is None:
            return None, {}
        enc = self._prof_delta
        if enc is None or enc._prof is not prof:
            enc = self._prof_delta = profiling.ProfileDelta(prof)
        return enc.encode()

    def _cycle(self) -> None:
        families: List[dict] = []
        fps: Dict = {}
        prof_payload, prof_fps = None, {}
        if metrics.enabled():
            run_collectors()
            families, fps = self._encoder.encode()
            prof_payload, prof_fps = self._profile_delta()
        self._seq += 1
        doc = {"schema": SCHEMA, "job": self.job,
               "instance": self.instance, "seq": self._seq,
               "interval_s": self.interval_s, "families": families}
        if prof_payload is not None:
            doc["profile"] = prof_payload
        if metrics.enabled():
            # causality plane (obs/context.py): stamp the push so the
            # aggregator's logical clock merges every producer's —
            # federation hops keep the original producer's stamp
            from namazu_tpu.obs import context as _context

            doc["ctx"] = _context.wire_stamp()
        if self.local is not None:
            try:
                # forward=False: our own doc must not land in the
                # forward buffer we ourselves drain — it already goes
                # upstream first-hand below
                self.local.note_push(doc, forward=False)
            except Exception:
                log.debug("local telemetry merge failed", exc_info=True)
        if self._push is None:
            self._encoder.mark_acked(fps)
            if self._prof_delta is not None:
                self._prof_delta.mark_acked(prof_fps)
            return
        try:
            # chaos seam (doc/robustness.md): a dropped push must
            # degrade exactly like a dead collector
            if chaos.decide("telemetry.push.drop") is not None:
                raise OSError("chaos: telemetry push dropped")
            self._push(doc)
        except Exception as e:
            spans.telemetry_push(False)
            if not self._warned:
                self._warned = True
                log.warning(
                    "telemetry push to %s failed (%s); metrics stay "
                    "local-only and unsent samples ride the next push "
                    "(bounded — never an error into host code)",
                    self._target_desc, e)
            else:
                log.debug("telemetry push still failing: %s", e)
            return
        self._warned = False
        self._encoder.mark_acked(fps)
        if self._prof_delta is not None:
            self._prof_delta.mark_acked(prof_fps)
        spans.telemetry_push(True)
        src = self.forward_source
        if src is not None:
            docs = src.drain_forward()
            for i, fdoc in enumerate(docs):
                try:
                    self._push(fdoc)
                except Exception as e:
                    # requeue EVERY undelivered doc, in order (the cap
                    # inside requeue_forward counts any overflow) — a
                    # failed hop must never silently discard the rest
                    # of the drained buffer
                    for d in reversed(docs[i:]):
                        src.requeue_forward(d)
                    log.debug("telemetry forward failed (%s); %d "
                              "doc(s) re-queued", e, len(docs) - i)
                    break

    def shutdown(self) -> None:
        """Stop the loop and perform one final flush so a producer's
        last interval of samples reaches the fleet before exit."""
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        self.flush()


# -- consumer side ---------------------------------------------------------

class _FamilyState:
    __slots__ = ("type", "help", "labelnames", "uppers", "samples",
                 "alt")

    def __init__(self, typ: str, help: str, labelnames: Tuple[str, ...],
                 uppers: Optional[List[float]]) -> None:
        self.type = typ
        self.help = help
        self.labelnames = labelnames
        self.uppers = uppers
        #: labelkey tuple -> float (counter/gauge) or
        #: (raw counts, sum, count) (histogram)
        self.samples: "OrderedDict[Tuple[str, ...], Any]" = OrderedDict()
        #: mixed-bucket-layout segregation (doc/observability.md):
        #: a push whose histogram layout differs from the first-seen
        #: one lands here, keyed by its uppers tuple — warned about and
        #: counted, NEVER blended into the primary samples (quantiles
        #: over mixed layouts would be fiction). Lazy: None until a
        #: mismatch actually happens.
        self.alt: Optional[Dict[Tuple[float, ...],
                                "OrderedDict[Tuple[str, ...], Any]"]] \
            = None


class _InstanceState:
    __slots__ = ("job", "instance", "last_seq", "last_seen", "first_seen",
                 "interval_s", "pushes", "duplicates", "families",
                 "rates", "run_rates", "profile")

    def __init__(self, job: str, instance: str, now: float) -> None:
        self.job = job
        self.instance = instance
        self.last_seq = 0
        self.last_seen = now
        self.first_seen = now
        self.interval_s = 2.0
        self.pushes = 0
        self.duplicates = 0
        self.families: Dict[str, _FamilyState] = {}
        #: profiling plane (obs/profiling.py): last-write absolute
        #: collapsed-stack counts from the instance's profile deltas,
        #: or None for producers without a profiler
        self.profile: Optional[Dict[str, Any]] = None
        #: counter name -> (t, total, rate) for the summary rates
        self.rates: Dict[str, Tuple[float, float, Optional[float]]] = {}
        #: tenancy plane: run namespace -> (t, total, rate) derived
        #: from nmz_tenancy_events_total{run} (the /fleet RUN rows)
        self.run_rates: Dict[str, Tuple[float, float,
                                        Optional[float]]] = {}


class FleetAggregator:
    """Merge point for telemetry pushes; see the module header."""

    #: distinct label-value series admitted per (instance, family)
    #: AFTER the merge — the producer-side entity cap (spans.py) is the
    #: primary defense, this is the aggregator's own bound against a
    #: misbehaving producer
    MAX_SAMPLES_PER_FAMILY = 128
    #: federation-hop buffer bound (docs, not samples)
    FORWARD_CAP = 256
    #: distinct collapsed stacks held per instance's profile state
    MAX_PROFILE_STACKS = 1024
    #: counters whose per-instance rate the summary derives
    RATE_COUNTERS = (spans.EVENTS_INTERCEPTED, spans.EDGE_DECISIONS)

    def __init__(self, stale_after_s: float = 0.0,
                 evict_after_s: float = 120.0) -> None:
        #: 0 = auto: max(5s, 3x the instance's own push interval)
        self.stale_after_s = max(0.0, float(stale_after_s))
        self.evict_after_s = max(0.0, float(evict_after_s))
        self._lock = threading.Lock()
        self._instances: "OrderedDict[Tuple[str, str], _InstanceState]" \
            = OrderedDict()
        self._forward: deque = deque()
        self._forwarding = False
        self._forward_dropped = 0
        self._series_folded = 0
        self._layouts_segregated = 0
        self._layout_warned: set = set()
        self._slo = slo.SLOEvaluator(slo.DEFAULT_SLOS, explicit=False)
        self._last_slo_eval = 0.0

    # -- configuration ----------------------------------------------------

    def set_slos(self, specs, explicit: bool = True) -> None:
        self._slo = slo.SLOEvaluator(specs, explicit=explicit)

    @property
    def slo_evaluator(self) -> slo.SLOEvaluator:
        return self._slo

    def enable_forwarding(self) -> None:
        self._forwarding = True

    # -- ingest -----------------------------------------------------------

    def note_push(self, doc: Any, forward: bool = True,
                  now: Optional[float] = None) -> Dict[str, Any]:
        """Merge one telemetry doc; returns the ack. Raises ValueError
        on a malformed doc (the wire surfaces turn that into a 400 /
        ``ok: false``)."""
        if not isinstance(doc, dict) or doc.get("schema") != SCHEMA:
            raise ValueError(f"telemetry doc must carry schema "
                             f"{SCHEMA!r}")
        job = str(doc.get("job") or "")
        instance = str(doc.get("instance") or "")
        if not job or not instance:
            raise ValueError("telemetry doc needs job + instance")
        try:
            seq = int(doc.get("seq"))
        except (TypeError, ValueError):
            raise ValueError("telemetry doc needs an integer seq") \
                from None
        now = time.monotonic() if now is None else now
        if metrics.enabled():
            # merge the producer's logical clock (obs/context.py) —
            # the aggregator is a receive point like any other wire
            from namazu_tpu.obs import context as _context

            _context.observe_wire(doc.get("ctx"))
        hist_deltas: List[Tuple[str, List[float], List[int]]] = []
        with self._lock:
            st = self._instances.get((job, instance))
            if st is None:
                st = self._instances[(job, instance)] = \
                    _InstanceState(job, instance, now)
            st.last_seen = now
            try:
                st.interval_s = float(doc.get("interval_s")
                                      or st.interval_s)
            except (TypeError, ValueError):
                pass
            if seq <= st.last_seq:
                # replay of a push whose ack was lost (or an out-of-
                # order duplicate): acknowledge WITHOUT merging — this
                # is the exactly-once half of the idempotence contract
                st.duplicates += 1
                return {"ok": True, "duplicate": True,
                        "last_seq": st.last_seq}
            st.last_seq = seq
            st.pushes += 1
            self._merge(st, doc.get("families") or [], hist_deltas)
            self._merge_profile(st, doc.get("profile"))
            self._update_rates(st, now)
            # evict on INGEST too, not only when /fleet is read: an
            # unattended aggregator (a supervisor nobody scrapes) must
            # not accumulate one dead pid-scoped instance per finished
            # run child forever
            self._evict(now)
            dropped = 0
            if forward and self._forwarding:
                self._forward.append(doc)
                while len(self._forward) > self.FORWARD_CAP:
                    self._forward.popleft()
                    dropped += 1
                if dropped:
                    self._forward_dropped += dropped
            n_instances = len(self._instances)
        # SLO windows + gauges outside the lock: they must never block
        # a concurrent producer's merge
        if dropped:
            spans.telemetry_forward_dropped(dropped)
        for metric, uppers, deltas in hist_deltas:
            self._slo.note_hist_delta(metric, uppers, deltas, now)
        # evaluate on INGEST (throttled): burn gauges, breach
        # transitions, and the flight-recorder annotation must fire
        # even in a deployment nobody reads over JSON — a Prometheus-
        # only scrape (or no scrape at all) would otherwise leave the
        # SLO plane silently green while objectives burn
        if now - self._last_slo_eval >= 1.0:
            self._last_slo_eval = now
            self._slo.evaluate(self.max_gauge, now)
        spans.fleet_occupancy(n_instances, self._stale_count(now))
        return {"ok": True, "last_seq": seq}

    def _merge(self, st: _InstanceState, families: List[Any],
               hist_deltas: List) -> None:
        """Merge one doc's families into ``st`` (caller holds the
        lock). Absolute-cumulative semantics: counters/histograms
        last-write their instance's cumulative state (the producer is
        monotonic per instance — a pid is part of the instance key),
        gauges last-write by definition. Bucket deltas vs the stored
        previous state are computed here for the SLO layer."""
        for f in families:
            if not isinstance(f, dict):
                continue
            name = str(f.get("name") or "")
            if not name:
                continue
            labelnames = tuple(str(n) for n in f.get("labelnames") or ())
            uppers = f.get("uppers")
            try:
                inc_uppers = ([float(u) for u in uppers]
                              if uppers else None)
            except (TypeError, ValueError):
                inc_uppers = None
            fs = st.families.get(name)
            if fs is None:
                fs = st.families[name] = _FamilyState(
                    str(f.get("type") or "gauge"),
                    str(f.get("help") or ""), labelnames, inc_uppers)
            # mixed bucket layouts (a fleet mid-rollout: old producers
            # on the pre-sub-ms nmz_event_stage_seconds bounds replay
            # through a forward hop into an instance slot that already
            # saw the new layout): WARN AND SEGREGATE — the layout's
            # samples are kept in a side table keyed by its uppers,
            # counted in /fleet as hist_layouts_segregated, and never
            # blended into primary quantiles
            alt_samples = None
            if (fs.type == "histogram" and fs.uppers is not None
                    and inc_uppers is not None
                    and inc_uppers != fs.uppers):
                wkey = (st.job, st.instance, name)
                if wkey not in self._layout_warned:
                    self._layout_warned.add(wkey)
                    log.warning(
                        "telemetry: %s/%s pushed %s with a different "
                        "bucket layout (%d vs %d bounds); segregating "
                        "— mixed layouts are never blended into one "
                        "quantile", st.job, st.instance, name,
                        len(inc_uppers), len(fs.uppers))
                if fs.alt is None:
                    fs.alt = {}
                alt_samples = fs.alt.setdefault(
                    tuple(inc_uppers), OrderedDict())
            watched = fs.type == "histogram" \
                and self._slo.watches(name) and fs.uppers \
                and alt_samples is None
            fam_delta = [0] * (len(fs.uppers) + 1) if watched else None
            for s in f.get("samples") or []:
                if not isinstance(s, dict):
                    continue
                labels = s.get("labels") or {}
                key = tuple(str(labels.get(n, ""))
                            for n in fs.labelnames)
                existing = fs.samples.get(key)
                if existing is None \
                        and len(fs.samples) >= self.MAX_SAMPLES_PER_FAMILY:
                    # post-merge cardinality cap: the sample is dropped
                    # and COUNTED — a fold that silently summed
                    # absolutes from different series would double-
                    # count on every push
                    self._series_folded += 1
                    continue
                if fs.type == "histogram":
                    try:
                        counts = [int(c) for c in s.get("counts") or []]
                        hsum = float(s.get("sum", 0.0))
                        hcount = int(s.get("count", 0))
                    except (TypeError, ValueError):
                        continue
                    if alt_samples is not None:
                        # segregated layout: last-write into its own
                        # side table, never the primary samples
                        if len(counts) == len(inc_uppers) + 1:
                            if key not in alt_samples:
                                self._layouts_segregated += 1
                            alt_samples[key] = (counts, hsum, hcount)
                        continue
                    if fs.uppers is None \
                            or len(counts) != len(fs.uppers) + 1:
                        # shape mismatch without a declared layout:
                        # still warn-and-count, never silently vanish
                        wkey = (st.job, st.instance, name)
                        if wkey not in self._layout_warned:
                            self._layout_warned.add(wkey)
                            log.warning(
                                "telemetry: %s/%s pushed %s with "
                                "%d bucket counts against %s bounds; "
                                "sample segregated (counted, not "
                                "merged)", st.job, st.instance, name,
                                len(counts),
                                "no" if fs.uppers is None
                                else str(len(fs.uppers)))
                        self._layouts_segregated += 1
                        continue
                    if fam_delta is not None:
                        prev = existing[0] if existing else [0] * len(counts)
                        for i, c in enumerate(counts):
                            # clamp: a producer-side registry reset
                            # shows as a regressed cumulative
                            fam_delta[i] += max(0, c - prev[i])
                    fs.samples[key] = (counts, hsum, hcount)
                else:
                    try:
                        fs.samples[key] = float(s.get("value", 0.0))
                    except (TypeError, ValueError):
                        continue
            if fam_delta is not None and any(fam_delta):
                hist_deltas.append((name, fs.uppers, fam_delta))

    def _merge_profile(self, st: _InstanceState, prof: Any) -> None:
        """Merge one push's profile delta (obs/profiling.py wire
        payload; caller holds the lock). Same absolute-cumulative
        last-write semantics as counters — a full resend after a lost
        ack merges idempotently, and the seq watermark upstream already
        discarded duplicate docs."""
        if not isinstance(prof, dict) \
                or not isinstance(prof.get("stacks"), list):
            return
        pstate = st.profile
        if pstate is None:
            pstate = st.profile = {"stacks": OrderedDict(),
                                   "samples_total": 0, "dropped": 0,
                                   "interval_s": 0.01}
        stacks = pstate["stacks"]
        for s in prof["stacks"]:
            if not isinstance(s, dict):
                continue
            try:
                key = (str(s.get("plane") or "other"),
                       tuple(str(x) for x in s.get("stack") or ()))
                cnt = int(s.get("count", 0))
            except (TypeError, ValueError):
                continue
            if not key[1]:
                continue
            if key not in stacks \
                    and len(stacks) >= self.MAX_PROFILE_STACKS:
                continue
            stacks[key] = cnt
        try:
            pstate["samples_total"] = int(
                prof.get("samples_total", pstate["samples_total"]))
            pstate["dropped"] = int(
                prof.get("dropped", pstate["dropped"]))
            pstate["interval_s"] = float(
                prof.get("interval_s", pstate["interval_s"]))
        except (TypeError, ValueError):
            pass

    def _profile_top(self, st: _InstanceState
                     ) -> Optional[Tuple[str, float]]:
        """Dominant self-time frame of an instance's merged profile
        (leaf with the most samples) — the /fleet PROF column (caller
        holds the lock)."""
        p = st.profile
        if not p or not p["stacks"]:
            return None
        selfs: Dict[str, int] = {}
        for (_plane, stack), c in p["stacks"].items():
            leaf = stack[-1]
            selfs[leaf] = selfs.get(leaf, 0) + c
        total = sum(selfs.values())
        if total <= 0:
            return None
        frame, cnt = max(selfs.items(), key=lambda kv: kv[1])
        return frame, cnt / total

    def _update_rates(self, st: _InstanceState, now: float) -> None:
        for name in self.RATE_COUNTERS:
            fs = st.families.get(name)
            if fs is None or fs.type != "counter":
                continue
            total = sum(v for v in fs.samples.values()
                        if isinstance(v, float))
            prev = st.rates.get(name)
            rate: Optional[float] = None
            if prev is not None and now > prev[0]:
                # floor the denominator at half the push interval: a
                # drained forward backlog merges queued docs ms apart,
                # and dividing each doc's interval-worth of delta by
                # that gap would report absurd rates (the floor bounds
                # the overshoot at ~2x until the next steady push)
                dt = max(now - prev[0], 0.5 * st.interval_s)
                rate = max(0.0, total - prev[1]) / dt
            elif prev is not None:
                rate = prev[2]
            st.rates[name] = (now, total, rate)
        # per-run-namespace rates (tenancy plane): same derivation,
        # one series per `run` label value
        by_run = self._counter_by(st, spans.TENANCY_EVENTS, "run")
        for run, total in by_run.items():
            prev = st.run_rates.get(run)
            rate = None
            if prev is not None and now > prev[0]:
                dt = max(now - prev[0], 0.5 * st.interval_s)
                rate = max(0.0, total - prev[1]) / dt
            elif prev is not None:
                rate = prev[2]
            st.run_rates[run] = (now, total, rate)
        # runs that vanished from the push (released/reclaimed
        # namespaces) drop their stale rate rows
        for run in [r for r in st.run_rates if r not in by_run]:
            del st.run_rates[run]

    # -- federation hop ---------------------------------------------------

    def drain_forward(self) -> List[dict]:
        with self._lock:
            docs, self._forward = list(self._forward), deque()
        return docs

    def requeue_forward(self, doc: dict) -> None:
        with self._lock:
            self._forward.appendleft(doc)
            dropped = 0
            while len(self._forward) > self.FORWARD_CAP:
                # evict the OLDEST doc (the left end, where requeues
                # land) — same freshness-first rule as the ingest-path
                # overflow; dropping the right end would discard the
                # newest arrivals in favor of superseded snapshots
                self._forward.popleft()
                dropped += 1
            if dropped:
                self._forward_dropped += dropped
        if dropped:
            spans.telemetry_forward_dropped(dropped)

    # -- read side --------------------------------------------------------

    def _stale_after(self, st: _InstanceState) -> float:
        if self.stale_after_s > 0:
            return self.stale_after_s
        return max(5.0, 3.0 * st.interval_s)

    def _stale_count(self, now: float) -> int:
        with self._lock:
            return sum(1 for st in self._instances.values()
                       if now - st.last_seen > self._stale_after(st))

    def _counter_total(self, st: _InstanceState,
                       name: str) -> Optional[float]:
        fs = st.families.get(name)
        if fs is None:
            return None
        return sum(v for v in fs.samples.values()
                   if isinstance(v, float))

    def _counter_by(self, st: _InstanceState, name: str,
                    label: str) -> Dict[str, float]:
        """Per-label-value totals of one counter family (the codec
        byte ledger's ``nmz_wire_bytes_total{codec}`` read), merged
        across the family's other labels."""
        fs = st.families.get(name)
        if fs is None:
            return {}
        try:
            idx = fs.labelnames.index(label)
        except ValueError:
            return {}
        out: Dict[str, float] = {}
        for key, v in fs.samples.items():
            if isinstance(v, float):
                out[key[idx]] = out.get(key[idx], 0.0) + v
        return out

    def _gauge_max(self, st: _InstanceState,
                   name: str) -> Optional[float]:
        fs = st.families.get(name)
        if fs is None or not fs.samples:
            return None
        vals = [v for v in fs.samples.values() if isinstance(v, float)]
        return max(vals) if vals else None

    def _gauge_sum(self, st: _InstanceState,
                   name: str) -> Optional[float]:
        """For additive per-entity gauges (parked-heap depth): an
        instance running 4 edges with 100 parked each holds 400, not
        100 — max is only right for worst-of gauges (staleness,
        version)."""
        fs = st.families.get(name)
        if fs is None or not fs.samples:
            return None
        vals = [v for v in fs.samples.values() if isinstance(v, float)]
        return sum(vals) if vals else None

    def _runs_section(self, st: _InstanceState) -> Dict[str, Any]:
        """``{"runs": {run: {...}}}`` for one instance, or ``{}`` when
        it serves no tenant namespaces (caller holds the lock)."""
        totals = self._counter_by(st, spans.TENANCY_EVENTS, "run")
        if not totals:
            return {}
        parked = self._counter_by(st, spans.TENANCY_PARKED, "run")
        out: Dict[str, Any] = {}
        for run, total in sorted(totals.items()):
            rate = st.run_rates.get(run, (0, 0, None))[2]
            out[run] = {
                "events_total": round(total),
                "events_per_sec": (round(rate, 1)
                                   if rate is not None else None),
                "parked": round(parked.get(run, 0)),
            }
        return {"runs": out}

    def _hist_quantile(self, st: _InstanceState, name: str,
                       q: float) -> Optional[float]:
        fs = st.families.get(name)
        if fs is None or fs.type != "histogram" or fs.uppers is None:
            return None
        merged = [0] * (len(fs.uppers) + 1)
        for v in fs.samples.values():
            counts = v[0]
            for i, c in enumerate(counts):
                merged[i] += c
        total = sum(merged)
        if total <= 0:
            return None
        target = q * total
        acc = 0
        for i, c in enumerate(merged):
            acc += c
            if acc >= target:
                # the +Inf overflow reports the highest finite bound
                # (the Prometheus histogram_quantile convention)
                return fs.uppers[min(i, len(fs.uppers) - 1)]
        return fs.uppers[-1]

    def _hist_quantile_by(self, st: _InstanceState, name: str,
                          label: str, q: float) -> Dict[str, float]:
        """Per-label-value quantiles of one histogram family (the
        causality plane's ``nmz_event_stage_seconds{stage}`` read):
        label value -> q-quantile upper bound, merged across the
        family's other labels."""
        fs = st.families.get(name)
        if fs is None or fs.type != "histogram" or fs.uppers is None:
            return {}
        try:
            idx = fs.labelnames.index(label)
        except ValueError:
            return {}
        merged: Dict[str, List[int]] = {}
        for key, v in fs.samples.items():
            counts = v[0]
            acc = merged.setdefault(key[idx],
                                    [0] * (len(fs.uppers) + 1))
            for i, c in enumerate(counts):
                acc[i] += c
        out: Dict[str, float] = {}
        for value, counts in merged.items():
            total = sum(counts)
            if total <= 0:
                continue
            target = q * total
            acc = 0
            for i, c in enumerate(counts):
                acc += c
                if acc >= target:
                    out[value] = fs.uppers[min(i, len(fs.uppers) - 1)]
                    break
            else:  # pragma: no cover - defensive
                out[value] = fs.uppers[-1]
        return out

    def max_gauge(self, name: str) -> Optional[float]:
        """Fleet-wide max of a gauge (the staleness-SLO resolver)."""
        best: Optional[float] = None
        with self._lock:
            for st in self._instances.values():
                v = self._gauge_max(st, name)
                if v is not None and (best is None or v > best):
                    best = v
        return best

    def _evict(self, now: float) -> None:
        """Drop instances silent past the eviction window (caller
        holds the lock). Staleness is surfaced first — /fleet marks an
        instance stale instead of serving frozen numbers, then forgets
        it entirely."""
        if self.evict_after_s <= 0:
            return
        dead = [key for key, st in self._instances.items()
                if now - st.last_seen > self.evict_after_s]
        for key in dead:
            del self._instances[key]

    def payload(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``/fleet`` JSON document."""
        now = time.monotonic() if now is None else now
        rows: List[Dict[str, Any]] = []
        # rows are built UNDER the lock: the per-family sample dicts
        # mutate on every concurrent push, and iterating them outside
        # would intermittently raise mid-read exactly when the fleet is
        # growing
        with self._lock:
            self._evict(now)
            snapshot = list(self._instances.values())
            fleet_version = 0.0
            for st in snapshot:
                for name in (spans.TABLE_VERSION,
                             spans.EDGE_TABLE_VERSION_HELD):
                    v = self._gauge_max(st, name)
                    if v is not None and v > fleet_version:
                        fleet_version = v
            stale_n = 0
            for st in snapshot:
                age = now - st.last_seen
                stale = age > self._stale_after(st)
                stale_n += stale
                version = self._gauge_max(st, spans.TABLE_VERSION)
                edge_version = self._gauge_max(
                    st, spans.EDGE_TABLE_VERSION_HELD)
                held = (edge_version if edge_version is not None
                        else version)
                ev_rate = st.rates.get(spans.EVENTS_INTERCEPTED,
                                       (0, 0, None))[2]
                prof_top = self._profile_top(st)
                rows.append({
                    "job": st.job,
                    "instance": st.instance,
                    "seq": st.last_seq,
                    "pushes": st.pushes,
                    "duplicate_pushes": st.duplicates,
                    "interval_s": st.interval_s,
                    "last_seen_age_s": round(age, 3),
                    "stale": stale,
                    "events_per_sec": (round(ev_rate, 1)
                                       if ev_rate is not None else None),
                    "events_total": self._counter_total(
                        st, spans.EVENTS_INTERCEPTED),
                    "edge_decisions_total": self._counter_total(
                        st, spans.EDGE_DECISIONS),
                    "queue_dwell_p99_s": self._hist_quantile(
                        st, spans.QUEUE_DWELL, 0.99),
                    "dispatch_p99_s": self._hist_quantile(
                        st, spans.EVENT_E2E, 0.99),
                    "backhaul_lag_p99_s": self._hist_quantile(
                        st, spans.EDGE_BACKHAUL_LAG, 0.99),
                    # per-lifecycle-segment p99s (queue/decision/
                    # parking/dispatch/wire/edge_parking/backhaul) —
                    # the causality plane's "where does the
                    # millisecond go", federated (obs/causality.py)
                    "stage_p99_s": self._hist_quantile_by(
                        st, spans.EVENT_STAGE, "stage", 0.99),
                    # the negotiated-codec byte ledger
                    # (nmz_wire_bytes_total{codec}): what this
                    # instance's wires actually moved, by codec — the
                    # tools-top CODEC column and the /fleet face of the
                    # JSON-vs-binary savings (doc/performance.md)
                    "wire_bytes_by_codec": {
                        k: round(v) for k, v in self._counter_by(
                            st, spans.WIRE_BYTES, "codec").items()},
                    "table_version": held,
                    "table_skew": (round(fleet_version - held)
                                   if held is not None else None),
                    # SKEW's time-domain twin: the measured publish->
                    # edge-install propagation p99
                    # (nmz_table_propagation_seconds, obs/spans.py)
                    "table_propagation_p99_s": self._hist_quantile(
                        st, spans.TABLE_PROPAGATION, 0.99),
                    # triage plane: distinct failure signatures this
                    # instance holds a dossier for (the tools-top SIGS
                    # column; doc/observability.md "Triage")
                    "triage_signatures": self._gauge_max(
                        st, spans.TRIAGE_SIGNATURES),
                    # campaign progress plane (obs/stats.py via the
                    # supervisor's per-slot publication): measured
                    # repro rate, pace, next-repro ETA, and the band
                    # SPRT verdict — the tools-top RATE/ETA columns
                    "repro_rate": self._gauge_max(
                        st, spans.CAMPAIGN_RATE),
                    "repros_per_hour": self._gauge_max(
                        st, spans.CAMPAIGN_REPROS_PER_HOUR),
                    # the virtual-clock twin (None on wall campaigns):
                    # same pace formula over VIRTUAL elapsed — shown
                    # beside the wall rate, never in place of it
                    "repros_per_hour_virtual": self._gauge_max(
                        st, spans.CAMPAIGN_REPROS_PER_HOUR_VIRTUAL),
                    "vclock_speedup": self._gauge_max(
                        st, spans.VCLOCK_SPEEDUP),
                    "eta_next_repro_s": self._gauge_max(
                        st, spans.CAMPAIGN_ETA_NEXT),
                    "campaign_in_band": self._gauge_max(
                        st, spans.CAMPAIGN_IN_BAND),
                    "edge_table_staleness_s": self._gauge_max(
                        st, spans.EDGE_TABLE_STALENESS),
                    "edge_parked": self._gauge_sum(
                        st, spans.EDGE_PARKED),
                    # profiling plane (obs/profiling.py): the
                    # instance's dominant self-time frame and its share
                    # of all self samples — the tools-top PROF column
                    "prof_top_frame": (prof_top[0] if prof_top
                                       else None),
                    "prof_top_share": (round(prof_top[1], 4)
                                       if prof_top else None),
                    # tenancy plane (doc/tenancy.md): one row per run
                    # namespace this instance serves — events, rate,
                    # and parked depth per tenant, the `tools top` RUN
                    # table. Instances without tenancy metrics carry
                    # no key (pre-tenancy payload shape preserved).
                    **self._runs_section(st),
                })
        rows.sort(key=lambda r: (r["job"], r["instance"]))
        spans.fleet_occupancy(len(rows), stale_n)
        return {
            "schema": FLEET_SCHEMA,
            "instance_count": len(rows),
            "stale_instances": stale_n,
            "fleet_table_version": fleet_version,
            "series_folded": self._series_folded,
            "forward_dropped": self._forward_dropped,
            "hist_layouts_segregated": self._layouts_segregated,
            "instances": rows,
            "slo": {
                "explicit": self._slo.explicit,
                "objectives": self._slo.evaluate(self.max_gauge, now),
            },
        }

    def slo_summary(self) -> Optional[Dict[str, Any]]:
        """The analytics fold (obs/analytics.payload): only EXPLICIT
        objectives — fleets that never declared SLOs keep a payload
        byte-identical to ``compute_payload`` (the REST-vs-CLI parity
        the analytics tests pin)."""
        if not self._slo.explicit:
            return None
        return {"objectives": self._slo.evaluate(self.max_gauge)}

    def prometheus(self) -> str:
        """Every merged sample as one Prometheus text exposition, with
        ``job``/``instance`` labels injected — one scrape covers the
        whole fleet."""
        esc = metrics._escape_label_value
        fmt = metrics._format_value
        # a prom-only deployment's scrape cadence drives SLO
        # evaluation too (fresh nmz_slo_burn in the host registry,
        # breach transitions), same as the JSON payload() path
        self._slo.evaluate(self.max_gauge)
        # sample dicts are copied UNDER the lock (the stored values —
        # floats and already-replaced-wholesale histogram tuples — are
        # never mutated in place, so a shallow copy is a consistent
        # snapshot); rendering then happens lock-free
        with self._lock:
            snapshot = []
            for st in self._instances.values():
                copies = {}
                for name, fs in st.families.items():
                    c = _FamilyState(fs.type, fs.help, fs.labelnames,
                                     fs.uppers)
                    c.samples = OrderedDict(fs.samples)
                    copies[name] = c
                snapshot.append((st.job, st.instance, copies))
        by_name: "OrderedDict[str, List]" = OrderedDict()
        for job, instance, families in snapshot:
            for name in sorted(families):
                by_name.setdefault(name, []).append(
                    (job, instance, families[name]))
        lines: List[str] = []
        for name, rows in by_name.items():
            fs0 = rows[0][2]
            if fs0.help:
                lines.append(f"# HELP {name} {fs0.help}")
            else:
                lines.append(f"# HELP {name}")
            lines.append(f"# TYPE {name} {fs0.type}")
            for job, instance, fs in rows:
                base = (f'job="{esc(job)}",instance="{esc(instance)}"')
                for key, value in fs.samples.items():
                    pairs = base
                    for n, v in zip(fs.labelnames, key):
                        pairs += f',{n}="{esc(v)}"'
                    if fs.type != "histogram":
                        lines.append(f"{name}{{{pairs}}} {fmt(value)}")
                        continue
                    counts, hsum, hcount = value
                    acc = 0
                    for upper, c in zip(fs.uppers or [], counts):
                        acc += c
                        lines.append(
                            f'{name}_bucket{{{pairs},'
                            f'le="{fmt(upper)}"}} {acc}')
                    lines.append(
                        f'{name}_bucket{{{pairs},le="+Inf"}} {hcount}')
                    lines.append(f"{name}_sum{{{pairs}}} {fmt(hsum)}")
                    lines.append(f"{name}_count{{{pairs}}} {hcount}")
        return "\n".join(lines) + ("\n" if lines else "")


# -- wire clients ----------------------------------------------------------

class _FramedPushClient:
    """Persistent framed-JSON push client (the sidecar codec) with one
    transparent reconnect — the ``uds://`` / ``tcp://`` face of
    :func:`pusher_for`. ``target`` is an AF_UNIX path, or
    ``(host, port)`` for the sidecar's TCP wire."""

    def __init__(self, target, timeout: float = 10.0) -> None:
        self._target = target
        self._timeout = timeout
        self._sock: Optional[_socket.socket] = None
        self._lock = threading.Lock()

    def _close(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def request(self, req: dict) -> dict:
        from namazu_tpu.endpoint.agent import read_frame, write_frame

        with self._lock:
            last_exc: Optional[BaseException] = None
            for attempt in (0, 1):
                sock = self._sock
                if sock is None:
                    family = (_socket.AF_INET
                              if isinstance(self._target, tuple)
                              else _socket.AF_UNIX)
                    sock = _socket.socket(family, _socket.SOCK_STREAM)
                    sock.settimeout(self._timeout)
                    try:
                        sock.connect(self._target)
                    except OSError as e:
                        try:
                            sock.close()
                        except OSError:
                            pass
                        last_exc = e
                        continue
                    self._sock = sock
                try:
                    write_frame(sock, req)
                    resp = read_frame(sock)
                    if resp is None:
                        raise OSError("connection closed mid-reply")
                    return resp
                except (OSError, ValueError) as e:
                    self._close()
                    last_exc = e
            raise last_exc  # type: ignore[misc]

    def push(self, doc: dict) -> dict:
        resp = self.request({"op": "telemetry", "doc": doc})
        if not resp.get("ok"):
            raise RuntimeError(resp.get("error", "telemetry refused"))
        return resp


def fetch(url: str, op: str, fmt: str = "") -> Any:
    """Read side of the fleet surfaces for the CLI (``tools metrics`` /
    ``tools top``): one ``fleet`` or ``metrics`` read against a live
    process. ``http(s)://`` hits the REST routes (``/fleet``,
    ``/metrics.json``); ``uds://`` speaks the framed obs ops — the
    same-host fleets without a TCP port. Returns the parsed JSON doc,
    or the exposition text when ``fmt == "prom"``."""
    if op not in ("fleet", "metrics", "profile"):
        raise ValueError(f"unknown obs read {op!r} "
                         "(want fleet|metrics|profile)")
    if url.startswith(("http://", "https://")):
        import urllib.request

        route = {"fleet": "/fleet", "metrics": "/metrics.json",
                 "profile": "/profile?format=json"}[op]
        if op == "fleet" and fmt == "prom":
            route += "?format=prom"
        with urllib.request.urlopen(url.rstrip("/") + route,
                                    timeout=10) as r:
            raw = r.read()
        return raw.decode() if fmt == "prom" else json.loads(raw)
    target = _framed_target(url)
    if target is not None:
        client = _FramedPushClient(target)
        try:
            req: Dict[str, Any] = {"op": op}
            if fmt == "prom":
                req["format"] = "prom"
            resp = client.request(req)
        finally:
            client._close()
        if not resp.get("ok"):
            raise RuntimeError(resp.get("error", f"{op} refused"))
        if fmt == "prom":
            return resp.get("text", "")
        return resp.get(op)
    raise ValueError(f"unsupported obs url {url!r} "
                     "(want http(s)://, uds:// or tcp://)")


def _framed_target(url: str):
    """The framed-wire connect target for a telemetry url, or None when
    the url is not a framed scheme: ``uds://path`` (a uds endpoint, a
    campaign supervisor's collector) or ``tcp://host:port`` (the
    sidecar's framed wire)."""
    if url.startswith("uds://"):
        return url[len("uds://"):]
    if url.startswith("tcp://"):
        host, _, port = url[len("tcp://"):].rpartition(":")
        return (host or "127.0.0.1", int(port))
    return None


def pusher_for(url: str) -> Callable[[dict], Any]:
    """A push callable for a telemetry target url: ``http(s)://`` =
    ``POST /api/v3/telemetry`` on an orchestrator's REST endpoint,
    ``uds://path`` / ``tcp://host:port`` = the framed ``telemetry`` op
    (uds endpoint, the campaign supervisor's collector, the sidecar's
    framed wire)."""
    if url.startswith(("http://", "https://")):
        import urllib.request

        target = url.rstrip("/") + "/api/v3/telemetry"

        def push(doc: dict) -> dict:
            req = urllib.request.Request(
                target, data=json.dumps(doc).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                return json.loads(r.read() or b"{}")

        return push
    target = _framed_target(url)
    if target is not None:
        return _FramedPushClient(target).push
    raise ValueError(f"unsupported telemetry url {url!r} "
                     "(want http(s)://, uds:// or tcp://)")


# -- wire surface (shared by UdsEndpoint + TelemetryServer) ----------------

def handle_obs_op(req: dict,
                  agg: Optional[FleetAggregator] = None
                  ) -> Optional[dict]:
    """Answer one framed observability op (``telemetry`` / ``fleet`` /
    ``metrics``); None = not an obs op (the caller keeps dispatching).
    Both framed wires — the uds event endpoint and the campaign
    supervisor's collector — route here, so the fleet surface is
    identical wherever the aggregator is hosted."""
    op = req.get("op")
    if op == "telemetry":
        if not enabled():
            # the kill switch holds on the SERVING side too: a fleet
            # with telemetry_enabled = false acks-and-discards pushes
            # from producers that didn't read the config, rather than
            # growing an aggregator nobody asked for
            return {"ok": True, "disabled": True}
        try:
            ack = (agg or aggregator()).note_push(req.get("doc"))
        except ValueError as e:
            return {"ok": False, "error": str(e)}
        return dict(ack, ok=True)
    if op == "fleet":
        a = agg or aggregator()
        if req.get("format") == "prom":
            return {"ok": True, "text": a.prometheus()}
        return {"ok": True, "fleet": a.payload()}
    if op == "metrics":
        # sampled gauges (edge staleness/parked, knowledge occupancy)
        # refresh on a relay cadence; a DIRECT registry read must not
        # serve values up to a push interval old (or never-set, when
        # the relay is disabled)
        run_collectors()
        return {"ok": True, "metrics": metrics.registry().to_jsonable()}
    if op == "profile":
        # this process's own sampling profile (obs/profiling.py) —
        # the framed twin of GET /profile
        from namazu_tpu.obs import profiling

        if req.get("format") == "collapsed":
            return {"ok": True, "text": profiling.render_collapsed()}
        return {"ok": True, "profile": profiling.payload()}
    return None


class TelemetryServer:
    """The campaign supervisor's collector: the shared framed-JSON
    serve loop (endpoint/framed.py) over AF_UNIX answering
    :func:`handle_obs_op` (plus ``ping``) — same-host ``run`` children
    and ``tools top --url uds://...`` speak to it without the
    supervisor growing an HTTP stack or a TCP port."""

    def __init__(self, path: str,
                 agg: Optional[FleetAggregator] = None) -> None:
        self.path = path
        self._agg = agg
        self._server = None

    def aggregator(self) -> FleetAggregator:
        return self._agg if self._agg is not None else aggregator()

    def _handle(self, req: dict) -> dict:
        resp = handle_obs_op(req, self.aggregator())
        if resp is None:
            resp = ({"ok": True, "server": "telemetry"}
                    if req.get("op") == "ping" else
                    {"ok": False,
                     "error": f"unknown op {req.get('op')!r}"})
        return resp

    def start(self) -> None:
        if self._server is not None:
            return
        # lazy: obs modules must stay importable without the endpoint
        # package resolving at module load
        from namazu_tpu.endpoint.framed import FramedServer

        srv = FramedServer(self._handle, name="telemetry-collector")
        # bind_unix reclaims only a LISTENER-LESS stale socket inode
        # (same rule as the uds event endpoint): a live listener means
        # another collector owns this path, and raises
        srv.bind_unix(self.path, backlog=32)
        srv.start()
        self._server = srv
        log.info("fleet telemetry collector on %s", self.path)

    def shutdown(self) -> None:
        srv, self._server = self._server, None
        if srv is not None:
            srv.shutdown()


# -- process-global wiring -------------------------------------------------

_enabled = True
_aggregator: Optional[FleetAggregator] = None
_self_relay: Optional[TelemetryRelay] = None
# reentrant: ensure_self_relay resolves aggregator() (which may lazily
# create under this same lock) while wiring the relay
_wiring_lock = threading.RLock()


def configure(on: bool) -> None:
    """Process-global switch (config key ``telemetry_enabled``):
    disabled, :meth:`TelemetryRelay.start` spawns no thread and
    :func:`ensure_self_relay` is a no-op — the ``obs_enabled`` cost
    contract."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def aggregator() -> FleetAggregator:
    """The process's fleet aggregator (lazily created — a process that
    never serves nor pushes telemetry allocates nothing)."""
    global _aggregator
    a = _aggregator
    if a is None:
        with _wiring_lock:
            a = _aggregator
            if a is None:
                a = _aggregator = FleetAggregator()
    return a


def set_aggregator(a: Optional[FleetAggregator]
                   ) -> Optional[FleetAggregator]:
    """Swap the process-global aggregator (tests); returns the old."""
    global _aggregator
    old, _aggregator = _aggregator, a
    return old


def slo_summary() -> Optional[Dict[str, Any]]:
    """The analytics fold: None unless an aggregator exists AND its
    objectives were declared explicitly in config."""
    a = _aggregator
    return None if a is None else a.slo_summary()


def configure_from_config(config) -> None:
    """Apply the fleet-telemetry config keys (called with the
    experiment config by ``obs.configure_from_config``). Only explicit
    keys touch process-global state — same multi-orchestrator rule as
    ``obs_enabled``."""
    if config.is_set("telemetry_enabled"):
        configure(bool(config.get("telemetry_enabled")))
    touched = (config.is_set("fleet_stale_after_s")
               or config.is_set("fleet_evict_after_s")
               or config.is_set("slo"))
    if not touched:
        return
    agg = aggregator()
    if config.is_set("fleet_stale_after_s"):
        agg.stale_after_s = max(0.0, float(
            config.get("fleet_stale_after_s") or 0))
    if config.is_set("fleet_evict_after_s"):
        agg.evict_after_s = max(0.0, float(
            config.get("fleet_evict_after_s") or 0))
    if config.is_set("slo"):
        agg.set_slos(slo.specs_from_config(config.get("slo") or []),
                     explicit=True)


def ensure_self_relay(job: str, push_url: str = "",
                      interval_s: float = 2.0,
                      instance: Optional[str] = None
                      ) -> Optional[TelemetryRelay]:
    """The ONE self-relay per process: walks the process registry and
    merges into the local aggregator (and upstream when ``push_url``
    is set). Idempotent — a second orchestrator in the same process
    reuses the first relay (two encoders over one shared registry
    would each report full state and double the fleet's view). A
    late-arriving ``push_url`` upgrades the existing relay."""
    global _self_relay
    if not _enabled:
        return None
    with _wiring_lock:
        relay = _self_relay
        if relay is None:
            push = pusher_for(push_url) if push_url else None
            relay = _self_relay = TelemetryRelay(
                job=job, instance=instance,
                push=push, local=aggregator(),
                forward_source=aggregator() if push else None,
                interval_s=interval_s, target_desc=push_url)
            relay.start()
            # final flush at interpreter exit: a 2-second `run` child
            # must deliver its last interval of samples
            atexit.register(relay.shutdown)
        elif push_url and relay._push is None:
            relay.set_upstream(pusher_for(push_url),
                               forward_source=aggregator(),
                               target_desc=push_url)
        return relay


def self_relay() -> Optional[TelemetryRelay]:
    return _self_relay


def reset() -> None:
    """Fresh wiring (tests): stops the self-relay, drops the
    aggregator, and forgets registered collectors (an abandoned
    component's bound-method collector would otherwise keep its whole
    object graph alive across resets and write stale gauges into the
    next test's registry)."""
    global _aggregator, _self_relay, _enabled
    with _wiring_lock:
        relay, _self_relay = _self_relay, None
        _aggregator = None
        _enabled = True
    with _collectors_lock:
        del _collectors[:]
    if relay is not None:
        relay._stop.set()
        t = relay._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
