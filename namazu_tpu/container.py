"""Container mode: fuzz a containerized testee with one command.

Capability parity with /root/reference/nmz/container + nmz/cli/container
(`nmz container run`, SURVEY.md section 2.12): boot a container with the
framework's interception pre-wired — an embedded autopilot orchestrator on
the host, the LD_PRELOAD fs interposer bind-mounted into the container
(replacing the reference's FUSE-volume rewrite), and a proc inspector
attached to the container's root PID (replacing its in-netns NFQUEUE
setup, which needs kernel privileges a TPU-pod environment will not have).

Requires a ``docker`` CLI; this image has none, so everything is gated
behind :func:`docker_available` and the CLI reports the gap cleanly.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import threading
from typing import List, Optional

from namazu_tpu.utils.config import Config
from namazu_tpu.utils.log import get_logger

log = get_logger("container")

INTERPOSE_LIB = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native", "build", "libnmz_fs_interpose.so",
)


def docker_available() -> bool:
    return shutil.which("docker") is not None


class ContainerRunError(RuntimeError):
    pass


def run_container(
    image: str,
    command: List[str],
    volumes: Optional[List[str]] = None,
    config: Optional[Config] = None,
    fs_root: str = "/data",
    proc_watch_interval: float = 1.0,
    docker_args: Optional[List[str]] = None,
) -> int:
    """`nmz-tpu container run` core.

    Boots an autopilot orchestrator (agent endpoint on an auto port), runs
    ``docker run --network=host`` with the interposer preloaded and
    pointed at it, attaches a proc inspector to the container's root PID,
    and returns the container's exit status.
    """
    if not docker_available():
        raise ContainerRunError(
            "container mode needs a `docker` CLI on PATH; none found. "
            "(The interception itself — LD_PRELOAD interposer + proc "
            "inspector — has no other host requirements.)"
        )
    if not os.path.exists(INTERPOSE_LIB):
        raise ContainerRunError(
            f"{INTERPOSE_LIB} missing; build it with `make -C native`"
        )

    from namazu_tpu.inspector.proc import ProcInspector
    from namazu_tpu.inspector.transceiver import new_transceiver
    from namazu_tpu.orchestrator import AutopilotOrchestrator

    cfg = config or Config()
    cfg.set("agent_port", 0)
    orc = AutopilotOrchestrator(cfg)
    orc.hub.add_endpoint(_agent_endpoint())
    orc.start()
    agent = orc.hub.endpoint("agent")

    name = f"nmz-tpu-{os.getpid()}"
    cmd = [
        "docker", "run", "--rm", "--name", name, "--network=host",
        "-v", f"{os.path.abspath(INTERPOSE_LIB)}:/opt/nmz/interpose.so:ro",
        "-e", "LD_PRELOAD=/opt/nmz/interpose.so",
        "-e", f"NMZ_TPU_AGENT_ADDR=127.0.0.1:{agent.port}",
        "-e", f"NMZ_TPU_FS_ROOT={fs_root}",
        "-e", "NMZ_TPU_ENTITY_ID=container",
    ]
    for v in volumes or []:
        cmd += ["-v", v]
    cmd += docker_args or []
    cmd += [image] + command

    log.info("booting container: %s", " ".join(cmd))
    proc = subprocess.Popen(cmd)

    inspector = ProcInspector(
        new_transceiver("local://", "_nmz_container_proc",
                        orc.local_endpoint),
        root_pid=proc.pid,
        entity_id="_nmz_container_proc",
        watch_interval=proc_watch_interval,
    )
    t = threading.Thread(target=inspector.serve, daemon=True)
    t.start()
    try:
        return proc.wait()
    finally:
        inspector.stop()
        t.join(timeout=5)
        orc.shutdown()


def _agent_endpoint():
    from namazu_tpu.endpoint.agent import AgentEndpoint

    return AgentEndpoint(port=0)
