"""Orchestrator: the core runtime tying endpoints to the policy."""

from namazu_tpu.orchestrator.core import Orchestrator, AutopilotOrchestrator

__all__ = ["Orchestrator", "AutopilotOrchestrator"]
