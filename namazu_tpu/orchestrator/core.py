"""The orchestrator runtime.

Capability parity with /root/reference/nmz/orchestrator/orchestrator.go:
three worker threads around queues —

* **event thread**: pulls merged inbound events from the EndpointHub and
  feeds the active policy (the configured one while orchestration is
  enabled, an always-instantiated passthrough ``dumb`` policy while
  disabled — parity orchestrator.go:43-45, 84-94);
* **action thread**: drains policy actions, stamps ``triggered_time``,
  executes orchestrator-side actions in-process, forwards the rest to the
  hub for dispatch, and appends everything to the trace when
  ``collect_trace`` (parity orchestrator.go:96-179);
* **control thread**: toggles enable/disable from REST ``/control``
  (parity orchestrator.go:181-199; config key ``skip_init_orchestration``).

``shutdown()`` stops the loops and returns the accumulated
:class:`SingleTrace` (parity orchestrator.go:207-220).
"""

from __future__ import annotations

import os
import queue
import signal as _signal
import threading
import time
import uuid as _uuid
from typing import Optional

from namazu_tpu import chaos, obs, tenancy
from namazu_tpu.endpoint.hub import EndpointHub
from namazu_tpu.endpoint.local import LocalEndpoint
from namazu_tpu.policy.base import POLICY_DONE, ExplorePolicy, create_policy
from namazu_tpu.signal.action import Action
from namazu_tpu.signal.control import ControlOp
from namazu_tpu.utils.config import Config
from namazu_tpu.utils.log import get_logger
from namazu_tpu.utils.trace import SingleTrace

log = get_logger("orchestrator")

_STOP = object()
_FWD_DONE = object()


class FlushMarker:
    """Rides the merged action queue behind a namespace's final
    actions (tenancy plane): the action loop fires it at the END of the
    batch that carried it — i.e. after those actions were dispatched
    AND their releases journaled — so a lease release can wait for its
    namespace's drain deterministically."""

    __slots__ = ("done",)

    def __init__(self) -> None:
        self.done = threading.Event()


class Orchestrator:
    def __init__(
        self,
        config: Config,
        policy: ExplorePolicy,
        collect_trace: bool = False,
        hub: Optional[EndpointHub] = None,
    ):
        self.config = config
        obs.configure_from_config(config)
        # the correlation key for this run's logs, metrics and flight-
        # recorder trace (GET /traces/<run_id>); `run` passes the run
        # dir's name so on-disk artifacts join on the same id
        self.run_id = str(config.get("run_id") or "") \
            or _uuid.uuid4().hex[:12]
        self.policy = policy
        self.collect_trace = collect_trace
        self.trace = SingleTrace()
        # the passthrough policy used while orchestration is disabled
        self.dumb = create_policy("dumb")
        self.enabled = not bool(config.get("skip_init_orchestration"))
        self.hub = hub or self._default_hub(config)
        self.local_endpoint: Optional[LocalEndpoint] = None
        ep = self.hub.endpoint("local")
        if isinstance(ep, LocalEndpoint):
            self.local_endpoint = ep
        self._threads: dict[str, threading.Thread] = {}
        self._merged_actions: "queue.Queue[object]" = queue.Queue()
        self._n_policies = 2  # policy + dumb; the action loop exits after
        # receiving this many _FWD_DONE markers
        self._started = False
        self._shut_down = False
        # liveness watchdog (doc/robustness.md): entities silent past
        # the timeout are declared dead and their parked events force-
        # released, so one hung/killed testee process cannot park the
        # run behind delays nobody will ever observe. 0 = disabled.
        self.liveness_timeout_s = float(
            config.get("entity_liveness_timeout_s", 0) or 0)
        # crash-recovery event journal (doc/robustness.md "Chaos
        # plane"): a write-ahead log of inbound events + dispatched
        # releases in the run's dir, so a killed-and-restarted
        # orchestrator resumes its parked events instead of losing the
        # run. Off unless the config names a dir ("" = the
        # pre-journal behavior, zero hot-path cost).
        journal_dir = str(config.get("event_journal_dir", "") or "")
        self.journal = None
        if journal_dir:
            from namazu_tpu.chaos.journal import EventJournal

            self.journal = EventJournal(journal_dir)
        self._watchdog_stop = threading.Event()
        # entities currently declared dead; an entity leaves the set
        # when it is seen again (metric + warning fire per transition,
        # not per sweep)
        self._stalled: set = set()
        # zero-RTT dispatch (doc/performance.md): a policy that
        # publishes its delay table (policy/edge_table.py) plugs its
        # publisher into the hub so endpoints can serve/version it;
        # suspended while orchestration is disabled — edges must not
        # keep deciding with a table the passthrough policy would not
        # have applied
        pub = getattr(policy, "table_publisher", None)
        self.hub.table_publisher = pub
        if pub is not None and not self.enabled:
            pub.suspend()
        # virtual clock (doc/performance.md "Virtual clock"): when the
        # process runs under a VirtualTimeSource (`run --virtual-clock`
        # installed it before this constructor), the orchestrator's
        # queues become the coordinator's busy probes — an event or
        # action anywhere in flight between intake and dispatch vetoes
        # fast-forward, so a jump can never overtake work that is about
        # to park a new deadline. Wall time: zero cost, nothing
        # registered.
        from namazu_tpu.utils import timesource

        self.time_source = timesource.get()
        if self.time_source.is_virtual:
            self.time_source.add_busy_probe(
                lambda: not self.hub.event_queue.empty())
            self.time_source.add_busy_probe(
                lambda: not self._merged_actions.empty())
            self.time_source.add_busy_probe(
                lambda: not self.policy.action_out.empty())
            self.time_source.add_busy_probe(
                lambda: not self.dumb.action_out.empty())

    @staticmethod
    def _default_hub(config: Config) -> EndpointHub:
        """Local endpoint always; REST / guest-agent endpoints when their
        ports are enabled (parity: endpoint.StartAll, endpoint.go:63-97)."""
        hub = EndpointHub()
        hub.add_endpoint(LocalEndpoint())
        rest_port = int(config.get("rest_port", -1))
        if rest_port >= 0:
            from namazu_tpu.endpoint.rest import RestEndpoint

            hub.add_endpoint(RestEndpoint(
                port=rest_port,
                # the long-poll window; configurable pre-start so a
                # successor orchestrator's first parked poll cannot
                # ride a 30s default before a test/operator shrinks it
                poll_timeout=float(
                    config.get("rest_poll_timeout", 30.0) or 30.0),
                # bounded ingress (doc/robustness.md): 0 = unbounded
                ingress_cap=int(config.get("rest_ingress_cap", 0) or 0),
                # bounded connection-handler pool (doc/tenancy.md):
                # beyond this many concurrent connections, new ones
                # queue for a handler instead of growing a thread each
                max_threads=int(
                    config.get("rest_max_threads", 64) or 64)))
        uds_path = str(config.get("uds_path", "") or "")
        if uds_path:
            from namazu_tpu.endpoint.uds import UdsEndpoint

            # same hub, same bound: the ingress cap protects the
            # orchestrator's event queue, whichever wire feeds it
            hub.add_endpoint(UdsEndpoint(
                uds_path,
                ingress_cap=int(config.get("rest_ingress_cap", 0) or 0)))
        agent_port = int(config.get("agent_port", -1))
        if agent_port >= 0:
            try:
                from namazu_tpu.endpoint.agent import AgentEndpoint
            except ImportError as e:
                raise NotImplementedError(
                    "guest-agent endpoint not available in this build"
                ) from e
            hub.add_endpoint(AgentEndpoint(port=agent_port))
        return hub

    def _add_thread(self, target, name: str) -> None:
        t = threading.Thread(target=target, name=f"orc-{name}", daemon=True)
        t.start()
        self._threads[name] = t

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        obs.begin_run(self.run_id)
        # recover BEFORE the endpoints open: the dedupe ring must know
        # the journaled uuids before an inspector's reconnect-and-
        # replay can reach the wire, or the replay doubles every
        # recovered event
        self._recover_journal()
        self.hub.start()
        self.policy.start()
        self.dumb.start()
        self._add_thread(self._event_loop, "events")
        self._add_thread(self._action_loop, "actions")
        self._add_thread(self._control_loop, "control")
        self._add_thread(self._forward_loop_factory(self.policy), "fwd-policy")
        self._add_thread(self._forward_loop_factory(self.dumb), "fwd-dumb")
        if self.liveness_timeout_s > 0:
            self._add_thread(self._watchdog_loop, "watchdog")
        # fleet telemetry (doc/observability.md "Fleet telemetry"): this
        # process is a producer — its registry rides the process relay
        # into the local aggregator (serving GET /fleet here) and, when
        # an upstream collector is named (config telemetry_url, or the
        # NMZ_TELEMETRY_URL a campaign supervisor exports to its run
        # children), pushed + forwarded upstream too. ensure_self_relay
        # is idempotent: a CLI layer that already named this process's
        # job (e.g. `run`) wins.
        push_url = str(self.config.get("telemetry_url", "") or "") \
            or os.environ.get("NMZ_TELEMETRY_URL", "")
        obs.federation.ensure_self_relay(
            "orchestrator", push_url=push_url,
            interval_s=float(
                self.config.get("telemetry_interval_s", 2.0) or 2.0))
        # continuous profiling (doc/observability.md "Profiling"):
        # idempotent like the relay — a CLI layer that already started
        # the sampler under its own job name wins
        obs.profiling.ensure_profiler("orchestrator", cfg=self.config)
        log.debug("orchestrator started (enabled=%s)", self.enabled)

    def _recover_journal(self) -> None:
        """Reload parked events a killed predecessor journaled but
        never dispatched (doc/robustness.md): seed the REST dedupe ring
        with their uuids (an inspector-side replay must ack idempotent,
        not double), then re-post them through the hub — which restores
        the entity routes AND the liveness bookkeeping, so the re-armed
        watchdog force-releases events whose entity never speaks
        again."""
        if self.journal is None:
            return
        recovered = self.journal.unreleased()
        if not recovered:
            return
        rest = self.hub.endpoint("rest")
        if rest is not None and hasattr(rest, "note_event_uuid"):
            for event, _ in recovered:
                rest.note_event_uuid(event.uuid)
        for event, endpoint_name in recovered:
            self.hub.post_event(event, endpoint_name or "local")
        obs.journal_recovered(len(recovered))
        log.warning(
            "recovered %d parked event(s) from the event journal; "
            "resuming the run (liveness watchdog %s)", len(recovered),
            f"re-armed at {self.liveness_timeout_s:.1f}s"
            if self.liveness_timeout_s > 0 else "disabled")

    def shutdown(self) -> SingleTrace:
        """Stop all loops, flushing in dependency order so no action is
        lost: event intake first, then policies (which release their still-
        delayed events immediately and emit POLICY_DONE), then the forward
        and action loops drain everything before exiting."""
        if self._shut_down:
            return self.trace
        self._shut_down = True
        if not self._started:
            return self.trace
        # 1. stop event intake (events already inbound are forwarded first)
        self.hub.event_queue.put(_STOP)  # type: ignore[arg-type]
        self._threads["events"].join(timeout=10)
        # 2. flush the policies; their dequeue workers emit remaining
        #    actions and then POLICY_DONE
        self.policy.shutdown()
        self.dumb.shutdown()
        # 3. forward loops exit on POLICY_DONE after draining; the action
        #    loop exits after both _FWD_DONE markers
        self._threads["fwd-policy"].join(timeout=10)
        self._threads["fwd-dumb"].join(timeout=10)
        self._threads["actions"].join(timeout=10)
        # 4. watchdog, control loop + transports
        self._watchdog_stop.set()
        if "watchdog" in self._threads:
            self._threads["watchdog"].join(timeout=10)
        self.hub.control_queue.put(_STOP)  # type: ignore[arg-type]
        self._threads["control"].join(timeout=10)
        self.hub.shutdown()
        if self.journal is not None:
            # every parked event was flushed above and its release
            # journaled: the run completed, so remove the file — a
            # later orchestrator over the same dir must not re-parse
            # (or endlessly grow) a fully-released history. A crash
            # ANYWHERE before this line leaves the journal for
            # recovery, which is the point.
            self.journal.remove()
        log.debug("orchestrator shut down; trace length %d", len(self.trace))
        # close the flight-recorder run LAST: the drains above still
        # stamp released/dispatched records against it
        obs.end_run(self.run_id)
        return self.trace

    def abandon(self) -> None:
        """Die WITHOUT the graceful drain — the in-process stand-in for
        ``kill -9`` the chaos harness's crash scenarios use: endpoints
        are torn down so the ports free up and a successor can bind
        them, but policies are NOT flushed, parked events are NOT
        released, and the journal gets no further records. Everything a
        real SIGKILL would leak (daemon worker threads parked on their
        queues) leaks here too; only a journal-recovering successor can
        resume the run."""
        self._shut_down = True
        self._watchdog_stop.set()
        # sever live connections first, like process death would: an
        # inspector's keep-alive long-poll must error and reconnect (to
        # the successor), not keep talking to zombie handler threads
        for name in ("rest",):
            ep = self.hub.endpoint(name)
            if ep is not None and hasattr(ep, "sever"):
                ep.sever()
        self.hub.shutdown()
        # a real SIGKILL takes the policy's delay queue with it: close
        # + drain WITHOUT releasing, or the still-parked items would be
        # dispatched by the leaked (daemon) release worker when their
        # delays expire — a dead orchestrator's policy emitting actions
        # minutes later, stamping records into whatever flight-recorder
        # run is current by then. The items die here; only the journal-
        # recovering successor resurrects them.
        for pol in (self.policy, self.dumb):
            q = getattr(pol, "_queue", None)
            if q is not None:
                try:
                    q.close()
                    q.drain_remaining()
                except Exception:  # pragma: no cover - best effort
                    pass
        if self.journal is not None:
            self.journal.close()
        obs.end_run(self.run_id)
        log.warning("orchestrator abandoned (simulated crash); parked "
                    "events remain journaled but undispatched")

    # -- loops -----------------------------------------------------------

    #: greedy-drain cap for the event and action loops: bounds how much
    #: one batch can delay the loop's shutdown sentinel check, and the
    #: largest batch a policy's vectorized decision sees at once
    BATCH_MAX = 256

    def _event_loop(self) -> None:
        while True:
            ev = self.hub.event_queue.get()
            if ev is _STOP:
                return
            # greedy drain: everything already inbound rides ONE policy
            # call (the batch POST route enqueues whole batches, so
            # under load this recovers them; when idle the batch is 1
            # and behavior is exactly the sequential path)
            batch = [ev]
            stop = False
            while len(batch) < self.BATCH_MAX:
                try:
                    nxt = self.hub.event_queue.get_nowait()
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stop = True
                    break
                batch.append(nxt)
            # edge-decided events (backhaul reconciliation,
            # doc/performance.md "Zero-RTT dispatch") never reach the
            # policy OR the journal: the edge already decided and
            # dispatched them — they only need their trace records and
            # synthetic actions. Partitioned BEFORE the journal append,
            # or recovery would re-dispatch an already-answered event.
            edge_batch = [ev for ev in batch
                          if getattr(ev, "_edge_decision", None)]
            if edge_batch:
                batch = [ev for ev in batch
                         if getattr(ev, "_edge_decision", None) is None]
                self._ingest_edge_batch(edge_batch)
                if not batch:
                    if stop:
                        return
                    continue
            self._dispatch_central_batch(batch)
            if stop:
                return

    def _dispatch_central_batch(self, batch: list) -> None:
        """Journal + feed one drained central batch to its policy. The
        single-run body; TenantOrchestrator overrides to partition the
        batch by run namespace first (doc/tenancy.md)."""
        # chaos seam (profiling plane): a seeded slowdown parks the
        # decision stage in a distinctively-named frame the sampling
        # profiler must localize — the CI seeded-slowdown smoke
        chaos.stage_slowdown("orchestrator.stage.slow")
        self._journal_and_queue(batch, self.journal,
                                self.policy if self.enabled else self.dumb)

    def _routes_for_ns(self, ns: str) -> dict:
        """One namespace's entity -> endpoint routes (bare entity
        keys): what its journal persists — a journal is a single-tenant
        artifact, so recovery resolves entities without knowing about
        route-key prefixes (and never sees other tenants' routes)."""
        out = {}
        for key, endpoint_name in self.hub.routes().items():
            key_ns, entity = tenancy.split_route_key(key)
            if key_ns == ns:
                out[entity] = endpoint_name
        return out

    def _journal_and_queue(self, batch: list, journal,
                           target: ExplorePolicy,
                           routes: Optional[dict] = None) -> None:
        if journal is not None:
            # write-ahead: the batch is durable BEFORE the policy
            # sees it, so a crash from here on can lose nothing
            try:
                journal.append_events(
                    batch, routes if routes is not None
                    else self._routes_for_ns(""))
                obs.journal_events(len(batch))
            except OSError:
                log.exception("event journal append failed; "
                              "continuing without durability")
            # chaos seam: die like kill -9 WOULD — after the journal
            # write, before dispatch (the recovery window the crash
            # scenarios exercise)
            if chaos.decide("orchestrator.crash") is not None:
                log.error("chaos: orchestrator.crash fired; "
                          "SIGKILLing this process")
                os.kill(os.getpid(), _signal.SIGKILL)
        for ev in batch:
            obs.mark(ev, "enqueued")
            obs.record_enqueued(ev, target.name)
        try:
            if len(batch) == 1:
                target.queue_event(batch[0])
                rejected = ()
            else:
                # queue_events isolates per-event failures itself
                # and reports them (policy/base.py contract);
                # reaching this except means a batch-level failure
                # (e.g. queue closed at shutdown)
                rejected = target.queue_events(batch) or ()
        except Exception:
            log.exception("policy %s rejected a batch of %d events "
                          "(first: %r)", target.name, len(batch),
                          batch[0])
        else:
            # queue_event(s) returning means the policy chose the
            # batch's delays/priorities — the decision point.
            # Rejected events get no marks, exactly like a scalar
            # rejection: batched and per-event telemetry stay
            # identical
            rejected_ids = {id(ev) for ev in rejected}
            for ev in batch:
                if id(ev) in rejected_ids:
                    continue
                obs.mark(ev, "decided")
                obs.record_decided(ev, target.name)
                obs.policy_decision(target.name, ev.entity_id,
                                    obs.latency(ev, "intercepted"))

    def _ingest_edge_batch(self, events: list) -> None:
        """Reconcile backhauled edge decisions: one complete flight-
        recorder record per event with the EDGE's own lifecycle stamps
        (same host, shared CLOCK_MONOTONIC) and the decision detail
        (``decision_source="edge"``, ``table_version``, delay), plus
        the synthesized accepting action appended straight to the
        collected trace — the edge already delivered the real action,
        so nothing is forwarded, journaled, or queued through the
        policy/action loops."""
        policy_name = (self.policy if self.enabled else self.dumb).name
        now_mono = time.monotonic()
        lags = []
        parkings = []
        for ev in events:
            d = ev._edge_decision
            action = ev.default_action()
            action.mark_triggered(now=d.get("triggered_wall"))
            obs.record_edge(ev, getattr(ev, "_edge_endpoint", ""),
                            policy_name, action, d)
            # backhaul reconciliation lag: the edge's dispatch stamp ->
            # this reconcile, both CLOCK_MONOTONIC on one host — the
            # fleet-level answer to "is the 151k/s edge plane keeping
            # its async-backhaul promise" (doc/observability.md)
            stamp = d.get("t_dispatched")
            if isinstance(stamp, (int, float)):
                obs.edge_backhaul_lag(ev.entity_id, now_mono - stamp)
                lags.append(now_mono - stamp)
                t0 = d.get("t_intercepted")
                if isinstance(t0, (int, float)):
                    parkings.append(stamp - t0)
            self._trace_append(action)
        # causality-plane stage attribution (obs/causality.py): the
        # edge path's two segments, observed batch-wise (one family
        # resolution per burst — this loop runs at zero-RTT rates)
        obs.event_stage_many("backhaul", lags)
        obs.event_stage_many("edge_parking", parkings)
        obs.action_dispatched("edge", None, n=len(events))

    def _forward_loop_factory(self, policy: ExplorePolicy):
        def loop() -> None:
            while True:
                action = policy.action_out.get()
                if action is POLICY_DONE:
                    self._merged_actions.put(_FWD_DONE)
                    return
                self._merged_actions.put(action)

        return loop

    def _action_loop(self) -> None:
        done = 0
        while True:
            raw = [self._merged_actions.get()]
            while len(raw) < self.BATCH_MAX:
                try:
                    raw.append(self._merged_actions.get_nowait())
                except queue.Empty:
                    break
            # an item is one action, a released burst (list — the
            # action_out contract, policy/base.py), or a sentinel
            batch: list = []
            for item in raw:
                if isinstance(item, list):
                    batch.extend(item)
                else:
                    batch.append(item)
            # forwardable actions accumulate and fan through the hub in
            # one send_actions call (one route-lock + one queue-lock per
            # endpoint/entity); orchestrator-side actions act as flush
            # barriers so in-process execution keeps its place in the
            # release order
            forward: list = []
            released: list = []  # (uuid, namespace) pairs
            markers: list = []
            for item in batch:
                if item is _FWD_DONE:
                    done += 1
                    continue
                if isinstance(item, FlushMarker):
                    # fired at the END of this batch (after dispatch +
                    # release journaling), where its namespace's
                    # preceding actions are fully accounted
                    markers.append(item)
                    continue
                action: Action = item  # type: ignore[assignment]
                released.append((action.event_uuid or action.uuid,
                                 getattr(action, "_ns", "")))
                action.mark_triggered()
                obs.mark(action, "dispatched")
                kind = ("orchestrator" if action.orchestrator_side_only
                        else "forwarded")
                obs.record_dispatched(action, kind)
                obs.action_dispatched(kind,
                                      obs.latency(action, "intercepted"))
                self._trace_append(action)
                if action.orchestrator_side_only:
                    if forward:
                        self.hub.send_actions(forward)
                        forward = []
                    try:
                        action.execute_on_orchestrator()
                    except Exception:
                        log.exception(
                            "orchestrator-side action failed: %r", action)
                else:
                    forward.append(action)
            if forward:
                self.hub.send_actions(forward)
            if released:
                # release records land AFTER dispatch: the crash window
                # between the two is at-least-once, which the endpoint
                # dedupe + waiter-keyed dispatch absorb; the reverse
                # order would lose events (chaos/journal.py)
                self._journal_releases(released)
            for marker in markers:
                marker.done.set()
            if done >= self._n_policies:
                return

    def _trace_append(self, action: Action) -> None:
        """Collected-trace hook; TenantOrchestrator routes namespaced
        actions to their namespace's own trace."""
        if self.collect_trace:
            self.trace.append(action)

    def _journal_releases(self, released: list) -> None:
        """Append ``(uuid, namespace)`` release records; the base class
        owns only the default namespace's journal."""
        if self.journal is None:
            return
        uuids = [u for u, ns in released if not ns]
        if not uuids:
            return
        try:
            self.journal.append_releases(uuids)
        except OSError:
            log.exception("event journal release append failed")

    def _watchdog_loop(self) -> None:
        """Liveness sweep: declare entities silent past the timeout dead
        and force-release their parked events from both policies' delay
        queues, surfacing each transition in ``nmz_entity_stalled_total``
        and one WARNING — instead of the run silently waiting out delays
        for a testee that no longer exists."""
        interval = max(min(self.liveness_timeout_s / 4.0, 1.0), 0.05)
        while not self._watchdog_stop.wait(interval):
            self.sweep_stalled_entities()

    def sweep_stalled_entities(self) -> int:
        """One watchdog pass (public for tests and embedded callers);
        returns how many parked events were force-released."""
        stalled = self.hub.stalled_entities(self.liveness_timeout_s)
        released = 0
        for key, silent_for in stalled.items():
            ns, entity = tenancy.split_route_key(key)
            n = 0
            for pol in self._policies_for(ns):
                try:
                    n += pol.force_release_entity(entity)
                except Exception:
                    log.exception("force-release for entity %s failed "
                                  "in policy %s", entity, pol.name)
            released += n
            if key not in self._stalled:
                self._stalled.add(key)
                obs.entity_stalled(entity)
                log.warning(
                    "entity %s declared dead (silent %.1fs > %.1fs); "
                    "force-released %d parked event(s)",
                    entity, silent_for, self.liveness_timeout_s, n)
        # entities that spoke again re-arm their stall transition
        self._stalled &= set(stalled)
        return released

    def _policies_for(self, ns: str):
        """The policies that may hold parked events of one namespace;
        TenantOrchestrator overrides for non-default namespaces."""
        return (self.policy, self.dumb)

    def _control_loop(self) -> None:
        while True:
            ctrl = self.hub.control_queue.get()
            if ctrl is _STOP:
                return
            ns = tenancy.ns_of(ctrl)
            if ns:
                # a namespace-scoped op (X-Nmz-Run / framed `run`)
                # touches exactly that tenant's serving state — the
                # process-default flag and publisher stay untouched
                self._control_namespace(ns, ctrl.op)
                continue
            pub = self.hub.table_publisher
            if ctrl.op is ControlOp.ENABLE_ORCHESTRATION:
                self.enabled = True
                if pub is not None:
                    pub.resume()
            elif ctrl.op is ControlOp.DISABLE_ORCHESTRATION:
                self.enabled = False
                if pub is not None:
                    # edges must stop deciding with the table: central
                    # decisions now come from the passthrough policy
                    pub.suspend()
            log.info("orchestration enabled=%s", self.enabled)

    def _control_namespace(self, ns: str, op: ControlOp) -> None:
        """Apply one namespace-scoped control op; the base orchestrator
        hosts no namespaces (TenantOrchestrator overrides)."""
        log.warning("control op %s for run %r ignored: this "
                    "orchestrator hosts no run namespaces", op.value, ns)


class AutopilotOrchestrator(Orchestrator):
    """Embedded orchestrator for `local://` inspectors.

    Parity: NewAutopilotOrchestrator
    (/root/reference/nmz/util/orchestrator/orchestratorutil.go:26-38):
    builds policy from config, local endpoint only, no trace collection.
    """

    def __init__(self, config: Config):
        policy = create_policy(config.get("explore_policy"))
        policy.load_config(config)
        hub = EndpointHub()
        hub.add_endpoint(LocalEndpoint())
        super().__init__(config, policy, collect_trace=False, hub=hub)
