"""Binary signal codec: length-prefixed, tagged, IEEE-double-exact.

The negotiated fast wire (doc/performance.md "Binary wire + sharded
edge"). PR 10 deliberately single-sourced signal serialization at
``Signal.to_jsonable`` / ``signal_from_jsonable``, so a codec change is
one seam: this module encodes and decodes exactly those **wire dicts**
— it never touches signal objects, option schemas, or the span-context
representation. A signal decoded from a binary frame is
``signal_from_jsonable(binary.loads(frame))``, byte-for-byte equal *in
meaning* to its JSON twin (pinned by the round-trip property tests over
every registered signal class).

Frame layout (everything little-endian)::

    +----+----+----+----+------------------------------------+
    | A6 | 4E |ver |flag|  tagged value tree ...             |
    +----+----+----+----+------------------------------------+

a fixed 4-byte header (magic ``0xA6 'N'``, version, flags) followed by
one tagged value. Value tags:

    00 None   01 True   02 False
    03 int8   04 int32  05 int64  06 bigint (u32 len + signed LE bytes)
    07 float64 (IEEE 754 binary64, bit-exact — a published delay table
       crosses this wire without ever passing through decimal text, so
       edge decisions stay bit-identical to central ones by
       construction, not by repr round-trip luck)
    08 str8 (u8 len + utf8)      09 str32 (u32 len + utf8)
    0A list (u32 count + items)  0B dict (u32 count + key/value pairs;
                                     keys are u8-length utf8 — wire
                                     dicts never carry non-str keys)
    0C bytes (u32 len)
    10 signal record: type code (u8: 0 event / 1 action / 2 other),
       class, entity, uuid (str8 each), option value, extras count (u8)
       + (key, value) pairs — the fixed signal fields ride tag slots
       instead of repeated key strings
    11 signal batch: u32 count + a TEMPLATE (type code, class, entity,
       shared ctx value-or-None) + per item (uuid, option, extras).
       Event bursts share type/class/entity and — since the burst mint
       (obs/context.mint_many) stamps ONE context per burst — usually
       the ctx too, so the per-event wire cost collapses to uuid +
       option values: ~2.4x fewer bytes than the JSON batch. CPU: the
       pure-Python encoder runs near C-json parity (string-encode
       caches), the decoder costs ~2x C-json — both OFF the zero-RTT
       decision path (flush/handler threads), so the codec trades a
       little handler-thread CPU for wire bytes and float exactness.

Negotiation is the transports' job (per connection, JSON remains the
default — doc/performance.md): this module only defines the names. A
decoder failure raises :class:`ValueError` with the offset — the framed
server answers it without severing the connection (the frame LENGTH was
intact, so the stream is still in sync), and the REST routes 400 it.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List

__all__ = [
    "CODEC_BINARY", "CODEC_JSON", "CODEC_HEADER", "CODEC_ACCEPT_HEADER",
    "CONTENT_TYPE_BINARY", "MAGIC", "VERSION", "dumps", "loads",
]

#: negotiated codec names (the values of the REST headers and the
#: framed ``codec`` op)
CODEC_BINARY = "nmzb1"
CODEC_JSON = "json"
#: REST: the codec of THIS message's body (request and response)
CODEC_HEADER = "X-Nmz-Codec"
#: REST: piggybacked on every API response — how a client discovers a
#: binary-capable server (the table-version piggyback pattern)
CODEC_ACCEPT_HEADER = "X-Nmz-Codec-Accept"
CONTENT_TYPE_BINARY = "application/x-nmz-binary"

MAGIC = b"\xa6N"
VERSION = 1
_HEADER = MAGIC + bytes((VERSION, 0))

_pack_d = struct.Struct("<d").pack
_pack_i = struct.Struct("<i").pack
_pack_q = struct.Struct("<q").pack
_pack_I = struct.Struct("<I").pack
_unpack_d = struct.Struct("<d").unpack_from
_unpack_i = struct.Struct("<i").unpack_from
_unpack_q = struct.Struct("<q").unpack_from
_unpack_I = struct.Struct("<I").unpack_from

#: the signal-record fixed slots (never re-encoded as extras)
_SIG_KEYS = frozenset(("type", "class", "entity", "uuid", "option"))
_TYPE_CODES = {"event": 0, "action": 1}
_TYPE_NAMES = {0: "event", 1: "action"}

#: bounded encode caches: wire strings repeat heavily — option keys,
#: entity ids, replay hints, class names are drawn from tiny sets while
#: uuids are unique. Caching the ENCODED bytes turns most of a batch's
#: string work into dict gets (cleared whole at the cap, the EdgeTable
#: memo convention — eviction bookkeeping would cost more than the
#: encodes it saves).
_CACHE_CAP = 4096
_rawstr_cache: Dict[str, bytes] = {}
_str_cache: Dict[str, bytes] = {}


def _is_signal_dict(v: Any) -> bool:
    return (type(v) is dict and "class" in v and "uuid" in v
            and "entity" in v)


def _enc_str(s: str, out: List[bytes]) -> None:
    enc = _str_cache.get(s)
    if enc is None:
        b = s.encode()
        n = len(b)
        enc = (b"\x08" + bytes((n,)) + b if n < 256
               else b"\x09" + _pack_I(n) + b)
        if n <= 128:
            if len(_str_cache) >= _CACHE_CAP:
                _str_cache.clear()
            _str_cache[s] = enc
    out.append(enc)


def _enc_rawstr(s: str, out: List[bytes]) -> None:
    """Tagless string (dict keys, the signal record's fixed slots):
    u8 length, with 255 escaping to a u32 length."""
    enc = _rawstr_cache.get(s)
    if enc is None:
        if type(s) is not str:
            raise TypeError(f"wire dict key must be str, got {type(s)}")
        b = s.encode()
        n = len(b)
        enc = (bytes((n,)) + b if n < 255
               else b"\xff" + _pack_I(n) + b)
        if n <= 128:
            if len(_rawstr_cache) >= _CACHE_CAP:
                _rawstr_cache.clear()
            _rawstr_cache[s] = enc
        else:
            out.append(enc)
            return
    out.append(enc)


def _enc_rawstr_nc(s: str, out: List[bytes]) -> None:
    """Uncached raw string (uuids: unique by construction, caching
    them would only churn the bounded caches)."""
    b = s.encode()
    n = len(b)
    if n < 255:
        out.append(bytes((n,)) + b)
    else:
        out.append(b"\xff" + _pack_I(n) + b)


def _enc_sig_tail(d: Dict[str, Any], out: List[bytes],
                  skip_ctx: bool = False) -> None:
    """uuid + option + extras of one signal record (the per-item part
    shared by the scalar record and the batch row). The flat
    string-valued option dict — every built-in event class — is
    encoded inline off the caches; anything else takes the generic
    path."""
    append = out.append
    u = d["uuid"].encode()
    append(bytes((len(u),)) + u if len(u) < 255
           else b"\xff" + _pack_I(len(u)) + u)
    option = d.get("option")
    if type(option) is dict:
        append(b"\x0b" + _pack_I(len(option)))
        raw_get = _rawstr_cache.get
        str_get = _str_cache.get
        for k, v in option.items():
            enc = raw_get(k)
            if enc is None:
                _enc_rawstr(k, out)
            else:
                append(enc)
            if type(v) is str:
                enc = str_get(v)
                if enc is None:
                    _enc_str(v, out)
                else:
                    append(enc)
            else:
                _enc_value(v, out)
    else:
        _enc_value(option, out)
    n_extras = 0
    for k in d:
        if k not in _SIG_KEYS and not (skip_ctx and k == "ctx"):
            n_extras += 1
    if n_extras > 255:
        raise TypeError("signal dict has too many extra fields")
    append(bytes((n_extras,)))
    if n_extras:
        for k, v in d.items():
            if k in _SIG_KEYS or (skip_ctx and k == "ctx"):
                continue
            _enc_rawstr(k, out)
            _enc_value(v, out)


def _enc_value(v: Any, out: List[bytes]) -> None:
    t = type(v)
    if t is str:
        _enc_str(v, out)
    elif t is dict:
        if "class" in v and "uuid" in v and "entity" in v:
            # one signal record: fixed slots instead of key strings.
            # A non-standard/absent "type" gets code 2 and rides the
            # extras (lossless; code 2 alone means "no type key").
            standard = v.get("type") in _TYPE_CODES and "type" in v
            out.append(b"\x10" + bytes(
                (_TYPE_CODES[v["type"]] if standard else 2,)))
            _enc_rawstr(str(v["class"]), out)
            _enc_rawstr(str(v["entity"]), out)
            if standard:
                _enc_sig_tail(v, out)
            else:
                _enc_sig_tail_odd_type(v, out)
        else:
            out.append(b"\x0b" + _pack_I(len(v)))
            for k, val in v.items():
                if type(k) is not str:
                    raise TypeError(
                        f"wire dict key must be str, got {type(k)}")
                _enc_rawstr(k, out)
                _enc_value(val, out)
    elif t is list:
        if len(v) > 1 and all(map(_is_signal_dict, v)):
            first = v[0]
            f_type = first.get("type")
            f_cls, f_ent = first["class"], first["entity"]
            f_ctx = first.get("ctx")
            if (f_type in _TYPE_CODES
                    and all(d.get("type") == f_type
                            and d["class"] == f_cls
                            and d["entity"] == f_ent for d in v)):
                # signal batch: template + rows (the burst fast path).
                # The template carries the shared ctx ONLY when every
                # row has that exact ctx — decode attaches the
                # template ctx to every row, so a mixed batch (one
                # ctx-less event coalesced with stamped ones) must
                # fall back to per-row ctx extras or decode would
                # FABRICATE a span context that was never minted.
                shared_ctx = (f_ctx if f_ctx is not None
                              and all(d.get("ctx") == f_ctx for d in v)
                              else None)
                out.append(b"\x11" + _pack_I(len(v))
                           + bytes((_TYPE_CODES[f_type],)))
                _enc_rawstr(str(f_cls), out)
                _enc_rawstr(str(f_ent), out)
                _enc_value(shared_ctx, out)
                skip = shared_ctx is not None
                for d in v:
                    _enc_sig_tail(d, out, skip_ctx=skip)
                return
        out.append(b"\x0a" + _pack_I(len(v)))
        for item in v:
            _enc_value(item, out)
    elif t is float:
        out.append(b"\x07" + _pack_d(v))
    elif t is bool:
        out.append(b"\x01" if v else b"\x02")
    elif t is int:
        if -128 <= v < 128:
            out.append(b"\x03" + v.to_bytes(1, "little", signed=True))
        elif -2147483648 <= v < 2147483648:
            out.append(b"\x04" + _pack_i(v))
        elif -(1 << 63) <= v < (1 << 63):
            out.append(b"\x05" + _pack_q(v))
        else:
            b = v.to_bytes((v.bit_length() + 8) // 8, "little",
                           signed=True)
            out.append(b"\x06" + _pack_I(len(b)) + b)
    elif v is None:
        out.append(b"\x00")
    elif t is bytes:
        out.append(b"\x0c" + _pack_I(len(v)) + v)
    elif t is tuple:
        _enc_value(list(v), out)
    elif isinstance(v, (str, dict, list, float, bool, int)):
        # subclasses (Enum strs, OrderedDict, numpy-ish floats that
        # passed a float() somewhere upstream) — re-dispatch on the
        # base type so the wire form matches what json.dumps would emit
        for base, conv in ((str, str), (dict, dict), (list, list),
                           (bool, bool), (int, int), (float, float)):
            if isinstance(v, base):
                _enc_value(conv(v), out)
                return
    else:
        raise TypeError(f"cannot binary-encode {type(v)}")


def _enc_sig_tail_odd_type(d: Dict[str, Any], out: List[bytes]) -> None:
    """Tail for a record whose ``type`` is absent or non-standard: the
    raw type value rides as an extra so decode reproduces the dict
    exactly (decode adds no "type" key for code 2)."""
    _enc_rawstr_nc(d["uuid"], out)
    _enc_value(d.get("option"), out)
    extras = [(k, v) for k, v in d.items() if k not in _SIG_KEYS]
    if "type" in d:
        extras.append(("type", d["type"]))
    if len(extras) > 255:
        raise TypeError("signal dict has too many extra fields")
    out.append(bytes((len(extras),)))
    for k, v in extras:
        _enc_rawstr(k, out)
        _enc_value(v, out)


def dumps(obj: Any) -> bytes:
    """Encode one value tree into a binary frame body."""
    out: List[bytes] = [_HEADER]
    _enc_value(obj, out)
    return b"".join(out)


# -- decode ----------------------------------------------------------------

_dec_cache: Dict[bytes, str] = {}


def _dec_rawstr(b: bytes, o: int):
    n = b[o]
    o += 1
    if n == 255:
        (n,) = _unpack_I(b, o)
        o += 4
    end = o + n
    raw = b[o:end]
    if n <= 32:
        # keys / class / entity names repeat across a batch; uuids
        # (36 bytes) deliberately sit above the cap
        s = _dec_cache.get(raw)
        if s is None:
            s = raw.decode()
            if len(_dec_cache) >= _CACHE_CAP:
                _dec_cache.clear()
            _dec_cache[raw] = s
        return s, end
    return raw.decode(), end


def _dec_sig_tail(b: bytes, o: int, type_name, cls: str, ent: str,
                  ctx):
    """One signal record's uuid/option/extras -> (dict, offset)."""
    uuid, o = _dec_rawstr(b, o)
    option, o = _dec_value(b, o)
    d: Dict[str, Any] = {"class": cls, "entity": ent, "uuid": uuid,
                         "option": option}
    if type_name is not None:
        d["type"] = type_name
    if ctx is not None:
        d["ctx"] = ctx
    n_extras = b[o]
    o += 1
    for _ in range(n_extras):
        k, o = _dec_rawstr(b, o)
        d[k], o = _dec_value(b, o)
    return d, o


def _dec_value(b: bytes, o: int):
    t = b[o]
    o += 1
    if t == 0x08:
        n = b[o]
        o += 1
        end = o + n
        return b[o:end].decode(), end
    if t == 0x10:
        code = b[o]
        o += 1
        cls, o = _dec_rawstr(b, o)
        ent, o = _dec_rawstr(b, o)
        return _dec_sig_tail(b, o, _TYPE_NAMES.get(code), cls, ent,
                             None)
    if t == 0x11:
        (n,) = _unpack_I(b, o)
        o += 4
        code = b[o]
        o += 1
        cls, o = _dec_rawstr(b, o)
        ent, o = _dec_rawstr(b, o)
        ctx, o = _dec_value(b, o)
        type_name = _TYPE_NAMES.get(code)
        items = []
        for _ in range(n):
            d, o = _dec_sig_tail(b, o, type_name, cls, ent, ctx)
            items.append(d)
        return items, o
    if t == 0x0b:
        (n,) = _unpack_I(b, o)
        o += 4
        d = {}
        for _ in range(n):
            k, o = _dec_rawstr(b, o)
            d[k], o = _dec_value(b, o)
        return d, o
    if t == 0x0a:
        (n,) = _unpack_I(b, o)
        o += 4
        items = []
        append = items.append
        for _ in range(n):
            v, o = _dec_value(b, o)
            append(v)
        return items, o
    if t == 0x07:
        return _unpack_d(b, o)[0], o + 8
    if t == 0x03:
        return int.from_bytes(b[o:o + 1], "little", signed=True), o + 1
    if t == 0x04:
        return _unpack_i(b, o)[0], o + 4
    if t == 0x05:
        return _unpack_q(b, o)[0], o + 8
    if t == 0x06:
        (n,) = _unpack_I(b, o)
        o += 4
        return int.from_bytes(b[o:o + n], "little", signed=True), o + n
    if t == 0x09:
        (n,) = _unpack_I(b, o)
        o += 4
        end = o + n
        return b[o:end].decode(), end
    if t == 0x00:
        return None, o
    if t == 0x01:
        return True, o
    if t == 0x02:
        return False, o
    if t == 0x0c:
        (n,) = _unpack_I(b, o)
        o += 4
        return b[o:o + n], o + n
    raise ValueError(f"unknown binary tag 0x{t:02x} at offset {o - 1}")


def loads(data: bytes) -> Any:
    """Decode one binary frame body; raises ValueError on anything
    malformed — wrong magic, truncation, garbled tags. The error is a
    per-FRAME condition: the transports answer/400 it and keep the
    connection, because the length prefix that delimited this frame
    was intact."""
    if len(data) < 4 or data[:2] != MAGIC:
        raise ValueError("not a binary frame (bad magic)")
    if data[2] != VERSION:
        raise ValueError(f"unsupported binary codec version {data[2]}")
    try:
        value, end = _dec_value(data, 4)
    except (IndexError, struct.error, UnicodeDecodeError) as e:
        raise ValueError(f"garbled binary frame: {e}") from None
    if end != len(data):
        raise ValueError(
            f"garbled binary frame: {len(data) - end} trailing byte(s)")
    return value
