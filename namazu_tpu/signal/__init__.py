"""Signal model: typed Events and Actions shared by every layer.

Capability-equivalent to the reference's ``nmz/signal`` package
(/root/reference/nmz/signal/signal.go:75-191, interface.go:8-82): events flow
from inspectors up to the orchestrator's policy; actions flow back down.
Unlike the reference's map-backed reflection design, signals here are plain
Python classes with a declarative ``OPTION_FIELDS`` schema and a class
registry used by the JSON wire codec.
"""

from namazu_tpu.signal.base import (
    Signal,
    SignalType,
    register_signal_class,
    signal_class,
    get_signal_class,
    known_signal_classes,
    signal_from_jsonable,
    signal_from_json,
)
from namazu_tpu.signal.event import (
    Event,
    NopEvent,
    PacketEvent,
    FilesystemEvent,
    FilesystemOp,
    ProcSetEvent,
    FunctionEvent,
    FunctionType,
    LogEvent,
)
from namazu_tpu.signal.action import (
    Action,
    NopAction,
    EventAcceptanceAction,
    PacketFaultAction,
    FilesystemFaultAction,
    ProcSetSchedAction,
    ShellAction,
)
from namazu_tpu.signal.control import Control, ControlOp

__all__ = [
    "Signal",
    "SignalType",
    "register_signal_class",
    "signal_class",
    "get_signal_class",
    "known_signal_classes",
    "signal_from_jsonable",
    "signal_from_json",
    "Event",
    "NopEvent",
    "PacketEvent",
    "FilesystemEvent",
    "FilesystemOp",
    "ProcSetEvent",
    "FunctionEvent",
    "FunctionType",
    "LogEvent",
    "Action",
    "NopAction",
    "EventAcceptanceAction",
    "PacketFaultAction",
    "FilesystemFaultAction",
    "ProcSetSchedAction",
    "ShellAction",
    "Control",
    "ControlOp",
]
