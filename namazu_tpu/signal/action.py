"""Action classes — what the policy decides to do with each event.

Capability parity with /root/reference/nmz/signal/action*.go. Every action
records the uuid and class of its cause event so the inspector-side
transceiver can correlate it back to the blocked operation
(/root/reference/nmz/signal/action.go:50-67 reconstructs a dummy event from
``event_uuid`` — here we carry ``event_uuid``/``event_entity`` as first-class
fields instead).

Actions are either *propagated* back to the inspector (accept, fault) or
*orchestrator-side* (nop, shell): executed in the orchestrator process and
recorded in the trace only (parity: OrchestratorSideAction,
/root/reference/nmz/signal/interface.go:73-82).
"""

from __future__ import annotations

import subprocess
import time
from typing import Any, Dict, Optional

from namazu_tpu.obs import spans as obs_spans
from namazu_tpu.signal.base import Signal, SignalType, signal_class
from namazu_tpu.signal.event import Event


class Action(Signal):
    """Base action. Parity: Action interface
    (/root/reference/nmz/signal/interface.go:41-62)."""

    #: True if this action executes inside the orchestrator and is never
    #: sent back over the wire.
    ORCHESTRATOR_SIDE_ONLY: bool = False

    def __init__(
        self,
        entity_id: str,
        option: Optional[Dict[str, Any]] = None,
        uuid: Optional[str] = None,
        event_uuid: str = "",
        event_class: str = "",
        event_hint: str = "",
        event_arrived: Optional[float] = None,
    ):
        super().__init__(entity_id=entity_id, option=option, uuid=uuid)
        self.event_uuid = event_uuid
        self.event_class = event_class
        # the cause event's semantic replay hint, preserved so recorded
        # traces keep the identity the search plane / replay keys on (the
        # reference loses this: its traces are action-only gobs)
        self.event_hint = event_hint
        # when the cause event ARRIVED at the orchestrator (reference:
        # BasicSignal.Arrived, /root/reference/nmz/signal/signal.go:75-191)
        # — unlike triggered_time this excludes the policy's own injected
        # delay, so the search plane's counterfactual anchors on the
        # interleaving the system produced, not on the recording policy's
        # jitter (ops/trace_encoding.encode_trace prefers it)
        self.event_arrived = event_arrived
        self.triggered_time: Optional[float] = None

    @classmethod
    def signal_type(cls) -> SignalType:
        return SignalType.ACTION

    @classmethod
    def for_event(cls, event: Event, option: Optional[Dict[str, Any]] = None) -> "Action":
        """Construct an action answering ``event``."""
        action = cls(
            entity_id=event.entity_id,
            option=option,
            event_uuid=event.uuid,
            event_class=event.class_name(),
            event_hint=event.replay_hint(),
            event_arrived=event.arrived,
        )
        # lifecycle spans survive the event -> action hand-off so the
        # dispatch/ack stages can report end-to-end latencies
        obs_spans.carry(action, event)
        # so does the tenancy namespace (doc/tenancy.md): the action
        # must route/record/poll under its cause event's run, and this
        # is the one choke point every policy's action minting crosses
        ns = getattr(event, "_ns", "")
        if ns:
            action._ns = ns
        return action

    def mark_triggered(self, now: Optional[float] = None) -> None:
        self.triggered_time = time.time() if now is None else now

    @property
    def orchestrator_side_only(self) -> bool:
        return self.ORCHESTRATOR_SIDE_ONLY

    def execute_on_orchestrator(self) -> None:
        """Run the orchestrator-side effect. Only called when
        ``orchestrator_side_only`` is True."""
        raise NotImplementedError

    def equals(self, other: Signal) -> bool:
        return (
            super().equals(other)
            and isinstance(other, Action)
            and self.event_class == other.event_class
        )

    # -- wire codec ------------------------------------------------------

    def to_jsonable(self) -> Dict[str, Any]:
        d = super().to_jsonable()
        if self.event_uuid:
            d["event_uuid"] = self.event_uuid
        if self.event_class:
            d["event_class"] = self.event_class
        if self.event_hint:
            d["event_hint"] = self.event_hint
        if self.event_arrived is not None:
            d["event_arrived"] = self.event_arrived
        return d

    @classmethod
    def from_jsonable(cls, d: Dict[str, Any]) -> "Action":
        return cls(
            entity_id=d["entity"],
            option=d.get("option") or {},
            uuid=d.get("uuid"),
            event_uuid=d.get("event_uuid", ""),
            event_class=d.get("event_class", ""),
            event_hint=d.get("event_hint", ""),
            event_arrived=d.get("event_arrived"),
        )


@signal_class
class NopAction(Action):
    """Do nothing; recorded in the trace only.

    Parity: action_nop.go:23-49 (orchestrator-side, not propagated).
    """

    ORCHESTRATOR_SIDE_ONLY = True

    def execute_on_orchestrator(self) -> None:
        pass


@signal_class
class EventAcceptanceAction(Action):
    """Release a deferred event now — THE scheduling primitive.

    Parity: action_accept_event.go:25-43. The moment this action reaches the
    inspector determines where the deferred operation lands in the global
    interleaving.
    """


@signal_class
class PacketFaultAction(Action):
    """Drop the intercepted packet (parity: action_fault_packet.go:29-46)."""


@signal_class
class FilesystemFaultAction(Action):
    """Fail the intercepted filesystem op with EIO
    (parity: action_fault_filesystem.go:29-46)."""


@signal_class
class ProcSetSchedAction(Action):
    """Set per-PID scheduler attributes on the testee's threads.

    Parity: action_sched_procset.go:9-36, carrying a map pid ->
    sched-attr dict (policy name, nice, rt priority, deadline params)
    applied by the proc inspector via sched_setattr(2).
    """

    OPTION_FIELDS = {"attrs": True}

    @classmethod
    def for_procset(cls, event: Event, attrs: Dict[str, Dict[str, Any]]) -> "ProcSetSchedAction":
        return cls.for_event(event, option={"attrs": attrs})

    @property
    def attrs(self) -> Dict[str, Dict[str, Any]]:
        return self.option["attrs"]


@signal_class
class ShellAction(Action):
    """Run an arbitrary shell command in the orchestrator (crash/fault
    injection). Blocking, parity: action_shell.go:38-67.
    """

    ORCHESTRATOR_SIDE_ONLY = True
    OPTION_FIELDS = {"command": True}

    @classmethod
    def create(cls, command: str, comments: Optional[Dict[str, Any]] = None) -> "ShellAction":
        opt: Dict[str, Any] = {"command": command}
        if comments:
            opt["comments"] = comments
        return cls(entity_id="_shell", option=opt)

    @property
    def command(self) -> str:
        return self.option["command"]

    def execute_on_orchestrator(self) -> None:
        # Blocking by design, like the reference: the experiment script is
        # expected to keep injected commands short.
        subprocess.run(
            self.command,
            shell=True,
            check=False,
            capture_output=True,
        )
