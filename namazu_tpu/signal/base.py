"""Base Signal class, class registry, and JSON wire codec.

Capability parity with /root/reference/nmz/signal/signal.go (BasicSignal,
RegisterSignalClass, NewSignalFromJSONString) — redesigned: instead of a
``map[string]interface{}`` plus reflection, each signal class declares its
option schema via ``OPTION_FIELDS`` and the registry is populated by a class
decorator. The wire format is JSON with the same conceptual fields as the
reference's doc/schema/{event,action}.json: type, class, entity, uuid,
option.
"""

from __future__ import annotations

import json
import os
import random as _random
import threading
import time
import uuid as uuid_mod
from enum import Enum
from typing import Any, Dict, Iterable, Optional, Type


# -- uuid minting ---------------------------------------------------------
#
# Every signal mints a uuid, which makes uuid cost part of the event
# plane's per-event budget. ``uuid.uuid4()`` draws from os.urandom —
# one syscall per id, and on some kernels/containers that syscall runs
# hundreds of µs, at which point it dominates the entire serving path
# (it was ~90% of the per-event cost on the 2-core loopback rig,
# bench.py --pipeline). Signal uuids are correlation keys, not security
# tokens: mint them from a process-local PRNG seeded ONCE from
# os.urandom + pid (so forked children and parallel processes diverge),
# formatted as canonical RFC-4122 v4 strings for wire compatibility.
# 128 random bits keep collisions as improbable as uuid4's.

def _seed_uuid_rng() -> None:
    global _uuid_bits
    _uuid_bits = _random.Random(
        int.from_bytes(os.urandom(16), "big") ^ (os.getpid() << 96)
        ^ threading.get_ident()).getrandbits


_seed_uuid_rng()
# re-seed after fork (no per-call getpid syscall): two children must
# not replay one uuid stream
if hasattr(os, "register_at_fork"):  # pragma: no branch
    os.register_at_fork(after_in_child=_seed_uuid_rng)


def fast_uuid4() -> str:
    """A random uuid string without the per-call urandom syscall."""
    h = "%032x" % _uuid_bits(128)
    # canonical v4 layout (version + variant nibbles), same shape
    # uuid.uuid4() serializes to
    return (f"{h[:8]}-{h[8:12]}-4{h[13:16]}-"
            f"{'89ab'[int(h[16], 16) & 3]}{h[17:20]}-{h[20:32]}")


# Version tag of the replay-hint format (the strings replay_hint()
# methods below produce, whose fnv64a hashes build the search plane's
# bucket space). Bump whenever hint derivation changes in a way that
# re-buckets events — it invalidates every delay table, archive feature,
# checkpoint, and recorded history: "flow-v2" = packet hints are
# flow-qualified ("src->dst:<content>", event.py PacketEvent.replay_hint).
# Artifacts from other spaces are rejected at load (models/search.py,
# policy/tpu.py) rather than silently delivering arbitrary delays.
HINT_SPACE = "flow-v2"


class SignalType(str, Enum):
    EVENT = "event"
    ACTION = "action"


class SignalError(Exception):
    """Raised on malformed or unregistered signals."""


_REGISTRY: Dict[str, Type["Signal"]] = {}


def register_signal_class(cls: Type["Signal"]) -> Type["Signal"]:
    """Register a concrete signal class under ``cls.class_name()``.

    Parity: RegisterSignalClass (/root/reference/nmz/signal/signal.go:47-63),
    which also gob-registers; JSON is our single serialization so there is
    only one registry.
    """
    name = cls.class_name()
    existing = _REGISTRY.get(name)
    if existing is not None and existing is not cls:
        raise SignalError(f"signal class {name!r} already registered as {existing!r}")
    _REGISTRY[name] = cls
    return cls


def signal_class(cls: Type["Signal"]) -> Type["Signal"]:
    """Class decorator alias of :func:`register_signal_class`."""
    return register_signal_class(cls)


def get_signal_class(name: str) -> Type["Signal"]:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise SignalError(f"unknown signal class {name!r}") from None


def known_signal_classes() -> Iterable[str]:
    return sorted(_REGISTRY)


class Signal:
    """A typed message exchanged between inspectors and the orchestrator.

    Core attributes (parity with BasicSignal getters,
    /root/reference/nmz/signal/signal.go:100-191):

    * ``uuid``      — unique id; excluded from equality.
    * ``entity_id`` — the inspector ("entity") this signal belongs to.
    * ``option``    — class-specific payload dict (validated against
      ``OPTION_FIELDS``).
    * ``arrived``   — wall-clock arrival timestamp set by the receiving side;
      excluded from equality and from the wire format.
    """

    #: mapping option-field name -> (required: bool). Subclasses override.
    OPTION_FIELDS: Dict[str, bool] = {}

    def __init__(
        self,
        entity_id: str,
        option: Optional[Dict[str, Any]] = None,
        uuid: Optional[str] = None,
    ):
        self.entity_id = str(entity_id)
        self.option: Dict[str, Any] = dict(option or {})
        self.uuid = uuid or fast_uuid4()
        self.arrived: Optional[float] = None
        self._validate_option()

    # -- schema ----------------------------------------------------------

    def _validate_option(self) -> None:
        for field, required in self.OPTION_FIELDS.items():
            if required and field not in self.option:
                raise SignalError(
                    f"{self.class_name()}: missing required option {field!r}"
                )

    @classmethod
    def class_name(cls) -> str:
        return cls.__name__

    @classmethod
    def signal_type(cls) -> SignalType:
        raise NotImplementedError

    # -- lifecycle -------------------------------------------------------

    def mark_arrived(self, now: Optional[float] = None) -> None:
        self.arrived = time.time() if now is None else now

    # -- equality --------------------------------------------------------

    def equals(self, other: "Signal") -> bool:
        """Structural equality ignoring uuid and arrival time.

        Parity: EqualsSignal (/root/reference/nmz/signal/signal.go:148-170).
        """
        return (
            type(self) is type(other)
            and self.entity_id == other.entity_id
            and self.option == other.option
        )

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"<{self.class_name()} entity={self.entity_id!r} "
            f"uuid={self.uuid[:8]} option={self.option!r}>"
        )

    # -- wire codec ------------------------------------------------------

    def to_jsonable(self) -> Dict[str, Any]:
        d = {
            "type": self.signal_type().value,
            "class": self.class_name(),
            "entity": self.entity_id,
            "uuid": self.uuid,
            "option": self.option,
        }
        # causality-plane span context (obs/context.py): attached by
        # the transceiver/hub when observability is on; riding the one
        # signal codec means it survives EVERY wire that carries
        # signals — batch routes, uds frames, edge backhaul, the crash
        # journal, reconnect replays — without per-wire plumbing. The
        # context IS its wire dict, so this is an attribute move.
        ctx = getattr(self, "_obs_ctx", None)
        if ctx is not None:
            d["ctx"] = ctx
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_jsonable(), sort_keys=True)


def signal_from_jsonable(d: Dict[str, Any]) -> "Signal":
    """Decode one wire dict into a concrete registered signal instance.

    Parity: NewSignalFromJSONString
    (/root/reference/nmz/signal/signal.go:193-243).
    """
    try:
        cls = get_signal_class(d["class"])
    except KeyError:
        raise SignalError(f"signal dict missing 'class': {d!r}") from None
    declared = d.get("type")
    if declared is not None and declared != cls.signal_type().value:
        raise SignalError(
            f"type mismatch: wire says {declared!r}, "
            f"{cls.class_name()} is {cls.signal_type().value!r}"
        )
    sig = cls.from_jsonable(d)
    sig.mark_arrived()
    ctx = d.get("ctx")
    if type(ctx) is dict:
        # restore the span context (an attribute move — the context IS
        # its wire dict; decode is PURE, the clock merge happens at the
        # hub/framed-server choke points, not per parse)
        sig._obs_ctx = ctx
    return sig


def signal_from_json(s: str) -> "Signal":
    return signal_from_jsonable(json.loads(s))
