"""Control messages: enable/disable orchestration at runtime.

Parity: Control (/root/reference/nmz/signal/interface.go:64-71) and the REST
``POST /api/v3/control?op=...`` endpoint. When orchestration is disabled the
orchestrator routes every event to the always-on passthrough (dumb) policy so
the system-under-test keeps running at native speed.
"""

from __future__ import annotations

from enum import Enum


class ControlOp(str, Enum):
    ENABLE_ORCHESTRATION = "enableOrchestration"
    DISABLE_ORCHESTRATION = "disableOrchestration"


class Control:
    def __init__(self, op: ControlOp):
        self.op = ControlOp(op)

    def __repr__(self) -> str:  # pragma: no cover
        return f"<Control {self.op.value}>"
