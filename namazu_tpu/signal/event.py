"""Event classes — what inspectors observe and defer.

Capability parity with /root/reference/nmz/signal/event*.go. Each event
declares whether it is *deferred* (the inspector blocks the intercepted
operation until the orchestrator answers) and contributes a *replay hint*:
a stable string derived only from semantic fields (never uuid or timing,
per the contract in /root/reference/nmz/signal/interface.go:24-31) so a
winning schedule can be replayed deterministically by hashing hints.
"""

from __future__ import annotations

import base64
from enum import Enum
from typing import Any, Dict, List, Optional, Sequence

from namazu_tpu.signal.base import Signal, SignalType, signal_class


class Event(Signal):
    """Base event. Parity: Event interface
    (/root/reference/nmz/signal/interface.go:8-39)."""

    #: whether the inspector blocks the intercepted op awaiting an action.
    DEFERRED: bool = False

    @classmethod
    def signal_type(cls) -> SignalType:
        return SignalType.EVENT

    @property
    def deferred(self) -> bool:
        return self.DEFERRED

    def replay_hint(self) -> str:
        """Stable semantic hash input. Empty string = no hint (events with
        no semantic identity, e.g. Nop)."""
        return ""

    #: lazily-cached (EventAcceptanceAction, NopAction) — the import
    #: cannot run at module load (action.py imports this module), and a
    #: per-call import costs µs on a path the event plane pays per event
    _DEFAULT_ACTION_CLASSES = None

    def default_action(self) -> "Action":
        """The action a policy should emit when it has no opinion.

        Parity: BasicEvent.DefaultAction
        (/root/reference/nmz/signal/event.go:40-55): accept if deferred,
        else no-op.
        """
        classes = Event._DEFAULT_ACTION_CLASSES
        if classes is None:
            from namazu_tpu.signal.action import (
                EventAcceptanceAction,
                NopAction,
            )

            classes = Event._DEFAULT_ACTION_CLASSES = (
                EventAcceptanceAction, NopAction)
        if self.deferred:
            return classes[0].for_event(self)
        return classes[1].for_event(self)

    def default_fault_action(self) -> Optional["Action"]:
        """The fault this event supports, or None."""
        return None

    def to_jsonable(self) -> Dict[str, Any]:
        # ``deferred`` rides the wire (doc/schema/event.json, parity with
        # the reference's schema): a consumer that does not know the
        # class can still tell whether the sender is blocked awaiting an
        # action. Decode ignores it — the registered class is
        # authoritative.
        d = super().to_jsonable()
        d["deferred"] = self.deferred
        return d

    @classmethod
    def from_jsonable(cls, d: Dict[str, Any]) -> "Event":
        return cls(
            entity_id=d["entity"],
            option=d.get("option") or {},
            uuid=d.get("uuid"),
        )


@signal_class
class NopEvent(Event):
    """Placeholder / testing event (parity: event_nop.go:20-39)."""

    DEFERRED = False


@signal_class
class PacketEvent(Event):
    """An intercepted network message between two entities.

    Parity: PacketEvent (/root/reference/nmz/signal/event_packet.go:25-46).
    ``payload`` is carried base64-encoded in the option dict so the wire
    format stays pure JSON.
    """

    DEFERRED = True
    OPTION_FIELDS = {"src_entity": True, "dst_entity": True}

    @classmethod
    def create(
        cls,
        entity_id: str,
        src_entity: str,
        dst_entity: str,
        payload: bytes = b"",
        hint: str = "",
    ) -> "PacketEvent":
        opt: Dict[str, Any] = {
            "src_entity": src_entity,
            "dst_entity": dst_entity,
        }
        if payload:
            opt["payload_b64"] = base64.b64encode(payload).decode("ascii")
        if hint:
            opt["replay_hint"] = hint
        event = cls(entity_id=entity_id, option=opt)
        # derive the replay hint eagerly — the flow parts are in hand
        # as locals, and the serving plane would otherwise pay the
        # option-dict lookups + f-string on its decision path
        # (replay_hint() memoizes into the same slot for events built
        # off the wire)
        event._rh = (f"{src_entity}->{dst_entity}:{hint}" if hint
                     else f"packet:{src_entity}->{dst_entity}")
        return event

    @property
    def payload(self) -> bytes:
        b64 = self.option.get("payload_b64", "")
        return base64.b64decode(b64) if b64 else b""

    def replay_hint(self) -> str:
        # A packet's replay identity is (flow, semantic content): the
        # SAME protocol message to two different receivers must live in
        # different delay buckets — per-destination delivery timing is
        # what decides e.g. a leader election (ZOOKEEPER-2212: the
        # outcome turns on WHICH decider saw the newest-zxid notification
        # before its window closed). Semantic parsers provide the
        # content half; the flow is prefixed here so every packet hint is
        # destination-resolved, and the searched delay table can delay
        # src->A independently of src->B.
        #
        # Memoized per instance (``_rh``): the hint is a pure function
        # of the immutable option dict, and the serving plane resolves
        # it on every decision — the edge burst path reads the memo
        # slot directly (inspector/edge.py), so this f-string work runs
        # once per event, not once per lookup.
        memo = self.__dict__.get("_rh")
        if memo is not None:
            return memo
        flow = (f"{self.option['src_entity']}->"
                f"{self.option['dst_entity']}")
        explicit = self.option.get("replay_hint")
        if explicit:
            self._rh = hint = f"{flow}:{explicit}"
            return hint
        self._rh = hint = f"packet:{flow}"
        return hint

    def default_fault_action(self):
        from namazu_tpu.signal.action import PacketFaultAction

        return PacketFaultAction.for_event(self)


class FilesystemOp(str, Enum):
    """Hooked filesystem operations (parity: event_filesystem.go:21-38)."""

    POST_READ = "post-read"
    POST_OPENDIR = "post-opendir"
    PRE_WRITE = "pre-write"
    PRE_MKDIR = "pre-mkdir"
    PRE_RMDIR = "pre-rmdir"
    PRE_FSYNC = "pre-fsync"


@signal_class
class FilesystemEvent(Event):
    """An intercepted filesystem operation (parity: event_filesystem.go:21-59)."""

    DEFERRED = True
    OPTION_FIELDS = {"op": True, "path": True}

    @classmethod
    def create(cls, entity_id: str, op: FilesystemOp, path: str) -> "FilesystemEvent":
        return cls(
            entity_id=entity_id,
            option={"op": FilesystemOp(op).value, "path": path},
        )

    @property
    def op(self) -> FilesystemOp:
        return FilesystemOp(self.option["op"])

    @property
    def path(self) -> str:
        return self.option["path"]

    def replay_hint(self) -> str:
        return f"fs:{self.option['op']}:{self.option['path']}"

    def default_fault_action(self):
        from namazu_tpu.signal.action import FilesystemFaultAction

        return FilesystemFaultAction.for_event(self)


@signal_class
class ProcSetEvent(Event):
    """A snapshot of the system-under-test's process/thread set.

    Parity: ProcSetEvent (/root/reference/nmz/signal/event_procset.go:21-42).
    Non-deferred: the proc inspector does not block the testee; it awaits
    the answering ProcSetSchedAction out-of-band.
    """

    DEFERRED = False
    OPTION_FIELDS = {"procs": True}

    @classmethod
    def create(cls, entity_id: str, pids: Sequence[int]) -> "ProcSetEvent":
        return cls(
            entity_id=entity_id,
            option={"procs": [str(int(p)) for p in pids]},
        )

    @property
    def pids(self) -> List[int]:
        return [int(p) for p in self.option["procs"]]

    def replay_hint(self) -> str:
        # PID values are not stable across runs; only the set size is.
        return f"procset:{self.entity_id}:{len(self.option['procs'])}"


class FunctionType(str, Enum):
    CALL = "call"
    RETURN = "return"


@signal_class
class FunctionEvent(Event):
    """A function call/return intercepted inside the testee process.

    Unifies the reference's JavaFunctionEvent and CFunctionEvent
    (/root/reference/nmz/signal/event_function.go:36-129) under one class
    with a ``runtime`` discriminator ("java", "c", "python", ...). Emitted
    by in-process guest agents over the framed TCP endpoint.
    """

    DEFERRED = True
    OPTION_FIELDS = {"func_name": True, "func_type": True, "runtime": True}

    @classmethod
    def create(
        cls,
        entity_id: str,
        func_name: str,
        func_type: FunctionType = FunctionType.CALL,
        runtime: str = "python",
        thread_name: str = "",
        params: Optional[Dict[str, str]] = None,
        stacktrace: Optional[List[str]] = None,
    ) -> "FunctionEvent":
        opt: Dict[str, Any] = {
            "func_name": func_name,
            "func_type": FunctionType(func_type).value,
            "runtime": runtime,
        }
        if thread_name:
            opt["thread_name"] = thread_name
        if params:
            opt["params"] = dict(params)
        if stacktrace:
            opt["stacktrace"] = list(stacktrace)
        return cls(entity_id=entity_id, option=opt)

    @property
    def func_name(self) -> str:
        return self.option["func_name"]

    @property
    def thread_name(self) -> str:
        return self.option.get("thread_name", "")

    def replay_hint(self) -> str:
        return (
            f"fn:{self.option['runtime']}:{self.option['func_name']}"
            f":{self.option['func_type']}:{self.option.get('thread_name', '')}"
        )


@signal_class
class LogEvent(Event):
    """An observed log line (observation-only, never deferred).

    Parity: LogEvent (/root/reference/nmz/signal/event_log.go:17-23 and
    misc/pynmz/signal/event.py:28-43).
    """

    DEFERRED = False
    OPTION_FIELDS = {"line": True}

    @classmethod
    def create(cls, entity_id: str, line: str) -> "LogEvent":
        return cls(entity_id=entity_id, option={"line": line})

    @property
    def line(self) -> str:
        return self.option["line"]
