"""Calibration plane: land every scenario's random-baseline repro rate
in the band where search pays (doc/observability.md "Calibration &
progress").

* :mod:`namazu_tpu.calibrate.artifact` — the ``calibration.json``
  contract (``nmz-calib-v1``): knob values as provenance, the probe
  journal, the sequential-vs-fixed-N budget ledger, and the
  ``NMZ_CALIB_*`` environment transport every experiment script reads;
* :mod:`namazu_tpu.calibrate.harness` — the ``tools calibrate`` sweep:
  per-probe supervised campaigns early-stopped by the band SPRT
  (obs/stats.py), log-space bisection over the declared knob axis.

Only the artifact module is imported eagerly — the harness pulls in the
campaign supervisor, which ``run``-path consumers (cli/run_cmd.py) must
not pay for just to read an artifact.
"""

from namazu_tpu.calibrate.artifact import (  # noqa: F401
    ARTIFACT_NAME,
    ENV_PREFIX,
    SCHEMA,
    env_name,
    knob_env,
    load_calibration,
    validate,
)

__all__ = [
    "ARTIFACT_NAME", "ENV_PREFIX", "SCHEMA",
    "env_name", "knob_env", "load_calibration", "validate",
]
