"""The calibration harness: sweep an example's timing knobs until the
random-policy baseline repro rate lands in the target band.

RESULTS.md's cross-scenario finding is the motivation: searched
schedules pay ~15x where the random baseline's repro rate is RARE
(the 2-10% band) and lose where random trivially repros — so a
scenario's value depends on timing constants nobody wants to hand-tune
(the zk-election decision window was hand-calibrated across four
commits). ``nmz-tpu tools calibrate <example>`` automates that search:

* the example declares its knobs in a ``[calibration]`` config table
  (``[[calibration.knob]]``: name, min, max, direction) — see
  examples/template/config.toml;
* each probe point runs a short supervised campaign
  (namazu_tpu/campaign.py) with the knob candidates exported as
  ``NMZ_CALIB_<NAME>`` environment, feeding every run outcome into a
  :class:`~namazu_tpu.obs.stats.BandSPRT`; the campaign early-stops the
  moment the SPRT concludes (the ``on_slot`` hook), so cheap verdicts
  ("this knob value trivially repros") cost ~10 runs, not the full cap;
* the sweep walks ONE shared effort axis ``e in [0, 1]`` mapped through
  each knob's range in log space (``direction = "up"``: a larger value
  means more contention, a higher repro rate; ``"down"``: smaller means
  higher) — probe the midpoint first, jump coarse to the indicated
  endpoint when the midpoint is out of band, then bisect the bracketing
  interval. **Monotone assumption**, documented and load-bearing: the
  repro rate is assumed monotone in the effort axis; a non-monotone
  knob (a resonance window) can defeat the bisection, which is why the
  artifact journals every probe — a failed sweep shows its work;
* after every probe the artifact (calibrate/artifact.py) is atomically
  rewritten with ``status: "in_progress"`` — a killed sweep leaves a
  readable journal, and rerunning resumes from scratch deterministically
  (same seed, same probes).

The budget ledger in the artifact compares ``runs_spent`` against
``fixed_n_equivalent``: probes x :func:`~namazu_tpu.obs.stats.
runs_for_ci_width` at the band's geometric midpoint for the band's
width — the fixed-sample size a test of the same discriminating power
would burn per probe. The SPRT's early stopping is what makes
calibration affordable; CI asserts the savings stay >= 30%.
"""

from __future__ import annotations

import math
import os
import shutil
import sys
import tempfile
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from namazu_tpu.calibrate import artifact
from namazu_tpu.obs import stats
from namazu_tpu.utils.atomic import atomic_write_json
from namazu_tpu.utils.log import get_logger

log = get_logger("calibrate.harness")

#: probe-count cap: bisection over a 1-D effort axis converges in
#: log2(range resolution) steps; 8 probes resolve the axis to 1/64
DEFAULT_MAX_PROBES = 8
#: per-probe run cap (the BandSPRT's point-estimate fallback budget)
DEFAULT_MAX_RUNS = 40


class CalibrationError(Exception):
    pass


@dataclass
class KnobSpec:
    """One tunable timing knob from ``[[calibration.knob]]``."""

    name: str
    lo: float
    hi: float
    #: "up" = a larger value raises the repro rate (a wider preemption
    #: window), "down" = a smaller value raises it (a tighter decision
    #: deadline)
    direction: str = "up"
    #: render calibrated values as integers (iteration counts, ms)
    integer: bool = True

    def __post_init__(self) -> None:
        if not (0 < self.lo < self.hi):
            raise CalibrationError(
                f"knob {self.name!r} needs 0 < min < max, got "
                f"[{self.lo}, {self.hi}]")
        if self.direction not in ("up", "down"):
            raise CalibrationError(
                f"knob {self.name!r} direction must be 'up' or 'down', "
                f"got {self.direction!r}")

    def value_at(self, effort: float):
        """The knob value at effort ``e in [0, 1]`` (0 = lowest
        expected repro rate, 1 = highest), interpolated in log space."""
        e = min(1.0, max(0.0, effort))
        if self.direction == "down":
            e = 1.0 - e
        v = math.exp(math.log(self.lo)
                     + e * (math.log(self.hi) - math.log(self.lo)))
        return int(round(v)) if self.integer else round(v, 6)


@dataclass
class CalibrationSpec:
    """Everything the ``[calibration]`` config table declares."""

    knobs: List[KnobSpec]
    band: Tuple[float, float] = stats.DEFAULT_BAND
    alpha: float = stats.DEFAULT_ALPHA
    beta: float = stats.DEFAULT_BETA
    max_runs_per_probe: int = DEFAULT_MAX_RUNS
    max_probes: int = DEFAULT_MAX_PROBES
    extra: Dict[str, Any] = field(default_factory=dict)


def parse_calibration(cfg) -> CalibrationSpec:
    """The example config's ``[calibration]`` table as a spec
    (raises :class:`CalibrationError` when absent or malformed)."""
    table = cfg.get("calibration")
    if not isinstance(table, dict):
        raise CalibrationError(
            "the config declares no [calibration] table; add one with "
            "[[calibration.knob]] entries (see examples/template)")
    raw_knobs = table.get("knob") or []
    if not isinstance(raw_knobs, list) or not raw_knobs:
        raise CalibrationError(
            "[calibration] declares no [[calibration.knob]] entries")
    knobs = []
    for raw in raw_knobs:
        try:
            knobs.append(KnobSpec(
                name=str(raw["name"]),
                lo=float(raw["min"]), hi=float(raw["max"]),
                direction=str(raw.get("direction", "up")),
                integer=bool(raw.get("integer", True))))
        except KeyError as e:
            raise CalibrationError(
                f"[[calibration.knob]] entry missing {e}") from None
    band = table.get("band") or list(stats.DEFAULT_BAND)
    if len(band) != 2 or not (0.0 < band[0] < band[1] < 1.0):
        raise CalibrationError(f"bad calibration band {band!r}")
    return CalibrationSpec(
        knobs=knobs,
        band=(float(band[0]), float(band[1])),
        alpha=float(table.get("alpha", stats.DEFAULT_ALPHA)),
        beta=float(table.get("beta", stats.DEFAULT_BETA)),
        max_runs_per_probe=int(table.get("max_runs_per_probe",
                                         DEFAULT_MAX_RUNS)),
        max_probes=int(table.get("max_probes", DEFAULT_MAX_PROBES)))


#: a probe runner feeds one probe's run outcomes into the given
#: BandSPRT (stopping when its verdict lands or the budget is gone)
ProbeRunner = Callable[[Dict[str, Any], "stats.BandSPRT"], None]


class Calibrator:
    """One calibration sweep over one example's knob axis."""

    def __init__(self, spec: CalibrationSpec, runner: ProbeRunner,
                 example: str = "", seed: Optional[int] = None,
                 out_path: str = ""):
        self.spec = spec
        self.runner = runner
        self.example = example
        self.seed = seed
        self.out_path = out_path
        self.probes: List[Dict[str, Any]] = []
        self.runs_spent = 0

    # -- the artifact ----------------------------------------------------

    def _fixed_n_equivalent(self) -> int:
        """Per-probe fixed-sample budget of equal discriminating power:
        the runs a target-CI-width test at the band's geometric midpoint
        would burn without sequential stopping."""
        lo, hi = self.spec.band
        per_probe = stats.runs_for_ci_width(math.sqrt(lo * hi),
                                            width=hi - lo)
        return (per_probe or self.spec.max_runs_per_probe) \
            * max(1, len(self.probes))

    def _doc(self, status: str,
             landed: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        fixed_n = self._fixed_n_equivalent()
        saved = max(0, fixed_n - self.runs_spent)
        doc: Dict[str, Any] = {
            "schema": artifact.SCHEMA,
            "example": self.example,
            "status": status,
            "band": [self.spec.band[0], self.spec.band[1]],
            "alpha": self.spec.alpha,
            "beta": self.spec.beta,
            "max_runs_per_probe": self.spec.max_runs_per_probe,
            "seed": self.seed,
            "knobs": (landed or {}).get("knobs") or {},
            "rate": (landed or {}).get("rate"),
            "rate_ci95": (landed or {}).get("rate_ci95"),
            "runs": (landed or {}).get("runs"),
            "failures": (landed or {}).get("failures"),
            "verdict": (landed or {}).get("verdict"),
            "decided_by": (landed or {}).get("decided_by"),
            "probes": self.probes,
            "runs_spent": self.runs_spent,
            "fixed_n_equivalent": fixed_n,
            "runs_saved": saved,
            "runs_saved_pct": (round(100.0 * saved / fixed_n, 1)
                               if fixed_n else 0.0),
        }
        return doc

    def _journal(self, status: str,
                 landed: Optional[Dict[str, Any]] = None) -> None:
        if self.out_path:
            atomic_write_json(self.out_path, self._doc(status, landed),
                              indent=2, sort_keys=True)

    # -- probing ---------------------------------------------------------

    def _values_at(self, effort: float) -> Dict[str, Any]:
        return {k.name: k.value_at(effort) for k in self.spec.knobs}

    def _probe(self, effort: float) -> Dict[str, Any]:
        values = self._values_at(effort)
        sprt = stats.BandSPRT(lo=self.spec.band[0], hi=self.spec.band[1],
                              alpha=self.spec.alpha, beta=self.spec.beta,
                              max_runs=self.spec.max_runs_per_probe)
        log.info("probe %d: effort %.3f -> %s", len(self.probes) + 1,
                 effort, values)
        self.runner(values, sprt)
        if sprt.runs == 0:
            raise CalibrationError(
                f"probe at {values} completed 0 runs (infra trouble?)")
        if sprt.verdict is None:
            # the campaign budget ran dry before the cap (infra-class
            # slots ate it): classify the point estimate, same fallback
            # semantics as the cap
            rate = sprt.failures / sprt.runs
            sprt.verdict = ("below" if rate < self.spec.band[0]
                            else "above" if rate > self.spec.band[1]
                            else "in_band")
            sprt.decided_by = "cap"
        probe = dict(sprt.to_jsonable(), effort=round(effort, 4),
                     knobs=values)
        self.probes.append(probe)
        self.runs_spent += sprt.runs
        log.info("probe %d: rate %s over %d run(s) -> %s (%s)",
                 len(self.probes), probe["rate"], probe["runs"],
                 probe["verdict"], probe["decided_by"])
        self._journal("in_progress")
        return probe

    def run(self) -> Dict[str, Any]:
        """The sweep: midpoint, coarse endpoint jump, then bisection.
        Returns the final artifact document (also written to
        ``out_path`` when set); ``status`` is "calibrated" with the
        landed probe's knob values, or "failed" with the journal."""
        self._journal("in_progress")
        lo_e, hi_e = 0.0, 1.0
        effort = 0.5
        landed = None
        while len(self.probes) < self.spec.max_probes:
            probe = self._probe(effort)
            if probe["verdict"] == "in_band":
                landed = probe
                break
            if probe["verdict"] == "below":
                # rate below the band: more effort. Coarse-jump to the
                # max-effort endpoint before bisecting — if even that is
                # below the band, the knob range cannot reach it
                if effort >= 1.0:
                    break
                lo_e = effort
                effort = 1.0 if hi_e >= 1.0 and effort == 0.5 \
                    else (lo_e + hi_e) / 2.0
            else:  # above
                if effort <= 0.0:
                    break
                hi_e = effort
                effort = 0.0 if lo_e <= 0.0 and effort == 0.5 \
                    else (lo_e + hi_e) / 2.0
            if self._values_at(effort) == probe["knobs"]:
                # the axis has collapsed to quantized-identical values;
                # another probe cannot say anything new
                break
        status = "calibrated" if landed is not None else "failed"
        doc = self._doc(status, landed)
        self._journal(status, landed)
        if landed is None:
            log.warning("calibration failed: no in-band point in %d "
                        "probe(s); journal: %s", len(self.probes),
                        self.out_path or "(not written)")
        else:
            log.info("calibrated: %s at rate %s (saved %s%% of runs vs "
                     "fixed-N %d)", landed["knobs"], landed["rate"],
                     doc["runs_saved_pct"], doc["fixed_n_equivalent"])
        return doc


# -- probe runners ----------------------------------------------------------

def synthetic_runner(rate_fn: Callable[[Dict[str, Any]], float],
                     seed: int = 0) -> ProbeRunner:
    """A deterministic in-process probe runner for tests: outcomes are
    Bernoulli draws at ``rate_fn(knob_values)`` from a seeded RNG (one
    RNG across the whole sweep — probe order matters, as it does for
    real campaigns)."""
    import random

    rng = random.Random(seed)

    def run_probe(values: Dict[str, Any], sprt: stats.BandSPRT) -> None:
        rate = rate_fn(values)
        while sprt.verdict is None and sprt.runs < sprt.max_runs:
            sprt.update(rng.random() < rate)

    return run_probe


def campaign_probe_runner(example_dir: str,
                          config_name: str = "config.toml",
                          workdir: Optional[str] = None,
                          python: str = sys.executable,
                          seed: Optional[int] = None,
                          run_wall_deadline_s: float = 0.0,
                          keep_storages: bool = False) -> ProbeRunner:
    """The real probe runner: each probe inits a throwaway storage from
    the example and drives a supervised campaign
    (namazu_tpu/campaign.py) with the knob candidates exported as
    ``NMZ_CALIB_*`` environment; every completed run feeds the probe's
    SPRT through the ``on_slot`` hook, which stops the campaign the
    moment the verdict lands."""
    from namazu_tpu.campaign import Campaign, CampaignSpec

    example_dir = os.path.abspath(example_dir)
    config_path = os.path.join(example_dir, config_name)
    materials_dir = os.path.join(example_dir, "materials")
    if not os.path.exists(config_path):
        raise CalibrationError(f"no {config_name} in {example_dir}")
    if not os.path.isdir(materials_dir):
        raise CalibrationError(f"no materials/ in {example_dir}")

    def run_probe(values: Dict[str, Any], sprt: stats.BandSPRT) -> None:
        from namazu_tpu.cli import cli_main

        probe_dir = tempfile.mkdtemp(prefix="nmz-calib-", dir=workdir)
        storage_dir = os.path.join(probe_dir, "storage")
        try:
            rc = cli_main(["init", "--force", config_path, materials_dir,
                           storage_dir])
            if rc != 0:
                raise CalibrationError(
                    f"init failed ({rc}) for probe {values}")
            extra_env = artifact.knob_env({"knobs": values})
            seen = {"runs": 0, "failures": 0}

            def on_slot(slot, progress) -> bool:
                if progress is None:
                    return False
                new_runs = progress["runs"] - seen["runs"]
                new_fails = progress["failures"] - seen["failures"]
                seen["runs"] = progress["runs"]
                seen["failures"] = progress["failures"]
                # feed the diff in order failures-last within a slot
                # (a slot contributes at most one outcome in practice)
                for _ in range(max(0, new_runs - new_fails)):
                    sprt.update(False)
                for _ in range(max(0, new_fails)):
                    sprt.update(True)
                return sprt.verdict is not None

            campaign = Campaign(CampaignSpec(
                storage_dir=storage_dir,
                runs=sprt.max_runs,
                run_wall_deadline_s=run_wall_deadline_s,
                python=python,
                seed=seed,
                telemetry_collector="",  # probes are throwaway fleets
                extra_env=extra_env,
                on_slot=on_slot))
            campaign.run(resume=False)
        finally:
            if not keep_storages:
                shutil.rmtree(probe_dir, ignore_errors=True)

    return run_probe


def calibrate_example(example_dir: str, out_path: str = "",
                      config_name: str = "config.toml",
                      workdir: Optional[str] = None,
                      seed: Optional[int] = None,
                      band: Optional[Tuple[float, float]] = None,
                      max_runs: Optional[int] = None,
                      run_wall_deadline_s: float = 0.0) -> Dict[str, Any]:
    """``tools calibrate``'s engine: parse the example's
    ``[calibration]`` table, sweep with the campaign runner, write the
    artifact. CLI overrides (band, per-probe cap) win over the table."""
    from namazu_tpu.utils.config import Config

    example_dir = os.path.abspath(example_dir)
    cfg = Config.from_file(os.path.join(example_dir, config_name))
    spec = parse_calibration(cfg)
    if band is not None:
        spec.band = (float(band[0]), float(band[1]))
    if max_runs is not None:
        spec.max_runs_per_probe = int(max_runs)
    runner = campaign_probe_runner(
        example_dir, config_name=config_name, workdir=workdir, seed=seed,
        run_wall_deadline_s=run_wall_deadline_s)
    out_path = out_path or os.path.join(example_dir,
                                        artifact.ARTIFACT_NAME)
    calibrator = Calibrator(
        spec, runner, example=os.path.basename(example_dir.rstrip("/")),
        seed=seed, out_path=out_path)
    return calibrator.run()
