"""The calibration artifact: ``calibration.json`` (``nmz-calib-v1``).

One document is the whole contract between the calibration harness
(calibrate/harness.py) and every consumer of a calibrated scenario:

* ``tools calibrate`` writes it into the example dir (crash-safe: the
  probe journal is atomically rewritten after every probe, so a killed
  sweep leaves a readable ``status: "in_progress"`` document, never a
  torn file);
* ``init`` copies it beside the config into the storage dir;
* ``run`` exports its knob values as ``NMZ_CALIB_<NAME>`` environment
  to every experiment script (utils/cmd.py ``CmdFactory.extra_env``) —
  calibrated timing is PROVENANCE carried by the artifact, never an
  edited source constant;
* the progress surface (obs/analytics.progress_stats) reads its band
  so the live verdict is judged against the calibrated regime;
* the A/B gates read its measured rate + CI instead of magic numbers.

Top-level fields: ``schema``, ``example``, ``status`` ("calibrated" /
"in_progress" / "failed"), ``band``, ``alpha``/``beta``/
``max_runs_per_probe`` (the per-probe BandSPRT parameters), ``seed``,
``knobs`` (name -> calibrated value), the landed probe's ``rate`` /
``rate_ci95`` / ``runs`` / ``failures`` / ``verdict`` / ``decided_by``,
the full ``probes`` journal, and the budget ledger: ``runs_spent``
(all probes), ``fixed_n_equivalent`` (probes x the fixed-sample size of
equal discriminating power — ``runs_for_ci_width`` at the band's
geometric midpoint for the band's width), ``runs_saved``,
``runs_saved_pct``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

from namazu_tpu.utils.log import get_logger

log = get_logger("calibrate.artifact")

SCHEMA = "nmz-calib-v1"
ARTIFACT_NAME = "calibration.json"

#: the environment-variable prefix knob values ride into experiment
#: scripts on (``NMZ_CALIB_<NAME_UPPER>``)
ENV_PREFIX = "NMZ_CALIB_"


def env_name(knob_name: str) -> str:
    """The environment variable carrying one knob's calibrated value."""
    return ENV_PREFIX + knob_name.upper()


def knob_env(calib: Dict[str, Any]) -> Dict[str, str]:
    """The artifact's knob values as the ``NMZ_CALIB_*`` environment
    block experiment scripts read (integral floats render as integers —
    a shell script comparing ``$NMZ_CALIB_ROUNDS`` wants ``400``, not
    ``400.0``)."""
    out: Dict[str, str] = {}
    for name, value in (calib.get("knobs") or {}).items():
        if isinstance(value, float) and value == int(value):
            value = int(value)
        out[env_name(str(name))] = str(value)
    return out


def validate(calib: Any) -> Optional[str]:
    """None when ``calib`` is a usable artifact, else what is wrong."""
    if not isinstance(calib, dict):
        return "not a JSON object"
    if calib.get("schema") != SCHEMA:
        return (f"schema {calib.get('schema')!r} is not {SCHEMA!r}")
    knobs = calib.get("knobs")
    if not isinstance(knobs, dict) or not knobs:
        return "no knobs"
    for name, value in knobs.items():
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            return f"knob {name!r} value {value!r} is not a number"
    band = calib.get("band")
    if (not isinstance(band, (list, tuple)) or len(band) != 2
            or not all(isinstance(b, (int, float)) for b in band)):
        return f"band {band!r} is not [lo, hi]"
    return None


def load_calibration(path_or_dir: str) -> Optional[Dict[str, Any]]:
    """Read an artifact from a file path or a directory holding
    ``calibration.json``. None when absent; a present-but-unusable
    artifact is logged and ignored (a torn or foreign file must degrade
    a run to its uncalibrated defaults, not kill it)."""
    path = path_or_dir
    if os.path.isdir(path):
        path = os.path.join(path, ARTIFACT_NAME)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            calib = json.load(f)
    except (OSError, ValueError) as e:
        log.warning("unreadable calibration artifact %s: %s", path, e)
        return None
    problem = validate(calib)
    if problem is not None:
        log.warning("ignoring calibration artifact %s: %s", path, problem)
        return None
    return calib
