"""Distributed search: device meshes, island-model sharding, collectives.

The population dimension is sharded over the mesh's ``i`` (island) axis;
fitness statistics ride ``psum`` and elite migration rides ``ppermute`` —
all ICI traffic, never the host (SURVEY.md section 5.8's TPU-native
communication design). Multi-host scale-out uses the same code over a
process-spanning mesh via ``jax.distributed``.
"""

from namazu_tpu.parallel.mesh import make_mesh, default_device_count
from namazu_tpu.parallel.islands import IslandState, make_island_step, init_island_state

__all__ = [
    "make_mesh",
    "default_device_count",
    "IslandState",
    "init_island_state",
    "make_island_step",
]
