"""Multi-host (DCN) execution for the search plane.

The reference's notion of "distributed" is one orchestrator plus N
inspector processes over REST/TCP (SURVEY.md §2.9) — that control plane is
host-side and already multi-process here. *This* module scales the search
plane itself the TPU way: ``jax.distributed`` bootstraps one JAX process
per host, the global device mesh gets two axes — ``h`` (hosts, DCN) and
``i`` (chips within a host, ICI) — and the island GA becomes hierarchical:

* every step: intra-host ring migration over ``i`` (cheap, rides ICI);
* every step: a *small* inter-host elite exchange over ``h`` (a ppermute
  of ``dcn_migrate_k`` genomes — a few KB — so DCN's lower bandwidth never
  gates the step);
* global best agreement: ``all_gather`` over both axes (one genome per
  island, replicated everywhere).

Single-process dry runs use the same code over a virtual mesh (the driver's
``dryrun_multichip`` and tests/test_distributed.py reshape N CPU devices
into ``h x i``), so the multi-host program is compile-checked without a
pod.

Launch (one command per host)::

    NMZ_TPU_COORDINATOR=host0:8476 NMZ_TPU_NUM_PROCESSES=4 \
    NMZ_TPU_PROCESS_ID=$RANK  python -m my_experiment ...

or rely on the TPU environment's auto-detection (on Cloud TPU,
``jax.distributed.initialize()`` discovers everything itself).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

from namazu_tpu.models.ga import GAConfig
from namazu_tpu.ops.schedule import ScoreWeights
from namazu_tpu.utils.log import get_logger

log = get_logger("parallel.distributed")

_initialized = False


def initialize_from_env(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Bootstrap ``jax.distributed`` for a multi-host run. Idempotent.

    Explicit arguments win; otherwise ``NMZ_TPU_COORDINATOR`` /
    ``NMZ_TPU_NUM_PROCESSES`` / ``NMZ_TPU_PROCESS_ID`` are read; if none
    are present and we are not on a Cloud TPU environment that
    auto-detects, this is a single-process run and returns False.
    """
    global _initialized
    if _initialized:
        return True
    coordinator = coordinator or os.environ.get("NMZ_TPU_COORDINATOR")
    np_env = os.environ.get("NMZ_TPU_NUM_PROCESSES")
    pid_env = os.environ.get("NMZ_TPU_PROCESS_ID")
    num_processes = num_processes if num_processes is not None else (
        int(np_env) if np_env else None
    )
    process_id = process_id if process_id is not None else (
        int(pid_env) if pid_env else None
    )
    if coordinator is None and num_processes is None:
        return False  # single-process
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    log.info("jax.distributed up: process %d/%d, %d global devices",
             jax.process_index(), jax.process_count(),
             len(jax.devices()))
    return True


def make_hybrid_mesh(
    n_hosts: Optional[int] = None,
    devices: Optional[Sequence] = None,
    axes: tuple = ("h", "i"),
) -> Mesh:
    """2-D ``h x i`` mesh: hosts (DCN) x per-host chips (ICI).

    In a real multi-process run ``n_hosts`` defaults to
    ``jax.process_count()`` and devices are grouped so each row of the
    mesh is one host's chips (collectives over ``i`` never leave a host).
    Single-process (tests, dry runs): any ``n_hosts`` dividing the device
    count reshapes the flat device list — same program, virtual hosts.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_hosts is None:
        n_hosts = max(1, jax.process_count())
    if len(devs) % n_hosts != 0:
        raise ValueError(
            f"{len(devs)} devices do not divide into {n_hosts} hosts"
        )
    if jax.process_count() > 1 and n_hosts % jax.process_count() != 0:
        # each mesh row must stay within one physical host, otherwise the
        # full-rate i-axis collectives silently cross DCN every step
        raise ValueError(
            f"n_hosts={n_hosts} must be a multiple of the process count "
            f"({jax.process_count()}) so the chip axis stays intra-host"
        )
    per_host = len(devs) // n_hosts
    if jax.process_count() > 1:
        # group by owning process so the i-axis stays intra-host
        devs = sorted(devs, key=lambda d: (d.process_index, d.id))
    grid = np.asarray(devs, dtype=object).reshape(n_hosts, per_host)
    if jax.process_count() > 1:
        # an unbalanced device subset (e.g. jax.devices()[:6] across two
        # 4-chip hosts) can still produce rows spanning processes after
        # the sort — refuse rather than let "ICI" collectives ride DCN
        for row in grid:
            procs = {d.process_index for d in row}
            if len(procs) > 1:
                raise ValueError(
                    "mesh row spans processes "
                    f"{sorted(procs)}; pass a per-process-balanced device "
                    "subset so the chip axis stays intra-host"
                )
    return Mesh(grid, axes)


def hier_rings(
    migrate_k: int = 8,
    dcn_migrate_k: int = 2,
    migrate_every: int = 1,
    dcn_every: int = 1,
    host_axis: str = "h",
    chip_axis: str = "i",
):
    """The topology-aware ring plan for an ``h x i`` mesh, as consumed
    by ``islands.make_multiaxis_island_step``/``make_fused_island_step``:
    the neighbor ring over the chip axis FIRST (full-rate, rides ICI
    within a host), then the thin cross-host ring over DCN. Each ring
    carries its own cadence — ``dcn_every > 1`` decouples the expensive
    cross-host hop from the generation count (the ppermute is skipped
    entirely on off-generations, moving zero bytes over DCN), which is
    what lets a 16+-device mesh scale near-linearly instead of gating
    every generation on its slowest fabric."""
    return (
        (chip_axis, migrate_k, migrate_every),
        (host_axis, dcn_migrate_k, dcn_every),
    )


def make_hier_island_step(
    mesh: Mesh,
    cfg: GAConfig,
    weights: ScoreWeights = ScoreWeights(),
    migrate_k: int = 8,
    dcn_migrate_k: int = 2,
    host_axis: str = "h",
    chip_axis: str = "i",
    migrate_every: int = 1,
    dcn_every: int = 1,
):
    """Hierarchical island step for an ``h x i`` mesh: full-rate elite
    ring over ICI (``migrate_k``, every ``migrate_every`` generations),
    thin elite ring over DCN (``dcn_migrate_k`` genomes — a few KB —
    every ``dcn_every`` generations, landing just above the ICI
    migrants so the rings never overwrite each other). State is the same
    :class:`~namazu_tpu.parallel.islands.IslandState` (init with
    ``init_island_state``), so drivers and checkpoints are identical for
    flat and hierarchical meshes. One configuration of the general
    ``islands.make_multiaxis_island_step``."""
    from namazu_tpu.parallel.islands import make_multiaxis_island_step

    return make_multiaxis_island_step(
        mesh, cfg, weights,
        rings=hier_rings(migrate_k, dcn_migrate_k, migrate_every,
                         dcn_every, host_axis, chip_axis),
    )
