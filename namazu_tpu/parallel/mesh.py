"""Mesh construction helpers."""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` across jax versions: older releases ship it as
    ``jax.experimental.shard_map.shard_map``, and the replication-check
    kwarg was spelled ``check_rep`` before the ``check_vma`` rename —
    the two renames landed independently, so detect each by signature
    rather than assuming they travel together."""
    import inspect

    if hasattr(jax, "shard_map"):
        impl = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as impl
    try:
        params = inspect.signature(impl).parameters
        flag = "check_vma" if "check_vma" in params else "check_rep"
    except (TypeError, ValueError):  # pragma: no cover - exotic wrappers
        flag = "check_vma"
    return impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                **{flag: check_vma})


def default_device_count() -> int:
    return len(jax.devices())


def make_mesh(n_devices: Optional[int] = None, axis: str = "i") -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (default: all).

    The island axis is the only mesh axis the search needs: genomes are
    embarrassingly parallel within an island (vmap), islands communicate
    only during migration (ppermute) and stats (psum).
    """
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    return jax.make_mesh((len(devices),), (axis,), devices=devices)
