"""Mesh construction helpers."""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh


def default_device_count() -> int:
    return len(jax.devices())


def make_mesh(n_devices: Optional[int] = None, axis: str = "i") -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (default: all).

    The island axis is the only mesh axis the search needs: genomes are
    embarrassingly parallel within an island (vmap), islands communicate
    only during migration (ppermute) and stats (psum).
    """
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    return jax.make_mesh((len(devices),), (axis,), devices=devices)
