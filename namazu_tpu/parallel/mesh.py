"""Mesh construction helpers."""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` across jax versions: older releases ship it as
    ``jax.experimental.shard_map.shard_map``, and the replication-check
    kwarg was spelled ``check_rep`` before the ``check_vma`` rename —
    the two renames landed independently, so detect each by signature
    rather than assuming they travel together."""
    import inspect

    if hasattr(jax, "shard_map"):
        impl = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as impl
    try:
        params = inspect.signature(impl).parameters
        flag = "check_vma" if "check_vma" in params else "check_rep"
    except (TypeError, ValueError):  # pragma: no cover - exotic wrappers
        flag = "check_vma"
    return impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                **{flag: check_vma})


def default_device_count() -> int:
    return len(jax.devices())


def make_topology_mesh(
    n_devices: Optional[int] = None,
    host_size: int = 4,
    axes: tuple = ("h", "i"),
) -> Mesh:
    """``h x i`` mesh grouped by physical host for meshes PAST one
    host's chips: ``host_size`` chips per row (the 2x4 host-chip
    topology's 4; a 16-device pod slice becomes 4x4), so the ``i``-axis
    ring permutes neighbors over ICI within a host and only the thin
    ``h``-axis ring crosses DCN. A device count that IS one host's worth
    (or less) falls back to the flat single-axis mesh — no reason to pay
    a second collective axis. Delegates to
    ``distributed.make_hybrid_mesh`` for the process-grouping rules in
    real multi-host runs."""
    n = n_devices if n_devices is not None else len(jax.devices())
    if n <= host_size:
        return make_mesh(n_devices, axis=axes[1])
    if n % host_size != 0:
        raise ValueError(
            f"{n} devices do not divide into hosts of {host_size}"
        )
    from namazu_tpu.parallel.distributed import make_hybrid_mesh

    devs = jax.devices()[:n] if n_devices is not None else None
    return make_hybrid_mesh(n_hosts=n // host_size, devices=devs,
                            axes=axes)


def make_mesh(n_devices: Optional[int] = None, axis: str = "i") -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (default: all).

    The island axis is the only mesh axis the search needs: genomes are
    embarrassingly parallel within an island (vmap), islands communicate
    only during migration (ppermute) and stats (psum).
    """
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    return jax.make_mesh((len(devices),), (axis,), devices=devices)
