"""Island-model GA over a device mesh.

Each device evolves an independent population shard ("island"); every step

* scores its local genomes (vmap -> VPU/MXU),
* evolves one GA generation locally,
* migrates its elite genomes to the next island on a ring (``ppermute``
  over ICI, replacing the neighbor's worst genomes),
* and agrees on the global best via ``all_gather`` (tiny: one genome per
  island).

Everything device-to-device rides XLA collectives; the host only sees the
replicated global best. This is the TPU-native replacement for the
reference's single-process random exploration (SURVEY.md section 2.9).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from namazu_tpu.models.ga import GAConfig, Population, ga_generation, init_population
from namazu_tpu.ops.schedule import (
    ScoreWeights,
    TraceArrays,
    score_population_multi,
)


class IslandState(NamedTuple):
    pop: Population  # delays/faults f32[P, H], sharded over axis i
    gen: jax.Array  # int32 scalar, replicated
    best_fitness: jax.Array  # f32 scalar, replicated
    best_delays: jax.Array  # f32[H], replicated
    best_faults: jax.Array  # f32[H], replicated


def init_island_state(key: jax.Array, P_total: int, H: int,
                      cfg: GAConfig) -> IslandState:
    pop = init_population(key, P_total, H, cfg)
    return IslandState(
        pop=pop,
        gen=jnp.zeros((), jnp.int32),
        best_fitness=jnp.full((), -jnp.inf, jnp.float32),
        best_delays=jnp.zeros((H,), jnp.float32),
        best_faults=jnp.zeros((H,), jnp.float32),
    )


def make_island_step(
    mesh: Mesh,
    cfg: GAConfig,
    weights: ScoreWeights = ScoreWeights(),
    migrate_k: int = 8,
    axis: str = "i",
):
    """Build the jitted sharded step:
    (state, base_key, trace, pairs, archive, failure_feats) -> state.
    """
    n_islands = mesh.shape[axis]

    def _local_step(key, pop, trace, pairs, archive, failure_feats):
        idx = jax.lax.axis_index(axis)
        key = jax.random.fold_in(key, idx)

        fitness, _feats = score_population_multi(
            pop.delays, trace, pairs, archive, failure_feats, weights
        )
        # local best before evolution (elites survive anyway)
        best_i = jnp.argmax(fitness)
        local_best_fit = fitness[best_i]
        local_best_d = pop.delays[best_i]
        local_best_f = pop.faults[best_i]

        new_pop = ga_generation(key, pop, fitness, cfg)

        # ring migration of the top-k genomes (replace neighbor's worst)
        if n_islands > 1 and migrate_k > 0:
            k = migrate_k
            top_idx = jax.lax.top_k(fitness, k)[1]
            perm = [(j, (j + 1) % n_islands) for j in range(n_islands)]
            mig_d = jax.lax.ppermute(new_pop.delays[top_idx], axis, perm)
            mig_f = jax.lax.ppermute(new_pop.faults[top_idx], axis, perm)
            worst_idx = jax.lax.top_k(-fitness, k)[1]
            new_pop = Population(
                delays=new_pop.delays.at[worst_idx].set(mig_d),
                faults=new_pop.faults.at[worst_idx].set(mig_f),
            )

        # replicated global best: gather one candidate per island
        all_fit = jax.lax.all_gather(local_best_fit, axis)  # [nd]
        all_d = jax.lax.all_gather(local_best_d, axis)  # [nd, H]
        all_f = jax.lax.all_gather(local_best_f, axis)
        g = jnp.argmax(all_fit)
        return new_pop, all_fit[g], all_d[g], all_f[g]

    sharded = jax.shard_map(
        _local_step,
        mesh=mesh,
        in_specs=(
            P(),  # key
            Population(delays=P(axis, None), faults=P(axis, None)),
            TraceArrays(hint_ids=P(), arrival=P(), mask=P()),
            P(),  # pairs
            P(),  # archive
            P(),  # failure feats
        ),
        out_specs=(
            Population(delays=P(axis, None), faults=P(axis, None)),
            P(), P(), P(),
        ),
        check_vma=False,
    )

    @jax.jit
    def step(state: IslandState, base_key, trace: TraceArrays, pairs,
             archive, failure_feats) -> IslandState:
        if trace.hint_ids.ndim == 1:  # single trace -> batch of one
            trace = TraceArrays(
                trace.hint_ids[None], trace.arrival[None], trace.mask[None]
            )
        key = jax.random.fold_in(base_key, state.gen)
        new_pop, fit, bd, bf = sharded(
            key, state.pop, trace, pairs, archive, failure_feats
        )
        improved = fit > state.best_fitness
        return IslandState(
            pop=new_pop,
            gen=state.gen + 1,
            best_fitness=jnp.where(improved, fit, state.best_fitness),
            best_delays=jnp.where(improved, bd, state.best_delays),
            best_faults=jnp.where(improved, bf, state.best_faults),
        )

    return step
