"""Island-model GA over a device mesh.

Each device evolves an independent population shard ("island"); every step

* scores its local genomes (vmap -> VPU/MXU),
* evolves one GA generation locally,
* migrates its elite genomes (the leading rows after ``ga_generation``)
  to the next island along one or more ring axes (``ppermute`` — over ICI
  for the chip axis, over DCN for the host axis of a hybrid mesh),
  landing them in the neighbor's tail rows so the neighbor's own
  preserved elites are never overwritten,
* and agrees on the global best via ``all_gather`` (tiny: one genome per
  island).

Everything device-to-device rides XLA collectives; the host only sees the
replicated global best. This is the TPU-native replacement for the
reference's single-process random exploration (SURVEY.md section 2.9).

Two step shapes share one local-step body (same math, same PRNG draw
order — the bit-exactness contract tests/test_fused_loop.py pins):

* ``make_multiaxis_island_step`` — the per-generation step: one jitted
  dispatch per generation, host round trip between generations. The
  general form for hybrid host x chip meshes; ``make_island_step`` is
  its one-ring special case.
* ``make_fused_island_step`` — the whole generation loop device-side:
  ``lax.scan`` over G generations inside ONE jitted, shard_mapped,
  buffer-donated program. Population/best buffers never round-trip to
  the host between generations; the per-generation global-best history
  comes back as one f32[G] array so the host can log convergence
  without extra syncs (doc/performance.md "Fused search loop").

Migration cadence is decoupled from the generation count: each ring is
``(axis, k)`` or ``(axis, k, every)`` — the ring's ppermute only runs on
generations where ``gen % every == 0`` (``lax.cond``, predicate
replicated, so every device takes the same branch and a skipped
generation pays zero ICI/DCN bandwidth). ``every=1`` (the default) is
the pre-cadence behavior bit-for-bit.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from namazu_tpu.models.ga import GAConfig, Population, ga_generation, init_population
from namazu_tpu.parallel.mesh import shard_map as compat_shard_map
from namazu_tpu.ops.schedule import (
    ScoreWeights,
    TraceArrays,
    normalize_fault_trace,
    replicated_trace_specs,
    score_population_multi,
)


class IslandState(NamedTuple):
    pop: Population  # delays/faults f32[P, H], sharded over the mesh
    gen: jax.Array  # int32 scalar, replicated
    best_fitness: jax.Array  # f32 scalar, replicated
    best_delays: jax.Array  # f32[H], replicated
    best_faults: jax.Array  # f32[H], replicated


def init_island_state(key: jax.Array, P_total: int, H: int,
                      cfg: GAConfig) -> IslandState:
    pop = init_population(key, P_total, H, cfg)
    return IslandState(
        pop=pop,
        gen=jnp.zeros((), jnp.int32),
        best_fitness=jnp.full((), -jnp.inf, jnp.float32),
        best_delays=jnp.zeros((H,), jnp.float32),
        best_faults=jnp.zeros((H,), jnp.float32),
    )


def _norm_rings(rings: Sequence[Tuple]) -> Tuple[Tuple[str, int, int], ...]:
    """Rings as ``(axis, k, every)``; 2-tuples get ``every=1``."""
    out = []
    for r in rings:
        if len(r) == 2:
            ax, k = r
            every = 1
        else:
            ax, k, every = r
        out.append((str(ax), int(k), max(1, int(every))))
    return tuple(out)


def _make_local_step(mesh: Mesh, cfg: GAConfig, weights: ScoreWeights,
                     rings: Sequence[Tuple]):
    """The per-device generation body shared by the per-generation and
    fused step factories: score -> local best -> GA generation ->
    ring migration -> global-best all_gather. ``gen`` (replicated i32)
    drives the per-ring migration cadence."""
    axes = tuple(mesh.axis_names)
    rings = _norm_rings(rings)

    def _local_step(key, gen, pop, trace, pairs, archive, failure_feats,
                    novelty_scale, mutation_bias, coin=None):
        # named scopes mark the per-phase op regions in any captured
        # device profile (xprof/perfetto) — the in-jit counterpart of the
        # host-side obs.search_phase timers (obs/spans.py): host timers
        # can only see the whole fused dispatch, these label its parts
        for ax in axes:
            key = jax.random.fold_in(key, jax.lax.axis_index(ax))

        with jax.named_scope("nmz_score"):
            fitness, _feats = score_population_multi(
                pop.delays, trace, pairs, archive, failure_feats, weights,
                faults=None if coin is None else pop.faults, coin=coin,
                novelty_scale=novelty_scale,
            )
        # local best before evolution (elites survive anyway)
        best_i = jnp.argmax(fitness)
        local_best_fit = fitness[best_i]
        local_best_d = pop.delays[best_i]
        local_best_f = pop.faults[best_i]

        with jax.named_scope("nmz_mutate"):
            new_pop = ga_generation(key, pop, fitness, cfg,
                                    delay_bias=mutation_bias)

        # Migration: after ga_generation the island's elites occupy rows
        # [0:n_elite) of new_pop (sorted best-first), so migrants are the
        # leading rows (elites, then offspring if migrate_k > n_elite),
        # and they land in the *tail* rows of the neighbor — successive
        # rings take successive tail slices, so elites are transported
        # verbatim and a later, thinner ring (e.g. DCN) never overwrites
        # an earlier ring's arrivals or the neighbor's preserved elites.
        rows = pop.delays.shape[0]
        n_elite = max(1, int(rows * cfg.elite_frac))
        offset = 0
        plan = []  # (axis, k, landing offset from the tail, every)
        for ax, k, every in rings:
            kk = min(k, max(0, rows - n_elite - offset))
            if mesh.shape[ax] > 1 and kk > 0:
                plan.append((ax, kk, offset, every))
                offset += kk
        with jax.named_scope("nmz_migrate"):
            for ax, kk, off, every in plan:
                n_ax = mesh.shape[ax]
                perm = [(j, (j + 1) % n_ax) for j in range(n_ax)]
                dst = rows - off - kk

                def _migrate(p, _ax=ax, _kk=kk, _perm=perm, _dst=dst):
                    mig_d = jax.lax.ppermute(p.delays[:_kk], _ax, _perm)
                    mig_f = jax.lax.ppermute(p.faults[:_kk], _ax, _perm)
                    return Population(
                        delays=p.delays.at[_dst:_dst + _kk].set(mig_d),
                        faults=p.faults.at[_dst:_dst + _kk].set(mig_f),
                    )

                if every > 1:
                    # gen is replicated, so every device takes the same
                    # branch and a skipped generation moves zero bytes
                    # over this ring's fabric
                    new_pop = jax.lax.cond(
                        gen % every == 0, _migrate, lambda p: p, new_pop)
                else:
                    new_pop = _migrate(new_pop)

        # replicated global best: gather one candidate per island, axis by
        # axis (innermost first, so ICI gathers before any DCN hop)
        with jax.named_scope("nmz_select"):
            all_fit, all_d, all_f = local_best_fit, local_best_d, local_best_f
            for ax in reversed(axes):
                all_fit = jax.lax.all_gather(all_fit, ax)
                all_d = jax.lax.all_gather(all_d, ax)
                all_f = jax.lax.all_gather(all_f, ax)
        all_fit = all_fit.reshape(-1)
        all_d = all_d.reshape(-1, all_d.shape[-1])
        all_f = all_f.reshape(-1, all_f.shape[-1])
        g = jnp.argmax(all_fit)
        return new_pop, all_fit[g], all_d[g], all_f[g]

    return _local_step, axes


def _pop_spec(axes) -> Population:
    return Population(delays=P(axes, None), faults=P(axes, None))


def _jit_donate_state(fn):
    """``jax.jit`` with the leading IslandState donated — the whole point
    of the fused step: population buffers are reused in place across the
    scan instead of allocating a fresh copy per call. One home so the
    donation contract (keep only the RETURNED state) is greppable."""
    return jax.jit(fn, donate_argnums=(0,))


def _prep_step_inputs(state: IslandState, trace: TraceArrays, coin,
                      novelty_scale, mutation_bias, cfg: GAConfig):
    """Input normalization shared by the per-generation and fused entry
    points — identical defaults keep the two paths bit-exact."""
    if trace.hint_ids.ndim == 1:  # single trace -> batch of one
        trace = jax.tree.map(lambda x: x[None], trace)
    trace = normalize_fault_trace(trace, coin)
    if coin is None and cfg.max_fault > 0:
        # without the coin the fault half would evolve unscored —
        # exactly the round-1 bug config 4 exists to fix
        raise ValueError(
            "fault search is enabled (max_fault > 0) but no fault "
            "coin was passed to the island step; build one with "
            "trace_encoding.fault_coin(seed, H)"
        )
    if novelty_scale is None:
        novelty_scale = jnp.ones((), jnp.float32)
    else:
        novelty_scale = jnp.asarray(novelty_scale, jnp.float32)
    if mutation_bias is None:
        # all-ones bias == the unbiased kernel bit-for-bit (the
        # bernoulli threshold values are identical), so guidance-off
        # callers keep the pre-guidance populations exactly
        mutation_bias = jnp.ones(
            (state.pop.delays.shape[1],), jnp.float32)
    else:
        mutation_bias = jnp.asarray(mutation_bias, jnp.float32)
    return trace, novelty_scale, mutation_bias


def make_multiaxis_island_step(
    mesh: Mesh,
    cfg: GAConfig,
    weights: ScoreWeights = ScoreWeights(),
    rings: Sequence[Tuple] = (("i", 8),),
):
    """Build the jitted sharded step:
    (state, base_key, trace, pairs, archive, failure_feats) -> state.

    ``rings`` is a sequence of ``(mesh_axis, migrate_k)`` or
    ``(mesh_axis, migrate_k, every)``: each entry runs a ring over that
    axis migrating the island's *leading* rows of ``new_pop`` (elites
    first — ``ga_generation`` sorts them into the first ``n_elite``
    slots — then best-effort tournament offspring when
    ``migrate_k > n_elite``), on generations where ``gen % every == 0``.
    Migrants land in successive *tail* slices of the neighbor's
    population, so the neighbor's own preserved elites are never
    overwritten and a later, thinner ring (e.g. DCN) never clobbers an
    earlier ring's arrivals. Counts clamp so the landing region stays
    clear of the elite rows (shapes are static at trace time). The global
    best is gathered over every mesh axis and replicated.
    """
    _local_step, axes = _make_local_step(mesh, cfg, weights, rings)
    pop_spec = _pop_spec(axes)
    fault_trace_spec, nofault_trace_spec = replicated_trace_specs()

    def base_specs(trace_spec):
        return (
            P(),  # key
            P(),  # gen (replicated scalar; migration cadence)
            pop_spec,
            trace_spec,
            P(),  # pairs
            P(),  # archive
            P(),  # failure feats
            P(),  # novelty anneal scale (replicated scalar)
            P(),  # mutation bias f32[H] (replicated; guidance plane)
        )

    sharded_fault = compat_shard_map(
        _local_step,
        mesh=mesh,
        in_specs=base_specs(fault_trace_spec) + (P(),),  # + fault coin
        out_specs=(pop_spec, P(), P(), P()),
        check_vma=False,
    )
    sharded_nofault = compat_shard_map(
        _local_step,
        mesh=mesh,
        in_specs=base_specs(nofault_trace_spec),
        out_specs=(pop_spec, P(), P(), P()),
        check_vma=False,
    )

    @jax.jit
    def step(state: IslandState, base_key, trace: TraceArrays, pairs,
             archive, failure_feats, coin=None,
             novelty_scale=None, mutation_bias=None) -> IslandState:
        trace, novelty_scale, mutation_bias = _prep_step_inputs(
            state, trace, coin, novelty_scale, mutation_bias, cfg)
        key = jax.random.fold_in(base_key, state.gen)
        if coin is None:
            # static no-fault variant: the drop-mask/penalty branch is
            # never compiled into the hot loop when faults are off
            new_pop, fit, bd, bf = sharded_nofault(
                key, state.gen, state.pop, trace, pairs, archive,
                failure_feats, novelty_scale, mutation_bias
            )
        else:
            new_pop, fit, bd, bf = sharded_fault(
                key, state.gen, state.pop, trace, pairs, archive,
                failure_feats, novelty_scale, mutation_bias, coin
            )
        improved = fit > state.best_fitness
        return IslandState(
            pop=new_pop,
            gen=state.gen + 1,
            best_fitness=jnp.where(improved, fit, state.best_fitness),
            best_delays=jnp.where(improved, bd, state.best_delays),
            best_faults=jnp.where(improved, bf, state.best_faults),
        )

    return step


def make_fused_island_step(
    mesh: Mesh,
    cfg: GAConfig,
    weights: ScoreWeights = ScoreWeights(),
    rings: Sequence[Tuple] = (("i", 8),),
    generations: int = 16,
):
    """The whole generation loop in ONE device program:
    ``(state, base_key, trace, pairs, archive, failure_feats, ...) ->
    (state, fit_hist f32[generations])``.

    ``lax.scan`` steps the shared local-step body ``generations`` times
    inside one shard_mapped jit with the state pytree DONATED — the
    population, best-so-far, and generation buffers live on device for
    the scan's whole span and the input state's buffers are reused in
    place instead of round-tripping HBM->host->HBM per generation.
    ``fit_hist[g]`` is the replicated global-best fitness of generation
    ``state.gen + g`` (the per-generation convergence record the host
    would otherwise pay one sync each for).

    Bit-exactness contract (pinned by tests/test_fused_loop.py): the
    per-generation PRNG key is ``fold_in(base_key, gen)`` — the same
    fold the per-generation step applies — so N fused generations
    produce populations and fitness identical to N calls of
    ``make_multiaxis_island_step``'s step from the same state, the way
    ``ScheduledQueue.put_many`` keeps the sequential path's draw order.

    CAUTION: donation invalidates the caller's input state; keep only
    the returned state (models/search.py replaces ``self._state``).
    """
    if generations < 1:
        raise ValueError(f"generations must be >= 1, got {generations}")
    _local_step, axes = _make_local_step(mesh, cfg, weights, rings)
    pop_spec = _pop_spec(axes)
    fault_trace_spec, nofault_trace_spec = replicated_trace_specs()
    state_spec = IslandState(pop=pop_spec, gen=P(), best_fitness=P(),
                             best_delays=P(), best_faults=P())

    def _fused_local(state, base_key, trace, pairs, archive, failure_feats,
                     novelty_scale, mutation_bias, coin=None):
        def body(carry, i):
            pop, gen, bf, bd, bfa = carry
            key = jax.random.fold_in(base_key, gen)
            new_pop, fit, d, f = _local_step(
                key, gen, pop, trace, pairs, archive, failure_feats,
                novelty_scale, mutation_bias,
                *(() if coin is None else (coin,)))
            improved = fit > bf
            carry = (new_pop, gen + 1,
                     jnp.where(improved, fit, bf),
                     jnp.where(improved, d, bd),
                     jnp.where(improved, f, bfa))
            return carry, fit

        init = (state.pop, state.gen, state.best_fitness,
                state.best_delays, state.best_faults)
        (pop, gen, bf, bd, bfa), fit_hist = jax.lax.scan(
            body, init, jnp.arange(generations, dtype=jnp.int32))
        return IslandState(pop=pop, gen=gen, best_fitness=bf,
                           best_delays=bd, best_faults=bfa), fit_hist

    def fused_specs(trace_spec, with_coin: bool):
        specs = (
            state_spec,
            P(),  # base key
            trace_spec,
            P(),  # pairs
            P(),  # archive
            P(),  # failure feats
            P(),  # novelty anneal scale
            P(),  # mutation bias
        )
        return specs + ((P(),) if with_coin else ())

    sharded_fault = compat_shard_map(
        _fused_local,
        mesh=mesh,
        in_specs=fused_specs(fault_trace_spec, True),
        out_specs=(state_spec, P()),
        check_vma=False,
    )
    sharded_nofault = compat_shard_map(
        _fused_local,
        mesh=mesh,
        in_specs=fused_specs(nofault_trace_spec, False),
        out_specs=(state_spec, P()),
        check_vma=False,
    )

    @_jit_donate_state
    def fused(state: IslandState, base_key, trace: TraceArrays, pairs,
              archive, failure_feats, coin=None,
              novelty_scale=None, mutation_bias=None):
        trace, novelty_scale, mutation_bias = _prep_step_inputs(
            state, trace, coin, novelty_scale, mutation_bias, cfg)
        if coin is None:
            return sharded_nofault(state, base_key, trace, pairs, archive,
                                   failure_feats, novelty_scale,
                                   mutation_bias)
        return sharded_fault(state, base_key, trace, pairs, archive,
                             failure_feats, novelty_scale, mutation_bias,
                             coin)

    return fused


def make_island_step(
    mesh: Mesh,
    cfg: GAConfig,
    weights: ScoreWeights = ScoreWeights(),
    migrate_k: int = 8,
    axis: str = "i",
    migrate_every: int = 1,
):
    """Flat single-axis island step: one elite ring over ``axis``,
    migrating every ``migrate_every`` generations."""
    return make_multiaxis_island_step(
        mesh, cfg, weights, rings=((axis, migrate_k, migrate_every),))
