"""Island-model GA over a device mesh.

Each device evolves an independent population shard ("island"); every step

* scores its local genomes (vmap -> VPU/MXU),
* evolves one GA generation locally,
* migrates its elite genomes (the leading rows after ``ga_generation``)
  to the next island along one or more ring axes (``ppermute`` — over ICI
  for the chip axis, over DCN for the host axis of a hybrid mesh),
  landing them in the neighbor's tail rows so the neighbor's own
  preserved elites are never overwritten,
* and agrees on the global best via ``all_gather`` (tiny: one genome per
  island).

Everything device-to-device rides XLA collectives; the host only sees the
replicated global best. This is the TPU-native replacement for the
reference's single-process random exploration (SURVEY.md section 2.9).

``make_island_step`` builds the flat single-axis step;
``make_multiaxis_island_step`` is the general form used for hybrid
host x chip meshes (parallel/distributed.py) — the flat step is its
one-ring special case.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from namazu_tpu.models.ga import GAConfig, Population, ga_generation, init_population
from namazu_tpu.parallel.mesh import shard_map as compat_shard_map
from namazu_tpu.ops.schedule import (
    ScoreWeights,
    TraceArrays,
    normalize_fault_trace,
    replicated_trace_specs,
    score_population_multi,
)


class IslandState(NamedTuple):
    pop: Population  # delays/faults f32[P, H], sharded over the mesh
    gen: jax.Array  # int32 scalar, replicated
    best_fitness: jax.Array  # f32 scalar, replicated
    best_delays: jax.Array  # f32[H], replicated
    best_faults: jax.Array  # f32[H], replicated


def init_island_state(key: jax.Array, P_total: int, H: int,
                      cfg: GAConfig) -> IslandState:
    pop = init_population(key, P_total, H, cfg)
    return IslandState(
        pop=pop,
        gen=jnp.zeros((), jnp.int32),
        best_fitness=jnp.full((), -jnp.inf, jnp.float32),
        best_delays=jnp.zeros((H,), jnp.float32),
        best_faults=jnp.zeros((H,), jnp.float32),
    )


def make_multiaxis_island_step(
    mesh: Mesh,
    cfg: GAConfig,
    weights: ScoreWeights = ScoreWeights(),
    rings: Sequence[Tuple[str, int]] = (("i", 8),),
):
    """Build the jitted sharded step:
    (state, base_key, trace, pairs, archive, failure_feats) -> state.

    ``rings`` is a sequence of ``(mesh_axis, migrate_k)``: each entry runs
    a ring over that axis migrating the island's *leading* rows of
    ``new_pop`` (elites first — ``ga_generation`` sorts them into the
    first ``n_elite`` slots — then best-effort tournament offspring when
    ``migrate_k > n_elite``). Migrants land in successive *tail* slices of
    the neighbor's population, so the neighbor's own preserved elites are
    never overwritten and a later, thinner ring (e.g. DCN) never clobbers
    an earlier ring's arrivals. Counts clamp so the landing region stays
    clear of the elite rows (shapes are static at trace time). The global
    best is gathered over every mesh axis and replicated.
    """
    axes = tuple(mesh.axis_names)

    def _local_step(key, pop, trace, pairs, archive, failure_feats,
                    novelty_scale, mutation_bias, coin=None):
        # named scopes mark the per-phase op regions in any captured
        # device profile (xprof/perfetto) — the in-jit counterpart of the
        # host-side obs.search_phase timers (obs/spans.py): host timers
        # can only see the whole fused dispatch, these label its parts
        for ax in axes:
            key = jax.random.fold_in(key, jax.lax.axis_index(ax))

        with jax.named_scope("nmz_score"):
            fitness, _feats = score_population_multi(
                pop.delays, trace, pairs, archive, failure_feats, weights,
                faults=None if coin is None else pop.faults, coin=coin,
                novelty_scale=novelty_scale,
            )
        # local best before evolution (elites survive anyway)
        best_i = jnp.argmax(fitness)
        local_best_fit = fitness[best_i]
        local_best_d = pop.delays[best_i]
        local_best_f = pop.faults[best_i]

        with jax.named_scope("nmz_mutate"):
            new_pop = ga_generation(key, pop, fitness, cfg,
                                    delay_bias=mutation_bias)

        # Migration: after ga_generation the island's elites occupy rows
        # [0:n_elite) of new_pop (sorted best-first), so migrants are the
        # leading rows (elites, then offspring if migrate_k > n_elite),
        # and they land in the *tail* rows of the neighbor — successive
        # rings take successive tail slices, so elites are transported
        # verbatim and a later, thinner ring (e.g. DCN) never overwrites
        # an earlier ring's arrivals or the neighbor's preserved elites.
        rows = pop.delays.shape[0]
        n_elite = max(1, int(rows * cfg.elite_frac))
        offset = 0
        plan = []  # (axis, k, landing offset from the tail)
        for ax, k in rings:
            kk = min(k, max(0, rows - n_elite - offset))
            if mesh.shape[ax] > 1 and kk > 0:
                plan.append((ax, kk, offset))
                offset += kk
        with jax.named_scope("nmz_migrate"):
            for ax, kk, off in plan:
                n_ax = mesh.shape[ax]
                perm = [(j, (j + 1) % n_ax) for j in range(n_ax)]
                mig_d = jax.lax.ppermute(new_pop.delays[:kk], ax, perm)
                mig_f = jax.lax.ppermute(new_pop.faults[:kk], ax, perm)
                dst = rows - off - kk
                new_pop = Population(
                    delays=new_pop.delays.at[dst:dst + kk].set(mig_d),
                    faults=new_pop.faults.at[dst:dst + kk].set(mig_f),
                )

        # replicated global best: gather one candidate per island, axis by
        # axis (innermost first, so ICI gathers before any DCN hop)
        with jax.named_scope("nmz_select"):
            all_fit, all_d, all_f = local_best_fit, local_best_d, local_best_f
            for ax in reversed(axes):
                all_fit = jax.lax.all_gather(all_fit, ax)
                all_d = jax.lax.all_gather(all_d, ax)
                all_f = jax.lax.all_gather(all_f, ax)
        all_fit = all_fit.reshape(-1)
        all_d = all_d.reshape(-1, all_d.shape[-1])
        all_f = all_f.reshape(-1, all_f.shape[-1])
        g = jnp.argmax(all_fit)
        return new_pop, all_fit[g], all_d[g], all_f[g]

    pop_spec = Population(delays=P(axes, None), faults=P(axes, None))
    fault_trace_spec, nofault_trace_spec = replicated_trace_specs()

    def base_specs(trace_spec):
        return (
            P(),  # key
            pop_spec,
            trace_spec,
            P(),  # pairs
            P(),  # archive
            P(),  # failure feats
            P(),  # novelty anneal scale (replicated scalar)
            P(),  # mutation bias f32[H] (replicated; guidance plane)
        )

    sharded_fault = compat_shard_map(
        _local_step,
        mesh=mesh,
        in_specs=base_specs(fault_trace_spec) + (P(),),  # + fault coin
        out_specs=(pop_spec, P(), P(), P()),
        check_vma=False,
    )
    sharded_nofault = compat_shard_map(
        _local_step,
        mesh=mesh,
        in_specs=base_specs(nofault_trace_spec),
        out_specs=(pop_spec, P(), P(), P()),
        check_vma=False,
    )

    @jax.jit
    def step(state: IslandState, base_key, trace: TraceArrays, pairs,
             archive, failure_feats, coin=None,
             novelty_scale=None, mutation_bias=None) -> IslandState:
        if trace.hint_ids.ndim == 1:  # single trace -> batch of one
            trace = jax.tree.map(lambda x: x[None], trace)
        trace = normalize_fault_trace(trace, coin)
        if coin is None and cfg.max_fault > 0:
            # without the coin the fault half would evolve unscored —
            # exactly the round-1 bug config 4 exists to fix
            raise ValueError(
                "fault search is enabled (max_fault > 0) but no fault "
                "coin was passed to the island step; build one with "
                "trace_encoding.fault_coin(seed, H)"
            )
        key = jax.random.fold_in(base_key, state.gen)
        if novelty_scale is None:
            novelty_scale = jnp.ones((), jnp.float32)
        else:
            novelty_scale = jnp.asarray(novelty_scale, jnp.float32)
        if mutation_bias is None:
            # all-ones bias == the unbiased kernel bit-for-bit (the
            # bernoulli threshold values are identical), so guidance-off
            # callers keep the pre-guidance populations exactly
            mutation_bias = jnp.ones(
                (state.pop.delays.shape[1],), jnp.float32)
        else:
            mutation_bias = jnp.asarray(mutation_bias, jnp.float32)
        if coin is None:
            # static no-fault variant: the drop-mask/penalty branch is
            # never compiled into the hot loop when faults are off
            new_pop, fit, bd, bf = sharded_nofault(
                key, state.pop, trace, pairs, archive, failure_feats,
                novelty_scale, mutation_bias
            )
        else:
            new_pop, fit, bd, bf = sharded_fault(
                key, state.pop, trace, pairs, archive, failure_feats,
                novelty_scale, mutation_bias, coin
            )
        improved = fit > state.best_fitness
        return IslandState(
            pop=new_pop,
            gen=state.gen + 1,
            best_fitness=jnp.where(improved, fit, state.best_fitness),
            best_delays=jnp.where(improved, bd, state.best_delays),
            best_faults=jnp.where(improved, bf, state.best_faults),
        )

    return step


def make_island_step(
    mesh: Mesh,
    cfg: GAConfig,
    weights: ScoreWeights = ScoreWeights(),
    migrate_k: int = 8,
    axis: str = "i",
):
    """Flat single-axis island step: one elite ring over ``axis``."""
    return make_multiaxis_island_step(mesh, cfg, weights,
                                      rings=((axis, migrate_k),))
