"""Supervised experiment campaigns: ``nmz-tpu campaign <storage> -n N``.

The tool's whole value proposition is the N-run reproduction loop
(BASELINE.md: ``for i in $(seq 1 100); do nmz-tpu run d; done``), but a
bare shell loop has no answer for the exact failure class the tool
exists to hunt: a hung testee parks the loop forever, a crashed
inspector burns the remaining N-i runs on a broken environment, and a
SIGKILL mid-write corrupts the storage every later run trains on. The
campaign runner is that loop with supervision (doc/robustness.md):

* each run is a child ``nmz-tpu run`` in its OWN session (process
  group); a per-run wall-clock deadline kills the entire group on
  expiry, so orphaned testee children cannot outlive their run;
* per-phase (run/validate/clean) deadlines are forwarded to the child,
  which enforces them the same way (cli/run_cmd.py, utils/cmd.py);
* every completed run is classified — ``experiment`` (an outcome,
  pass or repro), ``timeout`` (a deadline fired), ``infra`` (the
  harness itself failed). N bounds the SLOTS supervised: a slot that
  exhausts its retries keeps its failure class and still consumes one
  of the N (the budget is bounded wall-clock, not bounded outcomes);
  the final summary reports how many slots actually recorded an
  experiment outcome;
* infra-class failures are retried with capped exponential backoff +
  full jitter (utils/retry.py); K consecutive infra-class run slots
  abort the campaign (the environment is broken; burning the budget
  will not unbreak it);
* after every attempt the resumable ``campaign.json`` checkpoint is
  atomically rewritten, so a crashed supervisor resumes where it died;
* SIGINT/SIGTERM request a graceful stop (finish the in-flight run,
  checkpoint, exit); a second signal kills the in-flight group and
  aborts immediately.
"""

from __future__ import annotations

import json
import os
import queue
import random
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from namazu_tpu.cli.run_cmd import EXIT_TIMEOUT
from namazu_tpu.obs import spans as obs_spans
from namazu_tpu.utils.atomic import atomic_write_json
from namazu_tpu.utils.cmd import (
    CmdFactory,
    kill_process_group,
    sweep_stale_pgid_files,
)
from namazu_tpu.utils import timesource
from namazu_tpu.utils.log import get_logger
from namazu_tpu.utils.retry import backoff_delays

log = get_logger("campaign")

CHECKPOINT_NAME = "campaign.json"
CHECKPOINT_VERSION = 1

#: outcome classes (doc/robustness.md)
CLASS_EXPERIMENT = "experiment"  # the run recorded an outcome (pass/repro)
CLASS_TIMEOUT = "timeout"        # a deadline killed the run's process group
CLASS_INFRA = "infra"            # the harness failed (nonzero exit, signal)
CLASS_INTERRUPTED = "interrupted"  # operator abort mid-run

#: campaign exit statuses (distinct from run_cmd's, which the child uses)
EXIT_OK = 0
EXIT_USAGE = 2
EXIT_INFRA_STOP = 3     # K consecutive infra-class run slots
EXIT_INTERRUPTED = 130  # stopped on SIGINT/SIGTERM (128 + SIGINT)


@dataclass
class CampaignSpec:
    """Everything that parameterizes one supervised campaign."""

    storage_dir: str
    runs: int
    # supervisor-side wall-clock deadline for one whole `nmz-tpu run`
    # child (covers hangs the per-phase deadlines cannot see: a wedged
    # orchestrator shutdown, a stuck storage flush); 0 = none
    run_wall_deadline_s: float = 0.0
    # per-phase deadlines forwarded to the child (0 = none)
    run_deadline_s: float = 0.0
    validate_deadline_s: float = 0.0
    clean_deadline_s: float = 0.0
    retries: int = 2              # extra attempts per slot on infra/timeout
    backoff_base_s: float = 1.0
    backoff_cap_s: float = 30.0
    max_consecutive_infra: int = 3
    python: str = sys.executable
    seed: Optional[int] = None    # jitter RNG seed (tests)
    extra_run_args: List[str] = field(default_factory=list)
    # forward --virtual-clock to every run child (doc/performance.md
    # "Virtual clock"): each child fast-forwards its scheduled delays,
    # so campaign throughput decouples from the scenario's idle time.
    # The supervisor's own deadlines stay wall — they bound CHILD
    # processes whose hangs are real
    virtual_clock: bool = False
    # fleet telemetry collector (doc/observability.md "Fleet
    # telemetry"): "auto" = <storage>/telemetry.sock (with a /tmp
    # fallback past the AF_UNIX path limit), "" = off, else an explicit
    # socket path. The supervisor hosts the fleet aggregator on it and
    # exports NMZ_TELEMETRY_URL so every run child (and, through the
    # children's federation hop, their inspectors) pushes here —
    # ``tools top --url uds://<path>`` shows the whole campaign.
    telemetry_collector: str = "auto"
    # tenancy serve mode (doc/tenancy.md): when set, run slots LEASE
    # namespaced runs on this shared orchestrator (http://... or
    # uds://...) instead of forking `nmz-tpu run` children — the
    # supervisor drives each slot's loopback workload through the wire
    # under its leased namespace, renews the lease at TTL/3, and
    # records the released trace into the local storage. A slot that
    # stops renewing (crash) is reclaimed server-side on TTL expiry.
    serve_url: str = ""
    serve_ttl_s: float = 15.0
    serve_events: int = 200
    serve_entities: int = 2
    serve_policy: str = "random"
    serve_policy_param: Dict[str, Any] = field(default_factory=dict)
    # extra environment exported to every run child — the calibration
    # plane's knob transport (NMZ_CALIB_<NAME>, namazu_tpu/calibrate):
    # a probe's candidate knob values ride the environment into the
    # experiment scripts
    extra_env: Dict[str, str] = field(default_factory=dict)
    # called after every finished slot with (slot, progress-or-None);
    # returning True stops the campaign gracefully (stopped_reason
    # "callback", exit 0) — how the calibration harness early-stops a
    # probe the moment its band SPRT concludes
    on_slot: Optional[Callable[[Dict[str, Any],
                                Optional[Dict[str, Any]]], bool]] = None


class Campaign:
    """One supervised campaign over one storage dir."""

    def __init__(self, spec: CampaignSpec):
        self.spec = spec
        self.state: Dict[str, Any] = {}
        self._rng = random.Random(spec.seed)
        self._stop_requested = threading.Event()
        self._abort = threading.Event()
        self._child: Optional[subprocess.Popen] = None
        self._child_lock = threading.Lock()
        self._telemetry_server = None
        self._telemetry_path = ""

    # -- checkpoint ------------------------------------------------------

    @property
    def checkpoint_path(self) -> str:
        return os.path.join(self.spec.storage_dir, CHECKPOINT_NAME)

    def _fresh_state(self) -> Dict[str, Any]:
        return {
            "version": CHECKPOINT_VERSION,
            "requested_runs": self.spec.runs,
            "slots": [],            # one entry per finished run slot
            "consecutive_infra": 0,
            "stopped_reason": None,  # None while running; "done"/"infra"/
                                     # "interrupted" when finished
            "started_at": time.time(),
            "updated_at": time.time(),
        }

    def _load_or_init_state(self, resume: bool) -> None:
        path = self.checkpoint_path
        if resume and os.path.exists(path):
            try:
                with open(path) as f:
                    state = json.load(f)
            except (OSError, ValueError) as e:
                raise CampaignError(
                    f"unreadable checkpoint {path}: {e}; remove it or "
                    "rerun with --no-resume") from None
            if int(state.get("version", -1)) != CHECKPOINT_VERSION:
                raise CampaignError(
                    f"checkpoint {path} has version "
                    f"{state.get('version')!r}, this build writes "
                    f"{CHECKPOINT_VERSION}; rerun with --no-resume")
            # a resumed campaign may raise or lower the target; the
            # completed prefix stands either way
            state["requested_runs"] = self.spec.runs
            state["stopped_reason"] = None
            # the operator re-running IS the claim the environment is
            # fixed: carrying the counter over would re-stop on infra
            # before attempting a single run
            state["consecutive_infra"] = 0
            self.state = state
            log.info("resuming campaign from %s: %d slot(s) already done",
                     path, len(state["slots"]))
        else:
            self.state = self._fresh_state()
        self._checkpoint()

    def _checkpoint(self) -> None:
        self.state["updated_at"] = time.time()
        atomic_write_json(self.checkpoint_path, self.state, indent=2,
                          sort_keys=True)

    # -- signals ---------------------------------------------------------

    def _install_signal_handlers(self):
        if threading.current_thread() is not threading.main_thread():
            return None
        previous = {}

        def handler(signum, frame):
            if self._stop_requested.is_set():
                # second signal: the operator means it — kill the
                # in-flight group and abort
                log.warning("second signal; aborting the in-flight run")
                self._abort.set()
                with self._child_lock:
                    child = self._child
                if child is not None:
                    kill_process_group(child)
            else:
                log.warning("stop requested; finishing the in-flight run "
                            "then checkpointing (signal again to abort)")
                self._stop_requested.set()

        for signum in (signal.SIGINT, signal.SIGTERM):
            previous[signum] = signal.signal(signum, handler)
        return previous

    @staticmethod
    def _restore_signal_handlers(previous) -> None:
        if previous:
            for signum, old in previous.items():
                signal.signal(signum, old)

    # -- one attempt -----------------------------------------------------

    def _run_argv(self) -> List[str]:
        spec = self.spec
        argv = [spec.python, "-m", "namazu_tpu.cli", "run",
                spec.storage_dir]
        for flag, value in (("--run-deadline", spec.run_deadline_s),
                            ("--validate-deadline", spec.validate_deadline_s),
                            ("--clean-deadline", spec.clean_deadline_s)):
            if value and value > 0:
                argv += [flag, str(value)]
        if spec.virtual_clock:
            argv.append("--virtual-clock")
        argv += spec.extra_run_args
        return argv

    def _child_env(self) -> Dict[str, str]:
        # the child must be able to import the framework even when it is
        # not installed site-wide; CmdFactory.env() owns that logic
        env = CmdFactory(extra_env=self.spec.extra_env).env()
        if self._telemetry_path:
            # run children push their metrics (and forward their
            # inspectors') to the supervisor's collector — the one
            # campaign-wide fleet view (doc/observability.md)
            env["NMZ_TELEMETRY_URL"] = f"uds://{self._telemetry_path}"
        return env

    # -- fleet telemetry --------------------------------------------------

    def _collector_path(self) -> str:
        raw = self.spec.telemetry_collector
        if not raw:
            return ""
        if raw != "auto":
            return os.path.abspath(raw)
        path = os.path.abspath(os.path.join(self.spec.storage_dir,
                                            "telemetry.sock"))
        if len(path) >= 100:
            # sun_path caps AF_UNIX socket paths (~108 bytes); a deep
            # storage dir falls back to a pid-scoped /tmp name
            path = os.path.join("/tmp", f"nmz-telemetry-{os.getpid()}.sock")
        return path

    def _start_telemetry(self) -> None:
        from namazu_tpu import obs
        from namazu_tpu.obs import federation
        from namazu_tpu.utils.config import Config

        # honor the storage config's kill switch and SLO declarations
        # BEFORE deciding to host a collector: `telemetry_enabled =
        # false` must disable the whole plane for the supervisor too,
        # and declared [[slo]] objectives must reach the aggregator
        # this process is about to host (same config.toml-over-
        # config.json precedence as `run`)
        cfg_path = os.path.join(self.spec.storage_dir, "config.toml")
        if not os.path.exists(cfg_path):
            cfg_path = os.path.join(self.spec.storage_dir, "config.json")
        if os.path.exists(cfg_path):
            try:
                obs.configure_from_config(Config.from_file(cfg_path))
            except Exception:
                log.warning("could not apply the storage config's "
                            "telemetry keys; using process defaults",
                            exc_info=True)
        path = self._collector_path()
        if not path or not federation.enabled():
            return
        server = federation.TelemetryServer(path)
        try:
            server.start()
        except (OSError, RuntimeError) as e:
            # a dead collector must never gate the campaign itself —
            # the children simply stay local-only (the relay's own
            # degradation contract)
            log.warning("fleet telemetry collector on %s unavailable "
                        "(%s); campaign runs without the fleet view",
                        path, e)
            return
        self._telemetry_server = server
        self._telemetry_path = path
        # the supervisor is a producer too (campaign slot counters,
        # collector occupancy): its registry merges straight into the
        # local aggregator it hosts
        federation.ensure_self_relay("campaign")
        # continuous profiling: the supervisor samples itself too, so
        # `tools top` shows where campaign overhead goes between slots
        from namazu_tpu.obs import profiling

        profiling.ensure_profiler("campaign")
        log.info("fleet view: nmz-tpu tools top --url uds://%s", path)

    def _stop_telemetry(self) -> None:
        server, self._telemetry_server = self._telemetry_server, None
        self._telemetry_path = ""
        if server is not None:
            server.shutdown()

    def _one_attempt(self, slot_index: int = 0) -> Dict[str, Any]:
        """One attempt: fork mode spawns an ``nmz-tpu run`` child in
        its own session under the wall deadline; serve mode leases a
        run slot on the shared orchestrator instead (doc/tenancy.md)."""
        if self.spec.serve_url:
            return self._one_serve_attempt(slot_index)
        spec = self.spec
        t0 = time.monotonic()
        child = subprocess.Popen(
            self._run_argv(), env=self._child_env(),
            start_new_session=True)
        with self._child_lock:
            self._child = child
        timed_out = False
        try:
            deadline = (spec.run_wall_deadline_s
                        if spec.run_wall_deadline_s > 0 else None)
            try:
                child.wait(timeout=deadline)
            except subprocess.TimeoutExpired:
                timed_out = True
                log.warning("run exceeded the %.1fs wall deadline; "
                            "killing its process group", deadline)
                kill_process_group(child)
            except BaseException:
                kill_process_group(child)
                raise
        finally:
            with self._child_lock:
                self._child = None
            # a hard-killed child (SIGKILL skips its cleanup) can leave
            # its run script's process group orphaned in its own
            # session, outside the group we just killed — the pgid
            # breadcrumb run_cmd wrote points the sweep at it
            # (doc/robustness.md "Chaos plane")
            sweep_stale_pgid_files(spec.storage_dir)
        wall_s = time.monotonic() - t0
        rc = child.returncode
        if timed_out:
            cls = CLASS_TIMEOUT
        elif self._abort.is_set():
            cls = CLASS_INTERRUPTED
        elif rc == 0:
            cls = CLASS_EXPERIMENT
        elif rc == EXIT_TIMEOUT:
            cls = CLASS_TIMEOUT  # a child-enforced phase deadline fired
        else:
            cls = CLASS_INFRA  # nonzero exit or signal death (rc < 0)
        return {"class": cls, "exit_status": rc,
                "wall_s": round(wall_s, 3),
                "wall_deadline_hit": timed_out}

    # -- tenancy serve mode (doc/tenancy.md) ------------------------------

    def _one_serve_attempt(self, slot_index: int) -> Dict[str, Any]:
        from namazu_tpu.tenancy.client import TenancyWireError

        t0 = time.monotonic()
        crashed = False
        try:
            crashed = self._drive_serve_slot(slot_index)
        except (TenancyWireError, OSError, RuntimeError, ValueError) as e:
            log.warning("serve slot %d failed: %s", slot_index, e)
            return {"class": CLASS_INFRA, "exit_status": None,
                    "wall_s": round(time.monotonic() - t0, 3),
                    "wall_deadline_hit": False, "error": str(e)}
        wall_s = time.monotonic() - t0
        if self._abort.is_set():
            cls = CLASS_INTERRUPTED
        elif crashed:
            # the tenancy.slot.crash chaos seam fired: this tenant died
            # mid-run without releasing; the orchestrator reclaims its
            # namespace on TTL expiry — classified infra so the slot
            # retries like any crashed run child
            cls = CLASS_INFRA
        else:
            cls = CLASS_EXPERIMENT
        return {"class": cls, "exit_status": 0 if cls == CLASS_EXPERIMENT
                else None,
                "wall_s": round(wall_s, 3), "wall_deadline_hit": False}

    def _drive_serve_slot(self, slot_index: int) -> bool:
        """Lease a namespace, drive the slot's loopback workload through
        the shared orchestrator, release, record the returned trace.
        Returns True when the ``tenancy.slot.crash`` seam killed the
        tenant mid-run (lease left to expire server-side)."""
        import uuid as _uuid

        from namazu_tpu.storage import load_storage
        from namazu_tpu.tenancy.client import TenancyClient
        from namazu_tpu.utils.trace import SingleTrace

        spec = self.spec
        run_name = (f"{os.path.basename(os.path.abspath(spec.storage_dir))}"
                    f"-s{slot_index}-{_uuid.uuid4().hex[:6]}")
        client = TenancyClient(spec.serve_url)
        # serve slots run in-process: their durations and drive
        # deadlines read the process TimeSource, so a virtual-clock
        # supervisor fast-forwarding its own waits cannot time out a
        # healthy (parked) workload (doc/performance.md "Virtual clock")
        t0 = timesource.get().now()
        lease = self._serve_lease(client, run_name)
        lease_id = lease["lease_id"]
        # a placement service's lease says WHERE the workload runs
        # (host_url); a plain orchestrator's lease doesn't, and the
        # serve url is the workload url as before
        workload = {"url": lease.get("host_url") or spec.serve_url}
        moved = threading.Event()
        renew_stop = threading.Event()

        def renew_loop() -> None:
            interval = max(spec.serve_ttl_s / 3.0, 0.05)
            while not renew_stop.wait(interval):
                try:
                    doc = client.renew(lease_id)
                except Exception:
                    return  # lease gone (released, expired, or crash)
                new_url = str(doc.get("host_url") or "")
                if new_url and new_url != workload["url"]:
                    # the pool migrated this run (host drain/death);
                    # re-target the workload at its new home
                    log.warning("run %s migrated to %s; re-targeting "
                                "workload", run_name, new_url)
                    workload["url"] = new_url
                    moved.set()

        renewer = threading.Thread(target=renew_loop,
                                   name=f"lease-renew-s{slot_index}",
                                   daemon=True)
        renewer.start()
        try:
            crashed = self._drive_serve_workload(run_name, workload,
                                                 moved)
            if crashed:
                # die like a SIGKILLed tenant: no release — stop
                # renewing and walk away; TTL expiry reclaims the
                # namespace server-side (chaos: tenancy.slot.crash)
                return True
            released = client.release(lease_id)
        finally:
            renew_stop.set()
            renewer.join(timeout=2)
            client.close()
        storage = load_storage(spec.storage_dir)
        try:
            storage.create_new_working_dir()
            storage.record_new_trace(
                SingleTrace.from_jsonable(released.get("trace") or []))
            # serve slots run the wire workload, not a validate script:
            # the outcome is "completed" (successful = no repro claim)
            storage.record_result(True, timesource.get().now() - t0)
        finally:
            storage.close()
        log.info("serve slot %d: run %s released (%s event(s), %s "
                 "action(s) traced)", slot_index, run_name,
                 released.get("events"), released.get("dispatched"))
        return False

    def _serve_lease(self, client, run_name: str) -> Dict[str, Any]:
        """Lease the slot's namespace, honoring admission pushback: a
        refusal carrying Retry-After (the pool's 429 while its SLO
        burn is hot, or a single host's ingress gate) is a deferral,
        not a failure — wait as told and re-knock, bounded. Refusals
        without a Retry-After propagate to the slot's normal
        infra-retry path."""
        from namazu_tpu.tenancy.client import TenancyWireError

        spec = self.spec
        deferrals = 8
        while True:
            try:
                return client.lease(
                    run_name, ttl_s=spec.serve_ttl_s,
                    policy=spec.serve_policy or "random",
                    policy_param=dict(spec.serve_policy_param) or None)
            except TenancyWireError as e:
                hint = getattr(e, "retry_after", None)
                if hint is None or deferrals <= 0 \
                        or self._abort.is_set():
                    raise
                deferrals -= 1
                delay = min(max(float(hint), 0.0), 5.0)
                log.info("lease for %s deferred by admission control; "
                         "retrying in %.2fs (%s)", run_name, delay, e)
                if self._abort.wait(delay):
                    raise

    def _drive_serve_workload(self, run_name: str,
                              workload: Optional[Dict[str, str]] = None,
                              moved: Optional[threading.Event] = None,
                              ) -> bool:
        """The slot's loopback workload: post deferred events under the
        leased namespace, wait for every answering action. Returns True
        when the ``tenancy.slot.crash`` seam fired mid-drive.

        ``workload["url"]`` is the CURRENT workload target — the renew
        thread rewrites it and sets ``moved`` when the placement plane
        migrates the run to another host. On a move the transceivers
        are rebuilt against the new home; in-flight events whose
        actions died with the old host are NOT re-awaited — they were
        parked in the run's journal, recovered on the new host, and
        flush into the release trace (the exactly-once contract), so
        the slot only waits for answers that can still arrive."""
        from namazu_tpu import chaos
        from namazu_tpu.signal import PacketEvent

        spec = self.spec
        if workload is None:
            workload = {"url": spec.serve_url}
        entities = [f"n{i}" for i in range(max(1, spec.serve_entities))]

        def build(url):
            if url.startswith("uds://"):
                from namazu_tpu.inspector.uds_transceiver import (
                    UdsTransceiver,
                )

                built = {e: UdsTransceiver(e, url[len("uds://"):],
                                           run_ns=run_name)
                         for e in entities}
            else:
                from namazu_tpu.inspector.rest_transceiver import (
                    RestTransceiver,
                )

                built = {e: RestTransceiver(e, url, use_batch=True,
                                            flush_window=0.01,
                                            run_ns=run_name)
                         for e in entities}
            for tx in built.values():
                tx.start()
            return built

        def teardown(built):
            for tx in built.values():
                try:
                    tx.shutdown()
                except Exception:  # pragma: no cover - defensive
                    pass

        txs = build(workload["url"])
        crashed = False
        chans = []

        def retarget():
            nonlocal txs, chans
            teardown(txs)
            txs = build(workload["url"])
            # answers already delivered stay awaitable; the rest are
            # journal-recovered server-side and traced at release
            chans = [ch for ch in chans if not ch.empty()]

        def ride_out_migration(exc):
            """The wire died mid-send. Against a placement pool that is
            usually a host DYING under us — the monitor needs one
            detection window (dead_after + a renew tick) before the
            renew thread re-targets the workload, so wait that out
            rather than failing a slot the pool is about to save. A
            plain orchestrator (no mover) or a genuine outage (the
            renewer dies with the lease, ``moved`` never fires) still
            raises into the slot's infra-retry path."""
            if moved is None:
                raise exc
            deadline = timesource.get().now() + max(
                2.0 * spec.serve_ttl_s, 10.0)
            while not moved.wait(0.25):
                if self._abort.is_set() \
                        or timesource.get().now() >= deadline:
                    raise exc
            moved.clear()
            retarget()

        try:
            for i in range(max(1, spec.serve_events)):
                if i % 64 == 0 \
                        and chaos.decide("tenancy.slot.crash") is not None:
                    log.warning("chaos: tenancy.slot.crash fired; "
                                "abandoning run %s mid-drive", run_name)
                    crashed = True
                    break
                if self._abort.is_set():
                    break
                if moved is not None and moved.is_set():
                    moved.clear()
                    retarget()
                e = entities[i % len(entities)]
                ev = PacketEvent.create(e, e, "peer", hint=f"h{i % 16}")
                try:
                    chans.append(txs[e].send_event(ev))
                except (OSError, RuntimeError) as exc:
                    ride_out_migration(exc)
                    chans.append(txs[e].send_event(ev))
            if not crashed:
                deadline = timesource.get().now() + 60.0
                while chans:
                    if moved is not None and moved.is_set():
                        moved.clear()
                        retarget()
                        continue
                    try:
                        chans[0].get(timeout=0.5)
                        chans.pop(0)
                    except queue.Empty:
                        if timesource.get().now() >= deadline:
                            raise RuntimeError(
                                f"run {run_name}: workload actions "
                                "still outstanding after 60s")
        finally:
            teardown(txs)
        return crashed

    # -- the supervised loop ---------------------------------------------

    def run(self, resume: bool = True) -> int:
        spec = self.spec
        if spec.runs < 1:
            raise CampaignError(f"runs must be >= 1, got {spec.runs}")
        if not os.path.exists(os.path.join(spec.storage_dir,
                                           "config.json")):
            raise CampaignError(
                f"{spec.storage_dir} is not an initialized storage "
                "(no config.json; run `init` first)")
        self._load_or_init_state(resume)
        previous_handlers = self._install_signal_handlers()
        self._start_telemetry()
        try:
            return self._loop()
        finally:
            self._stop_telemetry()
            self._restore_signal_handlers(previous_handlers)
            self._checkpoint()

    def _finish(self, reason: str, status: int) -> int:
        self.state["stopped_reason"] = reason
        self._checkpoint()
        counts: Dict[str, int] = {}
        for slot in self.state["slots"]:
            counts[slot["class"]] = counts.get(slot["class"], 0) + 1
        log.info("campaign finished (%s): %d/%d slot(s) done, classes %s",
                 reason, len(self.state["slots"]),
                 self.state["requested_runs"], counts or "{}")
        return status

    def _loop(self) -> int:
        spec = self.spec
        state = self.state
        while len(state["slots"]) < state["requested_runs"]:
            if self._abort.is_set():
                return self._finish("interrupted", EXIT_INTERRUPTED)
            if self._stop_requested.is_set():
                return self._finish("interrupted", EXIT_INTERRUPTED)
            if (spec.max_consecutive_infra > 0
                    and state["consecutive_infra"]
                    >= spec.max_consecutive_infra):
                log.error(
                    "%d consecutive infra-class run slot(s); the "
                    "environment is broken — stopping the campaign",
                    state["consecutive_infra"])
                return self._finish("infra", EXIT_INFRA_STOP)
            slot_index = len(state["slots"])
            slot = self._run_slot(slot_index)
            state["slots"].append(slot)
            obs_spans.campaign_slot(slot["class"])
            if slot["class"] == CLASS_EXPERIMENT:
                state["consecutive_infra"] = 0
            elif slot["class"] == CLASS_INTERRUPTED:
                self._checkpoint()
                return self._finish("interrupted", EXIT_INTERRUPTED)
            else:
                state["consecutive_infra"] += 1
            self._checkpoint()
            progress = self._publish_progress()
            if spec.on_slot is not None and spec.on_slot(slot, progress):
                # the caller has seen enough (calibration probe SPRT
                # concluded, A/B budget reached): graceful stop, the
                # completed prefix stands
                return self._finish("callback", EXIT_OK)
        if (spec.max_consecutive_infra > 0
                and state["consecutive_infra"]
                >= spec.max_consecutive_infra):
            return self._finish("infra", EXIT_INFRA_STOP)
        return self._finish("done", EXIT_OK)

    def _run_slot(self, slot_index: int) -> Dict[str, Any]:
        """One run slot: attempt + bounded infra/timeout retries."""
        spec = self.spec
        attempts: List[Dict[str, Any]] = []
        delays = backoff_delays(max(0, spec.retries),
                                base=spec.backoff_base_s,
                                cap=spec.backoff_cap_s, rng=self._rng)
        while True:
            log.info("slot %d attempt %d", slot_index, len(attempts) + 1)
            attempt = self._one_attempt(slot_index)
            attempts.append(attempt)
            slot = {"slot": slot_index, "class": attempt["class"],
                    "attempts": attempts}
            if attempt["class"] == CLASS_EXPERIMENT:
                return slot
            if (attempt["class"] == CLASS_INTERRUPTED
                    or self._abort.is_set()):
                slot["class"] = CLASS_INTERRUPTED
                return slot
            if self._stop_requested.is_set():
                return slot
            # infra/timeout: retry with backoff while the budget lasts
            try:
                delay = next(delays)
            except StopIteration:
                return slot
            # persist the failed attempt before sleeping: a supervisor
            # crash during the backoff must not forget it
            self._checkpoint_partial(slot)
            log.warning("slot %d attempt %d was %s (exit %s); retrying "
                        "in %.2fs", slot_index, len(attempts),
                        attempt["class"], attempt["exit_status"], delay)
            if self._stop_requested.wait(delay):
                return slot

    def _publish_progress(self) -> Optional[Dict[str, Any]]:
        """The live progress surface's supervisor face: after every
        slot, recompute the storage's sequential statistics
        (obs/analytics.progress_stats), publish the nmz_campaign_*
        gauges the fleet federates, and stash the document in the
        in-memory state for the on_slot callback. Best-effort — a
        mid-write storage or a stats bug degrades to None, never kills
        the campaign loop."""
        try:
            from namazu_tpu.obs import analytics
            from namazu_tpu.storage import load_storage

            st = load_storage(self.spec.storage_dir)
            try:
                calib, ckpt = analytics._progress_inputs(
                    self.spec.storage_dir)
                progress = analytics.progress_stats(
                    st, calibration=calib, checkpoint=ckpt)
            finally:
                st.close()
        except Exception:
            log.warning("progress publication failed; continuing",
                        exc_info=True)
            return None
        obs_spans.campaign_progress(
            rate=progress["repro_rate"],
            ci=progress["rate_ci95"],
            repros_per_hour=progress["repros_per_hour"],
            eta_next_repro_s=progress["eta_next_repro_s"],
            runs_to_ci=(progress["runs_to_ci_width"] or {}).get(
                "more_runs"),
            in_band=(1 if progress["band_verdict"] == "in_band"
                     else 0 if progress["band_verdict"] in
                     ("below", "above") else None),
            repros_per_hour_virtual=progress.get(
                "repros_per_hour_virtual"),
        )
        self.state["progress"] = progress
        return progress

    def _checkpoint_partial(self, slot: Dict[str, Any]) -> None:
        """Checkpoint with the in-progress slot appended provisionally
        (it is rewritten when the slot finishes for real)."""
        snapshot = dict(self.state)
        snapshot["slots"] = self.state["slots"] + [
            dict(slot, in_progress=True)]
        snapshot["updated_at"] = time.time()
        atomic_write_json(self.checkpoint_path, snapshot, indent=2,
                          sort_keys=True)


class CampaignError(Exception):
    pass


def load_checkpoint(storage_dir: str) -> Optional[Dict[str, Any]]:
    """Read a storage's campaign checkpoint (None when absent)."""
    path = os.path.join(storage_dir, CHECKPOINT_NAME)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def summarize(state: Dict[str, Any]) -> Dict[str, Any]:
    """Roll a checkpoint up into the counts dashboards/CI gate on."""
    slots = [s for s in state.get("slots", [])
             if not s.get("in_progress")]
    by_class: Dict[str, int] = {}
    unclassified = 0
    for s in slots:
        cls = s.get("class")
        if cls not in (CLASS_EXPERIMENT, CLASS_TIMEOUT, CLASS_INFRA,
                       CLASS_INTERRUPTED):
            unclassified += 1
        else:
            by_class[cls] = by_class.get(cls, 0) + 1
    return {
        "requested_runs": state.get("requested_runs", 0),
        "completed_slots": len(slots),
        "experiment": by_class.get(CLASS_EXPERIMENT, 0),
        "timeout": by_class.get(CLASS_TIMEOUT, 0),
        "infra": by_class.get(CLASS_INFRA, 0),
        "interrupted": by_class.get(CLASS_INTERRUPTED, 0),
        "unclassified": unclassified,
        "stopped_reason": state.get("stopped_reason"),
    }
