#!/bin/sh
# Oracle: two increments from 0 must land at 2; a lost update (stale
# read-modify-write overwriting the other client's increment) leaves 1.
[ -f "$NMZ_WORKING_DIR/final" ] || exit 1
[ "$(cat "$NMZ_WORKING_DIR/final")" = "2" ] || exit 1
exit 0
