"""HTTP key-value server — the system under test.

A miniature of the etcd-class workload (the reference's etcd examples
drive a real etcd over HTTP, example/etcd/3517-reproduce): GET /kv
returns the current value, PUT /kv sets it. Threaded per connection
(keep-alive clients would otherwise starve each other behind the
stdlib's one-connection-at-a-time default); each individual request is
atomic under the GIL, so the server itself is consistent — the planted
bug lives entirely in the CLIENTS' unguarded read-modify-write
(client.py), like a real lost-update race.

Usage: server.py PORT
"""

import sys
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class KV(BaseHTTPRequestHandler):
    value = "0"

    def _reply(self, code: int, body: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 (stdlib naming)
        self._reply(200, KV.value)

    def do_PUT(self):  # noqa: N802
        n = int(self.headers.get("Content-Length", 0))
        KV.value = self.rfile.read(n).decode() or "0"
        self._reply(200, KV.value)

    def log_message(self, *a):  # quiet
        pass


def main():
    srv = ThreadingHTTPServer(("127.0.0.1", int(sys.argv[1])), KV)
    print("kv ready", flush=True)
    srv.serve_forever()


if __name__ == "__main__":
    main()
