#!/bin/sh
# Two clients race an unguarded read-modify-write on a KV server; every
# HTTP message crosses the orchestrator once (one proxied link per
# client). PALLAS_AXON_POOL_IPS= skips this image's TPU plugin boot in
# the short-lived interpreters.
PORT="${NMZ_REST_PORT:-10983}"
URL="http://127.0.0.1:${PORT}"
OUT="$NMZ_WORKING_DIR"

PALLAS_AXON_POOL_IPS= python "$NMZ_MATERIALS_DIR/server.py" 23300 \
  > "$OUT/server.log" 2>&1 &
srv_pid=$!

PALLAS_AXON_POOL_IPS= python "$NMZ_MATERIALS_DIR/proxy.py" "$URL" \
  "23311:23300:c1:kv,23312:23300:c2:kv" > "$OUT/proxy.log" 2>&1 &
proxy_pid=$!

ready() { grep -q "$2" "$1" 2>/dev/null; }
i=0
while [ $i -lt 100 ]; do
  if ready "$OUT/server.log" "kv ready" && ready "$OUT/proxy.log" "proxy ready"; then
    break
  fi
  # a dead server/proxy is an infra error: stop waiting immediately
  if ! kill -0 "$srv_pid" 2>/dev/null || ! kill -0 "$proxy_pid" 2>/dev/null; then
    i=100; break
  fi
  i=$((i + 1)); sleep 0.1
done
if [ $i -ge 100 ]; then
  echo "server/proxy failed to start" >&2
  cat "$OUT/server.log" "$OUT/proxy.log" >&2
  kill "$srv_pid" "$proxy_pid" 2>/dev/null
  exit 1
fi

# one interpreter drives both clients from threads (client.py): the
# 180 ms stagger sits on one clock, so uninspected runs are always
# serialized and the only reordering force is the policy's deferrals
rc=0
PALLAS_AXON_POOL_IPS= python "$NMZ_MATERIALS_DIR/client.py" \
  23311 23312 0.18 || rc=1

# read the final value DIRECTLY from the server (uninspected path); a
# failed read is an infra error, not a repro — abort without recording
if ! PALLAS_AXON_POOL_IPS= python - "$OUT/final" <<'EOF'
import http.client, sys
c = http.client.HTTPConnection("127.0.0.1", 23300, timeout=10)
c.request("GET", "/kv")
open(sys.argv[1], "w").write(c.getresponse().read().decode())
EOF
then
  echo "could not read the final value from the server" >&2
  kill "$srv_pid" "$proxy_pid" 2>/dev/null
  exit 1
fi

kill "$srv_pid" "$proxy_pid" 2>/dev/null
wait "$srv_pid" 2>/dev/null
wait "$proxy_pid" 2>/dev/null
if [ "$rc" != "0" ]; then
  echo "a client failed:" >&2
  exit 1
fi
exit 0
