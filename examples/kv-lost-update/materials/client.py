"""Both racing clients in one process, two proxied connections.

Each "client" does the classic lost-update read-modify-write: GET the
counter, increment, PUT it back unconditionally (no compare-and-swap, no
retry). One interpreter drives both from threads so the stagger between them
(run.sh passes 180 ms) sits on a millisecond-accurate clock — two
separate python processes on this one-core image boot with
±hundreds-of-ms relative jitter, which would drown the window under
test.

Uninspected, the staggered windows never overlap (a round trip is
milliseconds) and the final value is 2; under the ethernet inspector's
deferrals client 1's PUT can cross client 2's GET and one increment
vanishes.

Usage: client.py PORT1 PORT2 STAGGER_S
"""

import http.client
import sys
import threading
import time

errors = []


def rmw(conn: http.client.HTTPConnection, delay_s: float,
        start: float) -> None:
    # a crashed client is an infra error, not a bug repro: record the
    # exception so main() exits nonzero and the runner aborts without
    # recording (same guard as the zk-election node processes)
    try:
        time.sleep(max(0.0, start + delay_s - time.monotonic()))
        conn.request("GET", "/kv")
        v = int(conn.getresponse().read() or b"0")
        # ... the unguarded window: "compute" the new value ...
        new = str(v + 1)
        conn.request("PUT", "/kv", body=new)
        conn.getresponse().read()
    except Exception as e:  # noqa: BLE001 - any failure is infra
        errors.append(e)


def main():
    p1, p2 = int(sys.argv[1]), int(sys.argv[2])
    stagger = float(sys.argv[3])
    c1 = http.client.HTTPConnection("127.0.0.1", p1, timeout=30)
    c2 = http.client.HTTPConnection("127.0.0.1", p2, timeout=30)
    c1.connect()
    c2.connect()
    start = time.monotonic()
    t1 = threading.Thread(target=rmw, args=(c1, 0.0, start))
    t2 = threading.Thread(target=rmw, args=(c2, stagger, start))
    t1.start()
    t2.start()
    t1.join()
    t2.join()
    c1.close()
    c2.close()
    if errors:
        print(f"client error: {errors}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
