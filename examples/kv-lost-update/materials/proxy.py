"""One proxy-inspector process for both client links, with the etcd
(HTTP) stream parser providing semantic hints ("c1->kv:http:PUT:/kv").

Usage: proxy.py ORCHESTRATOR_URL LINK[,LINK...]
       LINK = listenPort:upstreamPort:srcEntity:dstEntity
"""

import signal as _signal
import sys
import threading

from namazu_tpu.inspector.ethernet import EthernetProxyInspector
from namazu_tpu.inspector.http_parser import etcd_parser
from namazu_tpu.inspector.transceiver import new_transceiver


def main():
    url = sys.argv[1]
    entity = "_nmz_kv_proxy"
    trans = new_transceiver(url, entity)
    inspector = EthernetProxyInspector(
        trans, entity_id=entity, parser=etcd_parser(), action_timeout=30.0,
    )
    for spec in sys.argv[2].split(","):
        lport, uport, src, dst = spec.split(":")
        inspector.add_link(f"127.0.0.1:{lport}", f"127.0.0.1:{uport}",
                           src_entity=src, dst_entity=dst)
    inspector.start()
    print("proxy ready", flush=True)
    stop = threading.Event()
    _signal.signal(_signal.SIGTERM, lambda *_: stop.set())
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    finally:
        inspector.stop()


if __name__ == "__main__":
    main()
