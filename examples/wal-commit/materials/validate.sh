#!/bin/sh
# Oracle: the run reproduced the race iff the reader exited non-zero.
test "$(cat "$NMZ_WORKING_DIR/rc.txt")" = "0"
