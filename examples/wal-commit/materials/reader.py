"""Reader side of the WAL-commit race (NOT under the interposer — it plays
the independent consumer whose assumption the bug violates).

For each epoch directory that appears, the reader immediately expects the
data file to exist and be non-empty — the faulty "marker implies payload"
assumption. Exit 1 the moment it catches a committed-but-empty epoch.
"""

import os
import sys
import time

EPOCHS = 12
DEADLINE_S = 30.0


def main() -> int:
    root = sys.argv[1]
    t0 = time.monotonic()
    epoch = 0
    while epoch < EPOCHS and time.monotonic() - t0 < DEADLINE_S:
        d = os.path.join(root, f"epoch-{epoch:03d}")
        if not os.path.isdir(d):
            time.sleep(0.0005)
            continue
        # the marker exists: the payload must be there and complete.
        # The reader is even lenient: it retries once after a grace period
        # (so ordinary IPC latency never trips it — only a genuinely
        # stretched window does).
        data = os.path.join(d, "data")
        ok = _payload_ok(data)
        if not ok:
            time.sleep(GRACE_S)
            if not _payload_ok(data):
                return 1  # race: committed epoch without usable payload
        os.unlink(data)
        os.rmdir(d)  # ack
        epoch += 1
    return 0


# Overridable so a loaded CI host can widen the grace to its measured
# scheduler jitter (tests/test_examples.py measured_grace); the default
# is the calibrated value the random-policy regime assumes.
GRACE_S = float(os.environ.get("WAL_GRACE_S", "0.025"))


def _payload_ok(data: str) -> bool:
    if not os.path.exists(data):
        return False
    try:
        with open(data, "rb") as f:
            return bool(f.read())
    except OSError:
        return False


if __name__ == "__main__":
    sys.exit(main())
