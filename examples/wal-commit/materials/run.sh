#!/bin/sh
# WAL-commit race under the LD_PRELOAD fs interposer.
# The writer runs with the interposer preloaded: its mkdir/create calls
# become deferred FilesystemEvents through the guest-agent endpoint; the
# reader runs clean. PALLAS_AXON_POOL_IPS= skips this image's TPU plugin
# boot in the short-lived interpreters.
PORT="${NMZ_AGENT_PORT:-10981}"
LIB=$(PALLAS_AXON_POOL_IPS= python -c 'import namazu_tpu, os; print(os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(namazu_tpu.__file__))), "native", "build", "libnmz_fs_interpose.so"))')
WAL="$NMZ_WORKING_DIR/wal"
mkdir -p "$WAL"

env LD_PRELOAD="$LIB" \
    NMZ_TPU_AGENT_ADDR="127.0.0.1:${PORT}" \
    NMZ_TPU_ENTITY_ID=waldb-writer \
    NMZ_TPU_FS_ROOT="$WAL" \
    PALLAS_AXON_POOL_IPS= \
    python "$NMZ_MATERIALS_DIR/writer.py" "$WAL" &
writer_pid=$!

PALLAS_AXON_POOL_IPS= python "$NMZ_MATERIALS_DIR/reader.py" "$WAL"
rc=$?
echo "$rc" > "$NMZ_WORKING_DIR/rc.txt"
kill "$writer_pid" 2>/dev/null
wait "$writer_pid" 2>/dev/null
exit 0
