"""Writer side of the WAL-commit race (runs under the LD_PRELOAD fs
interposer; every mkdir / file-create below becomes a deferred
FilesystemEvent the policy can delay).

Protocol per epoch (the buggy two-step commit, the shape of YARN-4301 /
write-ahead-log bugs): the writer first creates the epoch directory (the
"commit marker" readers key on), then writes the data file inside it.
Creation of the data file is a separate, hooked operation — so the
scheduler's delay on it IS the race window during which a reader observes
a committed-but-empty epoch.
"""

import os
import sys
import time

EPOCHS = 12


def main() -> int:
    root = sys.argv[1]
    for epoch in range(EPOCHS):
        d = os.path.join(root, f"epoch-{epoch:03d}")
        os.mkdir(d)  # step 1: the commit marker [hooked: pre-mkdir]
        # step 2: the payload  [hooked: pre-write on a different path]
        fd = os.open(os.path.join(d, "data"), os.O_CREAT | os.O_WRONLY, 0o644)
        os.write(fd, b"epoch=%d payload-ok\n" % epoch)
        os.close(fd)
        # wait for the reader to consume and ack (it removes the dir)
        t0 = time.monotonic()
        while os.path.exists(d):
            if time.monotonic() - t0 > 5.0:
                return 0
            time.sleep(0.001)
    return 0


if __name__ == "__main__":
    sys.exit(main())
