"""Miniature fast-leader-election node speaking ZooKeeper's FLE wire
format (QuorumCnxManager 3.4 handshake + length-framed notifications), so
the proxy inspector's ZkStreamParser produces real semantic hints.

The deliberately planted bug is the ZOOKEEPER-2212 class: a node decides
as soon as *some* candidate holds a quorum at the close of its decision
window and never re-evaluates afterwards — so when the highest-zxid
node's notifications are delayed past the window, the cluster elects a
stale leader (or splits). With no interception the exchange takes a few
ms and the decision window comfortably covers the start stagger, so the
healthy outcome (leader = the node with the newest zxid) is essentially
deterministic.

Usage: node.py SID ZXID LISTEN_PORT OUT_FILE PEER[,PEER...]
       PEER = sid:host:port  (proxy-side address of that peer's listener)
"""

import os
import socket
import struct
import sys
import threading
import time

# The decision window, in milliseconds — the scenario's one timing
# knob. Must exceed start stagger + uninspected RTTs; a LONGER window
# makes a direct starve rarer (a delayed notification must outlast it),
# so the knob's direction is "down": shrinking it raises the random
# baseline's repro rate. The value is CALIBRATED, not hand-tuned: it
# rides in from calibration.json as $NMZ_CALIB_DECISION_WINDOW_MS
# (namazu_tpu/calibrate; [calibration] table in ../config.toml), landing
# the random policy in the reference's rare-repro band (its ZK-2212
# row: 0% traditional / 21.8% namazu, README.md:43) where a searched
# table still has deterministic room.
DECISION_WINDOW_S = float(os.environ.get("NMZ_CALIB_DECISION_WINDOW_MS",
                                         "420")) / 1000.0
STATE_LOOKING = 0
QUORUM = 2


def note(sid, msg):
    sys.stderr.write(f"[node{sid}] {msg}\n")
    sys.stderr.flush()


class Node:
    def __init__(self, sid, zxid, listen_port, out_file, peers):
        self.sid = sid
        self.zxid = zxid
        self.listen_port = listen_port
        self.out_file = out_file
        self.peers = peers  # {sid: (host, port)}
        self.lock = threading.Lock()
        # my current vote and everyone's last-heard votes: sid -> (zxid, sid)
        self.vote = (zxid, sid)
        self.votes = {sid: self.vote}
        self.first_notif = threading.Event()
        self.decided = None
        self.socks = {}

    # -- FLE wire ---------------------------------------------------------

    def _notification(self):
        z, leader = self.vote
        body = struct.pack(">iqqqq", STATE_LOOKING, leader, z, 1, 1)
        return struct.pack(">i", len(body)) + body

    def _broadcast(self):
        for psid, sock in list(self.socks.items()):
            try:
                sock.sendall(self._notification())
            except OSError:
                pass

    def _dial(self, psid, addr):
        """Keep one live outbound connection to a peer: the proxy accepts
        and then dials the upstream, so a peer that is not up yet shows as
        an immediately-closed socket — watch for EOF and reconnect."""
        while self.decided is None:
            try:
                s = socket.create_connection(addr, timeout=1.0)
                s.settimeout(None)  # connect timeout must not make the
                # idle recv below churn healthy connections every second
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # 3.4-style initial: bare big-endian sid
                s.sendall(struct.pack(">q", self.sid))
                with self.lock:
                    self.socks[psid] = s
                    s.sendall(self._notification())
                while s.recv(4096):  # peers never send on this direction
                    pass
            except OSError:
                pass
            with self.lock:
                if self.socks.get(psid) is not None:
                    try:
                        self.socks.pop(psid).close()
                    except OSError:
                        pass
            time.sleep(0.02)

    # -- receive ----------------------------------------------------------

    def _serve(self, srv):
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            threading.Thread(target=self._recv, args=(conn,),
                             daemon=True).start()

    def _recv(self, conn):
        buf = b""

        def need(n):
            nonlocal buf
            while len(buf) < n:
                chunk = conn.recv(65536)
                if not chunk:
                    raise OSError("eof")
                buf += chunk
            out, buf = buf[:n], buf[n:]
            return out

        try:
            (peer_sid,) = struct.unpack(">q", need(8))
            while True:
                (flen,) = struct.unpack(">i", need(4))
                body = need(flen)
                _state, leader, zxid, _e, _pe = struct.unpack(
                    ">iqqqq", body[:36])
                self._on_vote(peer_sid, (zxid, leader))
        except OSError:
            return

    def _on_vote(self, peer_sid, vote):
        with self.lock:
            if self.decided is not None:
                return  # THE BUG: no re-evaluation after deciding
            self.votes[peer_sid] = vote
            if vote > self.vote:  # (zxid, sid) lexicographic
                self.vote = vote
                self.votes[self.sid] = vote
                self._broadcast()
        self.first_notif.set()

    # -- decision ---------------------------------------------------------

    def _tally(self):
        counts = {}
        for v in self.votes.values():
            counts[v] = counts.get(v, 0) + 1
        winners = [v for v, c in counts.items() if c >= QUORUM]
        return max(winners) if winners else None

    def run(self):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", self.listen_port))
        srv.listen(8)
        threading.Thread(target=self._serve, args=(srv,),
                         daemon=True).start()
        for psid, addr in self.peers.items():
            threading.Thread(target=self._dial, args=(psid, addr),
                             daemon=True).start()

        self.first_notif.wait(timeout=20.0)
        deadline = time.monotonic() + DECISION_WINDOW_S
        while True:
            time.sleep(0.02)
            with self.lock:
                winner = self._tally()
                if winner is not None and time.monotonic() >= deadline:
                    self.decided = winner
                    break
                if time.monotonic() > deadline + 20.0:
                    self.decided = (0, 0)  # stuck: report no leader
                    break
        zxid, leader = self.decided
        note(self.sid, f"elected leader={leader} zxid={zxid:#x}")
        with open(self.out_file, "w") as f:
            f.write(str(leader))
        # linger so peers still dialing us don't see resets mid-decision
        time.sleep(0.5)
        srv.close()


def main():
    sid = int(sys.argv[1])
    zxid = int(sys.argv[2], 0)
    listen_port = int(sys.argv[3])
    out_file = sys.argv[4]
    peers = {}
    for spec in sys.argv[5].split(","):
        psid, host, port = spec.split(":")
        peers[int(psid)] = (host, int(port))
    Node(sid, zxid, listen_port, out_file, peers).run()


if __name__ == "__main__":
    main()
