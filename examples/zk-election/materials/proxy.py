"""One proxy-inspector process for the whole election mesh: six proxied
links (every ordered node pair), one REST transceiver to the experiment's
orchestrator, one shared FLE stream parser (per-connection parse state).

Usage: proxy.py ORCHESTRATOR_URL LINK[,LINK...]
       LINK = listenPort:upstreamPort:srcEntity:dstEntity
"""

import signal as _signal
import sys
import threading

from namazu_tpu.inspector.ethernet import EthernetProxyInspector
from namazu_tpu.inspector.transceiver import new_transceiver
from namazu_tpu.inspector.zookeeper import ZkStreamParser


def main():
    url = sys.argv[1]
    entity = "_nmz_zk_election_proxy"
    trans = new_transceiver(url, entity)
    # entity_id must match the transceiver's: the REST action queue is
    # keyed by the event's entity and the transceiver polls its own
    inspector = EthernetProxyInspector(
        trans, entity_id=entity, parser=ZkStreamParser("fle"),
        action_timeout=30.0,
    )
    for spec in sys.argv[2].split(","):
        lport, uport, src, dst = spec.split(":")
        inspector.add_link(f"127.0.0.1:{lport}", f"127.0.0.1:{uport}",
                           src_entity=src, dst_entity=dst)
    inspector.start()
    print("proxy ready", flush=True)
    stop = threading.Event()
    _signal.signal(_signal.SIGTERM, lambda *_: stop.set())
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    finally:
        inspector.stop()


if __name__ == "__main__":
    main()
