#!/bin/sh
# 3-node miniature FLE election through the proxy inspector.
# Node listen ports 21281-21283; each ordered pair (src,dst) gets a
# dedicated proxied link on 22000+10*src+dst -> dst's listener, so every
# notification crosses the orchestrator exactly once.
# PALLAS_AXON_POOL_IPS= skips this image's TPU plugin boot in the
# short-lived interpreters (the control plane never needs a device).
PORT="${NMZ_REST_PORT:-10982}"
URL="http://127.0.0.1:${PORT}"
OUT="$NMZ_WORKING_DIR"

PALLAS_AXON_POOL_IPS= python "$NMZ_MATERIALS_DIR/proxy.py" "$URL" \
  "22012:21282:zk1:zk2,22013:21283:zk1:zk3,22021:21281:zk2:zk1,22023:21283:zk2:zk3,22031:21281:zk3:zk1,22032:21282:zk3:zk2" \
  > "$OUT/proxy.log" 2>&1 &
proxy_pid=$!

# wait for the six listeners; a dead proxy is an infra error, not a bug
# repro — exit non-zero so the runner aborts without recording
ready=0
i=0
while [ $i -lt 100 ]; do
  if grep -q "proxy ready" "$OUT/proxy.log" 2>/dev/null; then ready=1; break; fi
  if ! kill -0 "$proxy_pid" 2>/dev/null; then break; fi
  i=$((i + 1)); sleep 0.1
done
if [ "$ready" != "1" ]; then
  echo "proxy failed to start:" >&2
  cat "$OUT/proxy.log" >&2
  kill "$proxy_pid" 2>/dev/null
  exit 1
fi

# peers are addressed through the proxy ports; node 3 carries the newest
# zxid and starts 120ms late (a restarting node)
PALLAS_AXON_POOL_IPS= python "$NMZ_MATERIALS_DIR/node.py" 1 0x100 21281 \
  "$OUT/leader1" "2:127.0.0.1:22012,3:127.0.0.1:22013" \
  > "$OUT/node1.log" 2>&1 &
n1=$!
PALLAS_AXON_POOL_IPS= python "$NMZ_MATERIALS_DIR/node.py" 2 0x100 21282 \
  "$OUT/leader2" "1:127.0.0.1:22021,3:127.0.0.1:22023" \
  > "$OUT/node2.log" 2>&1 &
n2=$!
( sleep 0.12
  PALLAS_AXON_POOL_IPS= python "$NMZ_MATERIALS_DIR/node.py" 3 0x300 21283 \
    "$OUT/leader3" "1:127.0.0.1:22031,2:127.0.0.1:22032" \
    > "$OUT/node3.log" 2>&1 ) &
n3=$!

# a crashed node is an infra error, not a bug repro: propagate it so the
# runner aborts without recording (same guard as the proxy above)
rc=0
wait "$n1" || rc=1
wait "$n2" || rc=1
wait "$n3" || rc=1
kill "$proxy_pid" 2>/dev/null
wait "$proxy_pid" 2>/dev/null
if [ "$rc" != "0" ]; then
  echo "a node process failed:" >&2
  tail -5 "$OUT"/node*.log >&2
fi
exit "$rc"
