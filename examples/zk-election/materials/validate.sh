#!/bin/sh
# Oracle: healthy iff every node elected node 3 — the only replica with
# the newest zxid. A stale leader (2) or a split vote is the bug.
for n in 1 2 3; do
  f="$NMZ_WORKING_DIR/leader$n"
  [ -f "$f" ] || exit 1
  [ "$(cat "$f")" = "3" ] || exit 1
done
exit 0
