"""A deliberately scheduling-sensitive flaky test (the system under test).

Classic two-phase-init race (the shape of YARN-4548/ZOOKEEPER-2137 style
bugs), repeated for many rounds like a real integration test: each round a
*writer* process creates its status file and then fills it in
(non-atomically — create, compute, write); a *reader* process spins until
the file exists and immediately consumes it, assuming creation implies
content, then acknowledges by removing the file. Under normal scheduling
the create->write window is tens of microseconds and the reader virtually
never catches it. A scheduler fuzzer that gives the reader priority over
the writer stretches the window by orders of magnitude and the reader
observes the half-initialized state.

Both processes pin to CPU 0 so the kernel scheduler — the thing the fuzzer
perturbs — decides who runs inside the window.

Exit status: 0 = all rounds consistent, 1 = race manifested.
"""

import os
import sys
import time

ROUNDS = 150
DEADLINE_S = 8.0

# the create->write preemption window, in busy-loop iterations — the
# scenario's one timing knob, calibrated (not hand-tuned) into the
# 2-10% baseline-repro band by `nmz-tpu tools calibrate`: the value
# rides in from calibration.json as environment (NMZ_CALIB_<NAME>,
# namazu_tpu/calibrate), [calibration] table in ../config.toml
INIT_WINDOW_ITERS = int(os.environ.get("NMZ_CALIB_INIT_WINDOW_ITERS",
                                       "400"))


def writer(path: str, ack: str) -> None:
    for _ in range(ROUNDS):
        # phase 1: create the status file (visible to the reader at once)
        fd = os.open(path, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o644)
        # ... the preemption window: some "initialization work" ...
        x = 0
        for i in range(INIT_WINDOW_ITERS):
            x += i * i
        # phase 2: fill in the content
        os.write(fd, b"ready=1 checksum=%d\n" % (x % 997))
        os.close(fd)
        # wait for the reader's ack (it removes the file)
        t0 = time.monotonic()
        while os.path.exists(path):
            if time.monotonic() - t0 > 2.0:
                return
    # signal completion
    open(ack, "w").close()


def reader(path: str, ack: str) -> int:
    t0 = time.monotonic()
    rounds = 0
    while rounds < ROUNDS and time.monotonic() - t0 < DEADLINE_S:
        if os.path.exists(ack):
            break
        if os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                continue
            if not data:
                return 1  # the faulty assumption bites: empty status file
            os.unlink(path)
            rounds += 1
    return 0


def main() -> int:
    workdir = sys.argv[1] if len(sys.argv) > 1 else "."
    path = os.path.join(workdir, "status.file")
    ack = os.path.join(workdir, "done.marker")
    for p in (path, ack):
        if os.path.exists(p):
            os.unlink(p)
    try:
        os.sched_setaffinity(0, {0})
    except OSError:
        pass
    pid = os.fork()
    if pid == 0:
        writer(path, ack)
        os._exit(0)
    rc = reader(path, ack)
    os.waitpid(pid, 0)
    return rc


if __name__ == "__main__":
    sys.exit(main())
