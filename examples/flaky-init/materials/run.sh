#!/bin/sh
# Run the flaky test under the proc inspector, which reports the process
# tree to the orchestrator (REST) and applies the policy's scheduler
# attributes. PALLAS_AXON_POOL_IPS= skips this image's TPU plugin boot in
# the short-lived helper interpreters (it costs ~2s per python startup).
PORT="${NMZ_REST_PORT:-10980}"
PALLAS_AXON_POOL_IPS= python -m namazu_tpu.cli inspectors proc \
    --orchestrator-url "http://127.0.0.1:${PORT}" \
    --entity-id racy \
    --watch-interval 0.01 \
    --cmd "PALLAS_AXON_POOL_IPS= python \"$NMZ_MATERIALS_DIR/racy.py\" \"$NMZ_WORKING_DIR\""
rc=$?
echo "$rc" > "$NMZ_WORKING_DIR/rc.txt"
exit 0
