#!/bin/sh
# Oracle: the run reproduced the race iff racy.py exited non-zero.
# validate succeeding == test passed (no repro), matching the reference's
# convention (repro rate = failure rate in `tools summary`).
test "$(cat "$NMZ_WORKING_DIR/rc.txt")" = "0"
