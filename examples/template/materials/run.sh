#!/bin/sh
# Send two messages through the orchestrator and record the order the
# policy released them in. PALLAS_AXON_POOL_IPS= skips this image's TPU
# plugin boot in the short-lived interpreter (~2s per python startup).
PORT="${NMZ_REST_PORT:-10983}"
PALLAS_AXON_POOL_IPS= python "$NMZ_MATERIALS_DIR/pingpong.py" \
    "http://127.0.0.1:${PORT}" "$NMZ_WORKING_DIR/order.txt"
