"""A template for your own exploration policy.

Parity: /root/reference/example/template/mypolicy.go:15-80 — the
documented plugin entry point. The Go version must be compiled into its
own ``main`` that wraps the whole CLI; here the same file works BOTH
ways:

* **config-driven** (preferred): the experiment config names this file
  in ``policy_plugins`` and sets ``explore_policy = "mypolicy"`` — the
  stock ``nmz-tpu init/run`` loads it from the materials dir, no custom
  binary;
* **reference-style**: ``python mypolicy.py init|run ...`` is its own
  driver, exactly like the Go template's ``main()``.

The policy itself demonstrates the three things every policy does:
consume events without blocking, decide an order, and emit actions.
This one releases each window of pending events in REVERSE arrival
order ("pong" before "ping") — trivially wrong as a fuzzer, obviously
visible in a trace, which is the point of a template.
"""

from namazu_tpu.policy.base import QueueBackedPolicy, register_policy
from namazu_tpu.signal.event import Event, ProcSetEvent
from namazu_tpu.signal.action import ProcSetSchedAction
from namazu_tpu.utils.config import parse_duration


class MyPolicy(QueueBackedPolicy):
    NAME = "mypolicy"

    def __init__(self) -> None:
        super().__init__()
        self.hold = 0.05  # seconds each event is held back

    def load_config(self, config) -> None:
        # read your knobs from [explore_policy_param]
        self.hold = parse_duration(config.policy_param("hold", 50))

    def queue_event(self, event: Event) -> None:
        """Called for EVERY intercepted event; must never block.

        Possible events mirror the reference template's comment
        (mypolicy.go:48-53): PacketEvent, FilesystemEvent, ProcSetEvent,
        LogEvent, FunctionEvent. Fault actions (PacketFaultAction,
        FilesystemFaultAction, ShellAction) can be emitted instead of
        the default — see event.default_fault_action().
        """
        self.start()
        if isinstance(event, ProcSetEvent):
            # procfs events want scheduler attributes, not a release
            self._emit(ProcSetSchedAction.for_procset(event, {}))
            return
        # the ScheduledQueue releases each event at now+bound; holding
        # the n-th arrival for hold/n makes later arrivals OVERTAKE
        # earlier ones whenever they come close together — a visibly
        # "impossible" order a passthrough policy never produces, easy
        # to spot in `tools dump-trace`
        self._n = getattr(self, "_n", 0) + 1
        self._queue.put_at(event, self.hold / self._n)


register_policy(MyPolicy.NAME, MyPolicy)


if __name__ == "__main__":
    # reference-style standalone driver (mypolicy.go:73-80): this file
    # IS the CLI, with the policy pre-registered
    import sys

    from namazu_tpu.cli import cli_main

    sys.exit(cli_main(sys.argv[1:]))
