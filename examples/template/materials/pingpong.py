"""Minimal testee for the policy template: two messages through the
orchestrator, realized order written out.

Sends a "first" then a "second" PacketEvent via the REST endpoint (the
same wire real inspectors use) and records the order the policy RELEASED
them in. Under ``mypolicy`` (later arrivals release earlier) the realized
order is second,first — an order a passthrough policy never produces, so
validate.sh can assert the plugin actually drove the schedule.
"""

import sys
import threading
import time

from namazu_tpu.inspector.transceiver import new_transceiver
from namazu_tpu.signal import PacketEvent


def main() -> int:
    url, out_path = sys.argv[1], sys.argv[2]
    trans = new_transceiver(url, "pingpong")
    trans.start()

    order, lock = [], threading.Lock()

    def send(tag: str, delay: float):
        time.sleep(delay)
        ch = trans.send_event(PacketEvent.create(
            "pingpong", "client", "server", hint=tag))
        act = ch.get(timeout=30)
        assert act is not None, f"no action for {tag}"
        with lock:
            order.append(tag)

    threads = []
    # "first" demonstrably arrives before "second" (40 ms apart — well
    # inside mypolicy's default 200 ms hold, so the overtake triggers)
    for tag, delay in (("first", 0.0), ("second", 0.04)):
        t = threading.Thread(target=send, args=(tag, delay))
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=60)

    with open(out_path, "w") as f:
        f.write(",".join(order) + "\n")
    print("released order:", ",".join(order))
    return 0


if __name__ == "__main__":
    sys.exit(main())
