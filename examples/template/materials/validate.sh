#!/bin/sh
# "Bug found" = the policy reordered the messages: the later-sent
# "second" was released before "first". Under mypolicy this happens
# every run (deterministic overtake); under the dumb passthrough it
# never does — so the A/B over this pair demonstrates that the plugin
# actually drove the schedule.
test "$(cat "$NMZ_WORKING_DIR/order.txt")" = "second,first" && exit 1
exit 0
