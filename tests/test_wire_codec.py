"""Binary wire codec + sharded edge: the round-9 serving plane.

Pins (doc/performance.md "Binary wire + sharded edge"):

* the binary codec round-trips EVERY registered signal class's wire
  dict losslessly (span context, option payloads, action fields), with
  IEEE-754 bit-exact doubles;
* negotiation is per connection and loss-free for pre-binary peers
  (JSON stays the default; a binary 400 downgrades and resends; a
  garbled-in-flight payload retries in place WITHOUT downgrading);
* garbage/truncated frames are rejected per frame, never severing the
  keep-alive stream;
* mixed-codec clients share one endpoint;
* trace-differ equivalence (order AND delays) holds binary-vs-JSON and
  sharded-vs-single-dispatcher;
* the shared-memory ring moves event batches exactly-once with the
  ``wire.shm.drop`` losses accounted;
* the burst API delivers grouped verdicts for ripe groups and real
  actions for parked events, with the backhaul reconciling a complete
  trace.
"""

import json
import math
import os
import random
import struct
import time

import pytest

from namazu_tpu import chaos, obs
from namazu_tpu.chaos.plan import FaultPlan
from namazu_tpu.obs import export, metrics, recorder as recorder_mod
from namazu_tpu.obs.metrics import MetricsRegistry
from namazu_tpu.obs.recorder import FlightRecorder
from namazu_tpu.signal import PacketEvent, binary
from namazu_tpu.signal.base import (get_signal_class,
                                    known_signal_classes,
                                    signal_from_jsonable)
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.config import Config


@pytest.fixture(autouse=True)
def _fresh_obs():
    reg = metrics.set_registry(MetricsRegistry())
    was = metrics.enabled()
    metrics.configure(True)
    rec = recorder_mod.set_recorder(
        FlightRecorder(max_runs=8, max_records=1 << 14))
    chaos.clear()
    yield
    chaos.clear()
    metrics.set_registry(reg)
    metrics.configure(was)
    recorder_mod.set_recorder(rec)


# -- codec properties ------------------------------------------------------


def _instance_of(cls):
    """A minimally-valid instance of one registered signal class."""
    option = {field: f"v-{field}" for field, required
              in cls.OPTION_FIELDS.items() if required}
    try:
        return cls(entity_id="ent-x", option=option)
    except Exception:
        return None


def test_every_registered_signal_roundtrips_binary():
    """THE codec seam property: for every registered class, the binary
    round trip of ``to_jsonable()`` is the identical wire dict — so
    ``signal_from_jsonable`` reconstructs the identical signal, span
    context included."""
    covered = 0
    for name in known_signal_classes():
        sig = _instance_of(get_signal_class(name))
        if sig is None:
            continue
        sig._obs_ctx = {"lc": 987654321, "o": "77@host", "r": "run-9"}
        d = sig.to_jsonable()
        got = binary.loads(binary.dumps(d))
        assert got == d, f"{name}: binary round trip diverged"
        # and through the one decode seam both ways
        twin = signal_from_jsonable(got)
        assert twin.equals(sig), f"{name}: decoded twin differs"
        assert twin._obs_ctx == sig._obs_ctx
        covered += 1
    assert covered >= 10, f"only {covered} classes constructible"


def test_binary_doubles_are_bit_exact():
    rng = random.Random(17)
    doubles = [struct.unpack("<d", struct.pack(
        "<Q", rng.getrandbits(62)))[0] for _ in range(512)]
    doc = {"version": 3, "mode": "delay", "H": 512,
           "max_interval": 1e-9, "delays": doubles}
    got = binary.loads(binary.dumps(doc))
    for a, b in zip(got["delays"], doubles):
        assert struct.pack("<d", a) == struct.pack("<d", b)
    assert math.isnan(binary.loads(binary.dumps(float("nan"))))
    assert binary.loads(binary.dumps(float("inf"))) == float("inf")


def test_binary_value_fuzz_roundtrip():
    rng = random.Random(23)

    def rand_val(depth=0):
        kinds = ["int", "float", "str", "bool", "none"] + (
            ["list", "dict", "sig"] if depth < 3 else [])
        k = rng.choice(kinds)
        if k == "int":
            return rng.choice([0, 1, -1, 127, -128, 2 ** 31 - 1,
                               -2 ** 31, 2 ** 63 - 1, -2 ** 63,
                               2 ** 90, rng.randint(-10 ** 9, 10 ** 9)])
        if k == "float":
            return rng.choice([0.0, -0.0, 1e-300, float("inf"),
                               rng.random() * 1e9])
        if k == "str":
            return "".join(chr(rng.randint(32, 0x2FFF))
                           for _ in range(rng.randint(0, 300)))
        if k == "bool":
            return rng.random() < 0.5
        if k == "none":
            return None
        if k == "list":
            return [rand_val(depth + 1)
                    for _ in range(rng.randint(0, 6))]
        if k == "sig":
            d = {"class": "X", "entity": "e",
                 "uuid": "u" * rng.randint(1, 300),
                 "option": rand_val(depth + 1)}
            if rng.random() < 0.7:
                d["type"] = rng.choice(["event", "action", "weird"])
            if rng.random() < 0.5:
                d["ctx"] = {"lc": rng.randint(0, 2 ** 40), "o": "p@h"}
            return d
        return {f"k{i}": rand_val(depth + 1)
                for i in range(rng.randint(0, 6))}

    for i in range(400):
        v = rand_val()
        assert binary.loads(binary.dumps(v)) == v, f"case {i}"


def test_signal_batch_encoding_is_smaller_and_shares_ctx():
    evs = [PacketEvent.create("e0", "e0", "peer", hint=f"h{i % 32}")
           for i in range(64)]
    shared = {"lc": 5, "o": "p@h"}
    for ev in evs:
        ev._obs_ctx = shared  # the mint_many contract: ONE dict/burst
    batch = [ev.to_jsonable() for ev in evs]
    bb = binary.dumps(batch)
    jb = json.dumps(batch).encode()
    assert binary.loads(bb) == json.loads(jb)
    # the template batch must beat JSON by a wide margin (ctx once,
    # no per-event key strings)
    assert len(bb) < 0.55 * len(jb), (len(bb), len(jb))


def test_garbled_and_truncated_frames_raise_valueerror():
    evs = [PacketEvent.create("e0", "e0", "p", hint=f"h{i}")
           for i in range(16)]
    data = binary.dumps([e.to_jsonable() for e in evs])
    rng = random.Random(5)
    buf = bytearray(data)
    for _ in range(1500):
        i = rng.randrange(len(buf))
        old = buf[i]
        buf[i] ^= rng.randrange(1, 256)
        try:
            binary.loads(bytes(buf))
        except ValueError:
            pass  # the only acceptable failure mode
        buf[i] = old
    for cut in range(0, len(data), 97):
        try:
            binary.loads(data[:cut])
        except ValueError:
            pass


# -- negotiation + interop -------------------------------------------------


def _uds_stack(tmp_path, name, **tx_kw):
    from namazu_tpu.endpoint.hub import EndpointHub
    from namazu_tpu.endpoint.uds import UdsEndpoint
    from namazu_tpu.inspector.uds_transceiver import UdsTransceiver
    from namazu_tpu.utils.mock_orchestrator import MockOrchestrator

    path = str(tmp_path / f"{name}.sock")
    hub = EndpointHub()
    uds = UdsEndpoint(path, poll_timeout=2.0)
    hub.add_endpoint(uds)
    mock = MockOrchestrator(hub)
    mock.start()
    tx = UdsTransceiver("e0", path, poll_linger=0.005, **tx_kw)
    tx.start()
    return hub, uds, mock, tx


def test_uds_negotiates_binary_and_json_client_stays_json(tmp_path):
    hub, uds, mock, tx = _uds_stack(tmp_path, "nego")
    try:
        ch = tx.send_event(PacketEvent.create("e0", "e0", "p", hint="a"))
        assert ch.get(timeout=10) is not None
        assert tx._post_conn.codec == binary.CODEC_BINARY
        assert metrics.registry().value(
            "nmz_codec_negotiations_total",
            codec=binary.CODEC_BINARY) >= 1.0
        # byte ledger: the negotiated wire counted under its codec
        doc = metrics.registry().to_jsonable()
        codecs = {(s["labels"].get("codec"))
                  for m in doc["metrics"]
                  if m["name"] == "nmz_wire_bytes_total"
                  for s in m["samples"]}
        assert binary.CODEC_BINARY in codecs
    finally:
        tx.shutdown()
        mock.shutdown()
        hub.shutdown()

    # a json-pinned client on the same endpoint never upgrades
    hub, uds, mock, tx = _uds_stack(tmp_path, "nego2", codec="json")
    try:
        ch = tx.send_event(PacketEvent.create("e0", "e0", "p", hint="b"))
        assert ch.get(timeout=10) is not None
        assert tx._post_conn.codec == binary.CODEC_JSON
    finally:
        tx.shutdown()
        mock.shutdown()
        hub.shutdown()


def test_mixed_codec_clients_share_one_endpoint(tmp_path):
    from namazu_tpu.endpoint.hub import EndpointHub
    from namazu_tpu.endpoint.uds import UdsEndpoint
    from namazu_tpu.inspector.uds_transceiver import UdsTransceiver
    from namazu_tpu.utils.mock_orchestrator import MockOrchestrator

    path = str(tmp_path / "mixed.sock")
    hub = EndpointHub()
    hub.add_endpoint(UdsEndpoint(path, poll_timeout=2.0))
    mock = MockOrchestrator(hub)
    mock.start()
    txs = {
        "jent": UdsTransceiver("jent", path, codec="json",
                               poll_linger=0.005),
        "bent": UdsTransceiver("bent", path, codec="auto",
                               poll_linger=0.005),
    }
    try:
        for tx in txs.values():
            tx.start()
        chans = []
        for i in range(12):
            for ent, tx in txs.items():
                chans.append(tx.send_event(
                    PacketEvent.create(ent, ent, "p", hint=f"h{i}")))
        for ch in chans:
            assert ch.get(timeout=10) is not None
        assert txs["jent"]._post_conn.codec == binary.CODEC_JSON
        assert txs["bent"]._post_conn.codec == binary.CODEC_BINARY
    finally:
        for tx in txs.values():
            tx.shutdown()
        mock.shutdown()
        hub.shutdown()


def test_pre_binary_rest_server_keeps_auto_client_on_json(tmp_path):
    """Interop: a server that never advertises the codec (the
    pre-binary peer) serves an auto client a complete run on pure
    JSON — negotiation is the piggyback, absence means never
    upgrade."""
    from namazu_tpu.orchestrator import Orchestrator
    from namazu_tpu.inspector.rest_transceiver import RestTransceiver
    from namazu_tpu.policy import create_policy

    cfg = Config({"rest_port": 0, "run_id": "prebin",
                  "explore_policy": "random",
                  "explore_policy_param": {
                      "seed": 2, "min_interval": "1ms",
                      "max_interval": "1ms",
                      "fault_action_probability": 0.0,
                      "shell_action_interval": 0}})
    policy = create_policy("random")
    policy.load_config(cfg)
    orc = Orchestrator(cfg, policy, collect_trace=True)
    orc.start()
    rest = orc.hub.endpoint("rest")
    rest.advertise_codec = False  # simulate the pre-binary server
    tx = RestTransceiver("e0", f"http://127.0.0.1:{rest.port}",
                         use_batch=True, flush_window=0.0,
                         poll_linger=0.005, codec="auto")
    tx.start()
    try:
        chans = [tx.send_event(PacketEvent.create("e0", "e0", "p",
                                                  hint=f"h{i}"))
                 for i in range(8)]
        for ch in chans:
            assert ch.get(timeout=15) is not None
        assert tx._post_conn.accepts_binary is False
        assert tx._codec_down is False  # never upgraded, never burned
    finally:
        tx.shutdown()
        orc.shutdown()
    assert len(orc.trace) == 8  # loss-free on the legacy wire


def test_binary_400_downgrades_and_resends():
    """A non-garble 400 answered to a binary request = the peer cannot
    take this codec: downgrade to JSON permanently, resend the SAME
    chunk, lose nothing."""
    from namazu_tpu.inspector.rest_transceiver import RestTransceiver

    tx = RestTransceiver("dg0", "http://127.0.0.1:1", use_batch=True,
                         flush_window=0.0, codec="binary")
    sent = []

    def fake(method, path, body=None, codec="json"):
        sent.append((codec, body[:2]))
        if codec == binary.CODEC_BINARY:
            tx._post_conn.last_codec_error = None
            return 400, b'{"error": "cannot decode"}'
        return 200, b"{}"

    tx._post_conn.request = fake
    events = [PacketEvent.create("dg0", "dg0", "p", hint="h")]
    tx._post_batch_once(events, "dg0")
    assert tx._codec_down is True
    assert [c for c, _ in sent] == [binary.CODEC_BINARY,
                                    binary.CODEC_JSON]
    assert sent[0][1] == binary.MAGIC  # really was a binary body


def test_garbled_binary_retries_in_place_without_downgrade(tmp_path):
    """The wire.binary.garble chaos contract end to end over REST: the
    server 400s the damaged payload tagged ``garbled``, the bounded
    retry resends a clean copy on the SAME codec, dispatch is
    exactly-once, and the connection was never severed."""
    from namazu_tpu.orchestrator import Orchestrator
    from namazu_tpu.inspector.rest_transceiver import RestTransceiver
    from namazu_tpu.policy import create_policy

    cfg = Config({"rest_port": 0, "run_id": "garble",
                  "explore_policy": "random",
                  "explore_policy_param": {
                      "seed": 3, "min_interval": "1ms",
                      "max_interval": "1ms",
                      "fault_action_probability": 0.0,
                      "shell_action_interval": 0}})
    policy = create_policy("random")
    policy.load_config(cfg)
    orc = Orchestrator(cfg, policy, collect_trace=True)
    orc.start()
    port = orc.hub.endpoint("rest").port
    tx = RestTransceiver("e0", f"http://127.0.0.1:{port}",
                         use_batch=True, flush_window=0.0,
                         poll_linger=0.005, codec="auto",
                         backoff_step=0.02, backoff_max=0.1)
    tx.start()
    plan = chaos.install(FaultPlan(1, {"wire.binary.garble":
                                       {"at": [0]}}))
    try:
        chans = [tx.send_event(PacketEvent.create("e0", "e0", "p",
                                                  hint=f"h{i}"))
                 for i in range(6)]
        for ch in chans:
            assert ch.get(timeout=15) is not None
        assert plan.fired("wire.binary.garble") == 1
        assert tx._codec_down is False  # garble never downgrades
    finally:
        chaos.clear()
        tx.shutdown()
        orc.shutdown()
    from collections import Counter

    counts = Counter(a.event_uuid for a in orc.trace if a.event_uuid)
    assert len(counts) == 6 and all(c == 1 for c in counts.values())


# -- trace-differ equivalence ---------------------------------------------

ENTITIES = ("eqa", "eqb")
HINTS = tuple(f"k{i}" for i in range(6))


def _run_eq(run_id, *, codec="auto", edge=False, shard_pool=None,
            delays=None, burst=False):
    from namazu_tpu.orchestrator import Orchestrator
    from namazu_tpu.inspector.rest_transceiver import RestTransceiver
    from namazu_tpu.policy import create_policy

    cfg = Config({"rest_port": 0, "run_id": run_id,
                  "explore_policy": "tpu_search",
                  "explore_policy_param": {
                      "search_on_start": False, "max_interval": 0,
                      "seed": 7}})
    policy = create_policy("tpu_search")
    policy.load_config(cfg)
    policy.install_table(delays if delays is not None
                         else [0.0] * policy.H, source="test")
    orc = Orchestrator(cfg, policy, collect_trace=True)
    orc.start()
    port = orc.hub.endpoint("rest").port
    txs = {e: RestTransceiver(e, f"http://127.0.0.1:{port}",
                              use_batch=True, flush_window=0.0,
                              poll_linger=0.005, edge=edge,
                              codec=codec, shard_pool=shard_pool,
                              backhaul_window=0.01)
           for e in ENTITIES}
    for tx in txs.values():
        tx.start()
        if edge:
            assert tx.sync_table() is not None
    try:
        if burst:
            handles = []
            for e in ENTITIES:
                evs = [PacketEvent.create(e, e, "peer", hint=h)
                       for h in HINTS]
                handles.append(txs[e].send_events_burst(evs))
            for h in handles:
                h.get_all(timeout=15)
        else:
            chans = []
            for h in HINTS:
                for e in ENTITIES:
                    ev = PacketEvent.create(e, e, "peer", hint=h)
                    chans.append(txs[e].send_event(ev))
            for ch in chans:
                assert ch.get(timeout=15) is not None
    finally:
        for tx in txs.values():
            tx.shutdown()
        orc.shutdown()
    run = obs.trace_run(run_id)
    assert run is not None
    return [entry["json"] for entry in run.snapshot()["records"]]


def _delays_by_identity(docs):
    return {(d["entity"], d["hint"]): d["decision"]["delay"]
            for d in docs if d.get("decision")}


def test_binary_vs_json_runs_are_trace_equivalent():
    """Order AND delays identical across the codec switch — the codec
    moves bytes, never semantics."""
    docs_j = _run_eq("eq-json", codec="json")
    docs_b = _run_eq("eq-binary", codec="binary")
    diff = export.diff_order(export.order_lines_from_docs(docs_j),
                             export.order_lines_from_docs(docs_b),
                             "json", "binary")
    assert diff == "", f"dispatch order diverged:\n{diff}"
    assert _delays_by_identity(docs_j) == _delays_by_identity(docs_b)


def test_sharded_vs_single_dispatcher_trace_equivalent():
    """Order AND delays identical between one EdgeDispatcher per
    transceiver and the EdgeShardPool — sharding moves threads, never
    decisions."""
    from namazu_tpu.inspector.edge import EdgeShardPool

    docs_one = _run_eq("eq-edge1", edge=True)
    pool = EdgeShardPool(2, backhaul_window=0.01)
    docs_sh = _run_eq("eq-edge2", edge=True, shard_pool=pool)
    diff = export.diff_order(export.order_lines_from_docs(docs_one),
                             export.order_lines_from_docs(docs_sh),
                             "single", "sharded")
    assert diff == "", f"dispatch order diverged:\n{diff}"
    assert _delays_by_identity(docs_one) == _delays_by_identity(docs_sh)
    # both really decided at the edge
    for docs in (docs_one, docs_sh):
        assert all((d.get("decision") or {}).get("decision_source")
                   == "edge" for d in docs if d.get("decision"))


def test_sharded_nonzero_delays_decisions_bit_equal():
    """Nonzero per-hint delays through the parked/release path: the
    pool's decisions equal the single dispatcher's per identity (the
    release ORDER across shard threads is timing, the DECISIONS are
    the contract)."""
    from namazu_tpu.inspector.edge import EdgeShardPool
    from namazu_tpu.policy import create_policy

    probe = create_policy("tpu_search")
    H = probe.H
    delays = [0.0] * H
    # give half the hint buckets a small positive delay
    for i in range(0, H, 2):
        delays[i] = 0.012
    docs_one = _run_eq("eqn-edge1", edge=True, delays=delays)
    pool = EdgeShardPool(2, backhaul_window=0.01)
    docs_sh = _run_eq("eqn-edge2", edge=True, shard_pool=pool,
                      delays=delays)
    d1, d2 = _delays_by_identity(docs_one), _delays_by_identity(docs_sh)
    assert d1 == d2 and len(d1) == len(ENTITIES) * len(HINTS)


# -- burst API -------------------------------------------------------------


def test_burst_grouped_verdict_and_parked_actions():
    from namazu_tpu.inspector.edge import BurstAccept
    from namazu_tpu.orchestrator import Orchestrator
    from namazu_tpu.inspector.rest_transceiver import RestTransceiver
    from namazu_tpu.policy import create_policy
    from namazu_tpu.policy.replayable import fnv64a

    cfg = Config({"rest_port": 0, "run_id": "burst-mixed",
                  "explore_policy": "tpu_search",
                  "explore_policy_param": {
                      "search_on_start": False, "max_interval": 0,
                      "seed": 7}})
    policy = create_policy("tpu_search")
    policy.load_config(cfg)
    H = policy.H
    ripe_hint, parked_hint = "zero-hint", "slow-hint"
    delays = [0.0] * H
    parked_bucket = fnv64a(
        f"bm->peer:{parked_hint}".encode()) % H
    delays[parked_bucket] = 0.03
    policy.install_table(delays, source="test")
    orc = Orchestrator(cfg, policy, collect_trace=True)
    orc.start()
    port = orc.hub.endpoint("rest").port
    tx = RestTransceiver("bm", f"http://127.0.0.1:{port}",
                         use_batch=True, flush_window=0.0,
                         poll_linger=0.005, edge=True,
                         backhaul_window=0.01)
    tx.start()
    assert tx.sync_table() is not None
    try:
        evs = ([PacketEvent.create("bm", "bm", "peer", hint=ripe_hint)
                for _ in range(6)]
               + [PacketEvent.create("bm", "bm", "peer",
                                     hint=parked_hint)
                  for _ in range(2)])
        t0 = time.monotonic()
        handle = tx.send_events_burst(evs)
        items = handle.get_all(timeout=15)
        assert time.monotonic() - t0 >= 0.02  # waited out the parked
        groups = [i for i in items if isinstance(i, BurstAccept)]
        actions = [i for i in items if not isinstance(i, BurstAccept)]
        assert len(groups) == 1
        assert groups[0].count == 6
        assert sorted(groups[0].uuids) == sorted(
            e.uuid for e in evs[:6])
        assert groups[0].table_version == tx._edge.table_version
        assert len(actions) == 2  # parked events arrive as actions
        assert {a.event_uuid for a in actions} == {
            e.uuid for e in evs[6:]}
    finally:
        tx.shutdown()
        orc.shutdown()
    run = obs.trace_run("burst-mixed")
    docs = [e["json"] for e in run.snapshot()["records"]]
    by_uuid = {d["event"]: d for d in docs}
    # the backhaul reconciled a complete trace with per-event decisions
    assert set(by_uuid) == {e.uuid for e in evs}
    for e in evs:
        dec = by_uuid[e.uuid]["decision"]
        assert dec["decision_source"] == "edge"
        want = 0.03 if e.replay_hint().endswith(parked_hint) else 0.0
        assert dec["delay"] == want


def test_burst_without_table_goes_central():
    """No published table synced: the whole burst rides the central
    wire and every event is answered with a real action."""
    from namazu_tpu.inspector.edge import BurstAccept
    from namazu_tpu.orchestrator import Orchestrator
    from namazu_tpu.inspector.rest_transceiver import RestTransceiver
    from namazu_tpu.policy import create_policy

    cfg = Config({"rest_port": 0, "run_id": "burst-central",
                  "explore_policy": "random",
                  "explore_policy_param": {
                      "seed": 4, "min_interval": "1ms",
                      "max_interval": "1ms",
                      "fault_action_probability": 0.0,
                      "shell_action_interval": 0}})
    policy = create_policy("random")
    policy.load_config(cfg)
    orc = Orchestrator(cfg, policy, collect_trace=True)
    orc.start()
    port = orc.hub.endpoint("rest").port
    tx = RestTransceiver("bc", f"http://127.0.0.1:{port}",
                         use_batch=True, flush_window=0.0,
                         poll_linger=0.005, edge=True)
    tx.start()  # edge armed but dormant: nothing published
    try:
        evs = [PacketEvent.create("bc", "bc", "peer", hint=f"h{i}")
               for i in range(8)]
        items = tx.send_events_burst(evs).get_all(timeout=15)
        assert not any(isinstance(i, BurstAccept) for i in items)
        assert {a.event_uuid for a in items} == {e.uuid for e in evs}
    finally:
        tx.shutdown()
        orc.shutdown()


# -- shard pool ------------------------------------------------------------


def test_shard_pool_hashing_and_lifecycle():
    from namazu_tpu.inspector.edge import EdgeShardPool
    from namazu_tpu.policy.replayable import fnv64a

    pool = EdgeShardPool(3, backhaul_window=0.01)
    handles = []
    for i in range(9):
        ent = f"ent{i}"
        h = pool.register(ent, deliver=lambda a: None,
                          deliver_many=None,
                          fetch_table=lambda: (0, None),
                          send_backhaul=lambda e, items: None)
        assert h.shard is pool.shards[
            fnv64a(ent.encode()) % 3]
        handles.append(h)
    assert not pool.closed
    for h in handles:
        h.shutdown()
    assert pool.closed  # last unregister closes the pool
    # a closed pool refuses registration
    with pytest.raises(RuntimeError):
        pool.register("late", deliver=lambda a: None,
                      deliver_many=None,
                      fetch_table=lambda: (0, None),
                      send_backhaul=lambda e, items: None)


# -- shared-memory ring ----------------------------------------------------


def test_shm_ring_roundtrip_wrap_and_full(tmp_path):
    from namazu_tpu.endpoint.shm import ShmRing

    path = str(tmp_path / "ring")
    ring = ShmRing(path, capacity=256, create=True)
    reader = ShmRing(path)
    try:
        payloads = [os.urandom(60) for _ in range(40)]
        written = 0
        read_back = []
        for p in payloads:
            # drive the ring around its wrap point several times
            while not ring.try_write_frame(p, binary=True):
                frame = reader.try_read_frame()
                assert frame is not None
                read_back.append(frame)
            written += 1
        while len(read_back) < written:
            frame = reader.try_read_frame()
            assert frame is not None
            read_back.append(frame)
        assert [p for p, _ in read_back] == payloads
        assert all(b for _, b in read_back)
        # an oversized frame is refused, not wedged
        assert ring.try_write_frame(b"x" * 300) is False
    finally:
        reader.close()
        ring.close()
        ring.unlink()


def test_shm_transceiver_exactly_once_with_accounted_drop(tmp_path):
    """Events ride the ring into the same dedupe + hub path; a
    ``wire.shm.drop`` burst is the accounted-loss case — lost ==
    fired, everything else exactly-once."""
    from namazu_tpu.endpoint.hub import EndpointHub
    from namazu_tpu.endpoint.uds import UdsEndpoint
    from namazu_tpu.inspector.uds_transceiver import UdsTransceiver
    from namazu_tpu.utils.mock_orchestrator import MockOrchestrator

    path = str(tmp_path / "shm.sock")
    hub = EndpointHub()
    hub.add_endpoint(UdsEndpoint(path, poll_timeout=2.0))
    mock = MockOrchestrator(hub)
    mock.start()
    tx = UdsTransceiver("e0", path, shm=True, poll_linger=0.005,
                        post_attempts=1)
    tx.start()
    assert tx._shm_ring is not None
    plan = chaos.install(FaultPlan(9, {"wire.shm.drop": {"at": [2]}}))
    chans = {}
    try:
        for i in range(10):
            ev = PacketEvent.create("e0", "e0", "p", hint=f"h{i}")
            chans[ev.uuid] = tx.send_event(ev)
        dropped = plan.fired("wire.shm.drop")
        assert dropped == 1
        answered = 0
        deadline = time.monotonic() + 15
        while answered < len(chans) - dropped \
                and time.monotonic() < deadline:
            answered = 0
            for ch in chans.values():
                if not ch.empty():
                    answered += 1
            time.sleep(0.02)
        assert answered == len(chans) - dropped
    finally:
        chaos.clear()
        tx.shutdown()
        mock.shutdown()
        hub.shutdown()


def test_shm_full_ring_falls_back_to_acked_op_wire(tmp_path):
    from namazu_tpu.endpoint.hub import EndpointHub
    from namazu_tpu.endpoint.uds import UdsEndpoint
    from namazu_tpu.inspector.uds_transceiver import UdsTransceiver
    from namazu_tpu.utils.mock_orchestrator import MockOrchestrator

    path = str(tmp_path / "full.sock")
    hub = EndpointHub()
    hub.add_endpoint(UdsEndpoint(path, poll_timeout=2.0))
    mock = MockOrchestrator(hub)
    mock.start()
    tx = UdsTransceiver("e0", path, shm=True, poll_linger=0.005)
    tx.start()
    try:
        # shrink the mapped ring to something trivially overflowable:
        # the transceiver must fall back to the acked uds op, loss-free
        class _Tiny:
            def try_write_frame(self, payload, binary=True):
                return False

            def pending(self):
                return 0

            def close(self):
                pass

        tx._shm_ring = _Tiny()
        chans = [tx.send_event(PacketEvent.create("e0", "e0", "p",
                                                  hint=f"h{i}"))
                 for i in range(6)]
        for ch in chans:
            assert ch.get(timeout=10) is not None
    finally:
        tx.shutdown()
        mock.shutdown()
        hub.shutdown()


# -- review-hardening regressions ------------------------------------------


def test_uds_transceiver_constructs_with_edge_shards(tmp_path):
    """The uds twin of the sharded-edge knob really constructs (a
    missing module import made ``edge_shards>1`` a NameError on the
    uds wire only — no test passed through new_transceiver's kwargs)."""
    from namazu_tpu.inspector.transceiver import new_transceiver

    tx = new_transceiver(f"uds://{tmp_path}/none.sock", "e0",
                         edge=True, edge_shards=2, codec="auto")
    assert tx._edge is not None and tx._edge.shard is not None
    tx._edge.shutdown()


def test_batch_ctx_is_never_fabricated_for_ctxless_rows():
    """A batch mixing ctx-carrying and ctx-LESS events must decode
    with the absence preserved — the template-ctx optimization only
    applies when every row shares the exact ctx (a fabricated clock
    would invent a happens-before relation in the causality graph)."""
    evs = [PacketEvent.create("e0", "e0", "p", hint=f"h{i}")
           for i in range(4)]
    shared = {"lc": 9, "o": "p@h"}
    for ev in evs[:3]:
        ev._obs_ctx = shared
    batch = [ev.to_jsonable() for ev in evs]
    assert "ctx" not in batch[3]
    got = binary.loads(binary.dumps(batch))
    assert got == batch
    assert "ctx" not in got[3] and got[0]["ctx"] == shared
    # and the all-shared batch still rides the template (stays small)
    for ev in evs:
        ev._obs_ctx = shared
    batch = [ev.to_jsonable() for ev in evs]
    assert binary.loads(binary.dumps(batch)) == batch


def test_pool_backhaul_for_departed_entity_drops_not_wedges():
    """A backhaul record enqueued for an entity whose route is gone
    (a release that slipped past its unregister drain) must be
    DROPPED — re-queueing it forever would wedge every other entity's
    trace records behind it on the shared shard."""
    from namazu_tpu.inspector.edge import EdgeShardPool

    pool = EdgeShardPool(1, backhaul_window=30.0)
    delivered = []
    h_keep = pool.register("keep", deliver=lambda a: None,
                           deliver_many=None,
                           fetch_table=lambda: (0, None),
                           send_backhaul=lambda e, items:
                               delivered.extend(items) or 0)
    shard = pool.shards[0]
    ev_gone = PacketEvent.create("gone", "gone", "p", hint="g")
    ev_keep = PacketEvent.create("keep", "keep", "p", hint="k")
    shard._enqueue_backhaul([(ev_gone, 1, 0.0, 0.0, 0.0, 0.0, 0.0),
                            (ev_keep, 1, 0.0, 0.0, 0.0, 0.0, 0.0)])
    assert shard._flush_backhaul_once() is True
    assert [i["event"]["entity"] for i in delivered] == ["keep"]
    assert shard.pending_backhaul() == 0  # nothing wedged
    h_keep.shutdown()


def test_shm_ring_full_counter_really_counts(tmp_path):
    from namazu_tpu.endpoint.hub import EndpointHub
    from namazu_tpu.endpoint.uds import UdsEndpoint
    from namazu_tpu.inspector.uds_transceiver import UdsTransceiver
    from namazu_tpu.utils.mock_orchestrator import MockOrchestrator

    path = str(tmp_path / "fullc.sock")
    hub = EndpointHub()
    hub.add_endpoint(UdsEndpoint(path, poll_timeout=2.0))
    mock = MockOrchestrator(hub)
    mock.start()
    tx = UdsTransceiver("e0", path, shm=True, poll_linger=0.005)
    tx.start()
    try:
        class _Full:
            def try_write_frame(self, payload, binary=True):
                return False

            def pending(self):
                return 0

            def close(self):
                pass

        tx._shm_ring = _Full()
        ch = tx.send_event(PacketEvent.create("e0", "e0", "p",
                                              hint="h"))
        assert ch.get(timeout=10) is not None
        assert metrics.registry().value(
            "nmz_shm_ring_full_total", entity="e0") == 1.0
    finally:
        tx.shutdown()
        mock.shutdown()
        hub.shutdown()


def test_parked_burst_actions_carry_event_arrived():
    """Parked burst events must release actions stamped with the
    decision wall time, like every other edge path (the burst loop
    used to skip the arrival stamp)."""
    from namazu_tpu.inspector.edge import EdgeDispatcher
    import queue as _q

    delivered = []
    d = EdgeDispatcher("pa0", deliver=delivered.append,
                       fetch_table=lambda: (0, None),
                       send_backhaul=lambda e, items: 0,
                       backhaul_window=30.0)
    d._table = __import__(
        "namazu_tpu.inspector.edge", fromlist=["EdgeTable"]).EdgeTable(
        {"mode": "delay", "version": 1, "H": 4, "max_interval": 0.02,
         "delays": [0.02, 0.02, 0.02, 0.02]})
    ev = PacketEvent.create("pa0", "pa0", "p", hint="x")
    chan = _q.SimpleQueue()
    assert d.try_dispatch_burst([ev], chan) == []
    deadline = time.monotonic() + 5
    while not delivered and time.monotonic() < deadline:
        time.sleep(0.01)
    assert delivered and delivered[0].event_arrived is not None
    d.shutdown()


def test_factory_edge_shards_one_builds_a_pool(tmp_path):
    """edge_shards=1 means a real single-shard pool (the bench's
    semantics), not a silent fallback to per-entity dispatchers."""
    from namazu_tpu.inspector.edge import ShardedEdge
    from namazu_tpu.inspector.transceiver import new_transceiver

    tx = new_transceiver(f"uds://{tmp_path}/one.sock", "e0",
                         edge=True, edge_shards=1)
    assert isinstance(tx._edge, ShardedEdge)
    assert tx._edge.pool.n_shards == 1
    tx._edge.shutdown()


def test_shm_ring_reset_renegotiates_after_restart_signature(tmp_path):
    """A receive-loop reconnect (server-restart signature) must drop
    the orphan ring and negotiate a fresh one — writes into the dead
    server's mapping would be note_posted but never drained."""
    from namazu_tpu.endpoint.hub import EndpointHub
    from namazu_tpu.endpoint.uds import UdsEndpoint
    from namazu_tpu.inspector.uds_transceiver import UdsTransceiver
    from namazu_tpu.utils.mock_orchestrator import MockOrchestrator

    path = str(tmp_path / "reset.sock")
    hub = EndpointHub()
    hub.add_endpoint(UdsEndpoint(path, poll_timeout=2.0))
    mock = MockOrchestrator(hub)
    mock.start()
    tx = UdsTransceiver("e0", path, shm=True, poll_linger=0.005)
    tx.start()
    try:
        old_ring = tx._shm_ring
        assert old_ring is not None
        tx._reset_shm()
        assert tx._shm_ring is not None
        assert tx._shm_ring is not old_ring
        assert tx._shm_ring.path != old_ring.path
        # and the fresh ring actually carries traffic
        ch = tx.send_event(PacketEvent.create("e0", "e0", "p",
                                              hint="post-reset"))
        assert ch.get(timeout=10) is not None
    finally:
        tx.shutdown()
        mock.shutdown()
        hub.shutdown()
