"""tpu_search policy integration: history -> search -> installed schedule."""

import time

import numpy as np
import pytest

from namazu_tpu.policy import create_policy
from namazu_tpu.signal import EventAcceptanceAction, PacketEvent
from namazu_tpu.storage import new_storage
from namazu_tpu.utils.config import Config
from namazu_tpu.utils.policy_tester import pump_concurrent
from namazu_tpu.utils.trace import SingleTrace


def record_run(storage, entities, successful):
    storage.create_new_working_dir()
    t = SingleTrace()
    now = time.time()
    for i, e in enumerate(entities):
        ev = PacketEvent.create(e, e, "peer", hint=f"{e}:{i % 4}")
        a = ev.default_action()
        a.mark_triggered(now + i * 0.002)
        t.append(a)
    storage.record_new_trace(t)
    from namazu_tpu.signal.base import HINT_SPACE

    # stamp like cli/run_cmd.py does: unstamped runs are treated as
    # pre-flow-prefix recordings and excluded from search ingest
    storage.record_result(successful, 0.5,
                          metadata={"hint_space": HINT_SPACE})


@pytest.fixture
def history(tmp_path):
    st = new_storage("naive", str(tmp_path / "st"))
    st.create()
    record_run(st, ["a", "b", "a", "c", "b", "a"], successful=True)
    record_run(st, ["b", "a", "c", "a", "b", "c"], successful=False)
    return st


def small_cfg(tmp_path, extra=None):
    param = {
        "max_interval": 30,
        "generations": 6,
        "population": 128,
        "hint_buckets": 32,
        "trace_length": 64,
        "feature_pairs": 32,
        "seed": 11,
        "checkpoint": str(tmp_path / "search.npz"),
    }
    param.update(extra or {})
    return Config({"explore_policy_param": param})


def test_search_installs_schedule_from_history(tmp_path, history):
    policy = create_policy("tpu_search")
    policy.load_config(small_cfg(tmp_path))
    policy.set_history_storage(history)
    try:
        policy.start()
        assert policy.wait_for_search(timeout=180)
        assert policy._delays is not None
        assert policy._delays.shape == (32,)
        assert (policy._delays >= 0).all()
        assert (policy._delays <= 0.03 + 1e-6).all()
        # checkpoint written for the next run
        assert (tmp_path / "search.npz").exists()
        # events answered using the searched table
        acts = pump_concurrent(policy, 20, entities=3)
        assert len(acts) == 20
        assert all(isinstance(a, EventAcceptanceAction) for a in acts)
    finally:
        policy.shutdown()


def test_fallback_to_hash_delays_without_history(tmp_path):
    policy = create_policy("tpu_search")
    policy.load_config(small_cfg(tmp_path, {"search_on_start": False}))
    try:
        acts = pump_concurrent(policy, 10, entities=2)
        assert len(acts) == 10
        assert policy._delays is None  # still on the hash fallback
    finally:
        policy.shutdown()


def test_checkpoint_resume_across_policy_instances(tmp_path, history):
    p1 = create_policy("tpu_search")
    p1.load_config(small_cfg(tmp_path))
    p1.set_history_storage(history)
    p1.start()
    assert p1.wait_for_search(timeout=180)
    gen1 = p1._search.generations_run
    p1.shutdown()

    p2 = create_policy("tpu_search")
    p2.load_config(small_cfg(tmp_path))
    p2.set_history_storage(history)
    p2.start()
    assert p2.wait_for_search(timeout=180)
    assert p2._search.generations_run == gen1 + 6  # resumed, not restarted
    p2.shutdown()


def test_delay_lookup_deterministic(tmp_path):
    policy = create_policy("tpu_search")
    policy.load_config(small_cfg(tmp_path, {"search_on_start": False}))
    d1 = policy._delay_for("packet:a->b")
    d2 = policy._delay_for("packet:a->b")
    d3 = policy._delay_for("packet:b->a")
    assert d1 == d2
    assert 0 <= d1 < 0.03
    assert d1 != d3
    policy.shutdown()


def test_reorder_window_zero_rejected(tmp_path):
    """window=0 means 'one global window' to the scorer but a busy-spin
    continuous drain to the control plane — must fail fast."""
    policy = create_policy("tpu_search")
    with pytest.raises(ValueError, match="reorder_window"):
        policy.load_config(small_cfg(tmp_path, {
            "release_mode": "reorder", "reorder_window": 0,
        }))
    # delay mode doesn't care about the window knob
    policy2 = create_policy("tpu_search")
    policy2.load_config(small_cfg(tmp_path, {
        "release_mode": "delay", "reorder_window": 0,
        "search_on_start": False,
    }))


class _RecordingSearch:
    """Stub search backend: records what _ingest_history feeds it."""

    def __init__(self):
        self.executed = []
        self.failures = []
        self.occupied = None

    def set_occupied_buckets(self, occupied):
        self.occupied = list(occupied)

    def add_executed_trace(self, enc, reproduced=False, arrival=None):
        self.executed.append((enc, reproduced))

    def add_failure_trace(self, enc):
        self.failures.append(enc)


def _policy_with_storage(storage):
    pol = create_policy("tpu_search")
    pol.load_config(Config({"explore_policy_param": {
        "search_on_start": False, "hint_buckets": 32,
        "reference_mode": "recent",
    }}))
    pol.set_history_storage(storage)
    return pol


def test_ingest_history_refs_are_successes_only(tmp_path):
    """References for the counterfactual are SUCCESS traces whenever any
    exist — a failure trace's arrivals already contain the bug-inducing
    delays, so scoring against it lets a no-op genome match the failure
    signature (advisor finding, round 2)."""
    st = new_storage("naive", str(tmp_path / "st"))
    st.create()
    record_run(st, ["a", "b", "a"], successful=True)
    record_run(st, ["b", "a", "c"], successful=False)
    record_run(st, ["c", "b", "a"], successful=True)
    record_run(st, ["a", "c", "b"], successful=False)
    record_run(st, ["b", "c", "a"], successful=False)
    pol = _policy_with_storage(st)
    search = _RecordingSearch()
    refs = pol._ingest_history(search)
    # 2 successes exist -> refs are exactly those (latest first), never
    # padded with failures
    assert len(refs) == 2
    # all five runs still feed the archives
    assert len(search.executed) == 5
    assert len(search.failures) == 3


def test_ingest_history_refs_fall_back_to_failures(tmp_path):
    st = new_storage("naive", str(tmp_path / "st"))
    st.create()
    record_run(st, ["a", "b", "a"], successful=False)
    record_run(st, ["b", "a", "c"], successful=False)
    pol = _policy_with_storage(st)
    search = _RecordingSearch()
    refs = pol._ingest_history(search)
    assert len(refs) == 2  # no success yet: failures anchor the search
